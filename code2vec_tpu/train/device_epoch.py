"""Device-resident epochs: HBM-staged corpus, on-device sampling, scanned steps.

The host pipeline (data/pipeline.py) rebuilds `[N, L]` epoch tensors in numpy
and ships one `[B, L]` batch per step to the device. That reproduces the
reference's data flow (model/dataset_builder.py:112-210 + DataLoader,
main.py:162-172), but on TPU the per-step host->device transfer is pure
overhead: the *corpus* is static across epochs, and the per-epoch work —
context subsampling, `@method_0 -> @question` substitution, batch assembly —
is all gather/where arithmetic the TPU does in microseconds.

So this module moves the whole epoch on-device:

- ``stage_method_corpus``: one-time transfer of the CSR context arrays
  (interleaved ``[total, 3]`` so each batch slot is a single 12-byte row
  gather), with the ``@question`` substitution (model/dataset_builder.py:
  122-144) pre-applied and each method's contexts pre-shuffled host-side.
- ``make_epoch_runner``: jitted ``lax.scan`` over whole chunks of batches.
  Each scan iteration samples a fresh context window per method and runs the
  *same* raw train step the per-batch path uses (train/step.py) — one
  dispatch per ~16 batches instead of one transfer + dispatch per batch.
  Per-epoch traffic is a `[N]` int32 permutation and a PRNG key.

Sampling semantics vs the reference: the reference shuffles each method's
context list every epoch and keeps the first L (model/dataset_builder.py:
134-135) — a uniform sample without replacement. Here each method's contexts
are shuffled once at staging, and each epoch takes a random *rotation window*
of length L: ``ctx[(shift + j) % n]``. For methods with ``n <= L`` (the
common case) both schemes take every context, and attention pooling is
permutation-invariant, so they are equivalent. For ``n > L`` the window keeps
uniform per-context inclusion probability ``L/n`` without duplicates, but
adjacent (post-shuffle) contexts co-occur; the host pipeline remains the
exact-parity path. Re-staging (with a different shuffle seed) redraws the
within-method order.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX
from code2vec_tpu.data.pipeline import flat_context_indices
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.models.code2vec import Code2VecConfig
from code2vec_tpu.train.step import (
    build_eval_step_fn,
    build_train_step_fn,
    contract_step,
)


@dataclass
class StagedCorpus:
    """Device-resident corpus (CSR, interleaved contexts). Rows are training
    EXAMPLES: one per method (method task, ``stage_method_corpus``) and/or
    one per ``@var_*`` alias (variable task, ``stage_variable_corpus`` —
    the expansion is corpus-static, so it happens once at staging)."""

    contexts: jax.Array  # int32 [total, 3] — (start, path, end), @question applied
    row_splits: jax.Array  # int32 [n_items + 1]
    labels: jax.Array  # int32 [n_items]
    n_items: int
    # variable-task remap support (None/absent for pure method corpora):
    # the per-epoch @var-index shuffle (model/dataset_builder.py:155-195)
    # runs on device as a per-row permutation over these ids, applied only
    # to rows flagged as variable examples
    remap_ids: jax.Array | None = None  # int32 [V] sorted @var terminal ids
    remap_flags: jax.Array | None = None  # int32 [n_items] 1 = variable row

    @property
    def n_contexts(self) -> int:
        return int(self.contexts.shape[0])


def _check_device_total(total: int) -> None:
    """Device row_splits are int32; enforced at every whole-corpus device
    boundary (direct staging and place_staged). shard_staged's limit is
    per-SHARD instead — java-large's ~2.3G contexts exceed this whole-
    corpus limit and stage fine sharded."""
    if total >= 2**31:
        raise ValueError(
            f"staged corpus has {total} contexts; device row_splits are "
            "int32 — use --shard_staged_corpus (per-shard limit) or stage "
            "a subset / shard over hosts"
        )


def _per_row_shuffle(
    total: int, row_splits: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """A permutation of [0, total) that shuffles within each CSR row only.

    Vectorized: sort (row_id, uniform) pairs — stable layout per row, random
    order within. O(total log total) once at staging. Keys are kept narrow
    (int32 row ids, f32 uniforms) — at java-large scale (2.3G contexts)
    every byte per element is gigabytes of staging transients; an f32
    collision within a row falls back to stable order, a negligible bias
    at realistic bag sizes.
    """
    row_ids = np.repeat(
        np.arange(len(row_splits) - 1, dtype=np.int32), np.diff(row_splits)
    )
    return np.lexsort((rng.random(total, dtype=np.float32), row_ids))


def stage_method_corpus(
    data: CorpusData,
    item_idx: np.ndarray,
    rng: np.random.Generator,
    device: Any | None = None,
) -> StagedCorpus:
    """Stage the selected items' contexts into device memory.

    ``item_idx`` is the train (or test) split; only those rows are shipped.
    The method's own anonymized token is replaced by ``@question`` here, once,
    instead of per epoch (same global substitution the host pipeline applies,
    model/dataset_builder.py:122-144 — ``@method_0`` is a single vocab id).
    """
    counts = np.diff(data.row_splits)[item_idx]
    new_splits = np.zeros(len(item_idx) + 1, np.int64)
    np.cumsum(counts, out=new_splits[1:])
    total = int(new_splits[-1])
    if device != "host":
        # a host-staged intermediate keeps int64 splits; place_staged /
        # shard_staged enforce the device-side limits downstream
        _check_device_total(total)

    # flat indices of every context of every selected item, in item order;
    # the per-row shuffle is applied to the INDICES before the gather (one
    # [total, 3] pass instead of gather-then-permute — at java-large scale
    # that second copy is ~27 GB of transient)
    flat, _, _ = flat_context_indices(
        data.row_splits, item_idx, row_base=data.row_base
    )
    perm = _per_row_shuffle(total, new_splits, rng)
    flat = flat[perm]
    del perm

    contexts = np.empty((total, 3), np.int32)
    contexts[:, 0] = data.starts[flat]
    contexts[:, 1] = data.paths[flat]
    contexts[:, 2] = data.ends[flat]
    del flat

    method_idx = data.method_token_index
    if method_idx is not None:
        terms = contexts[:, (0, 2)]
        np.putmask(terms, terms == method_idx, QUESTION_TOKEN_INDEX)
        contexts[:, (0, 2)] = terms

    put = _putter(device)
    splits_dtype = np.int64 if device == "host" else np.int32
    return StagedCorpus(
        contexts=put(contexts),
        row_splits=put(new_splits.astype(splits_dtype)),
        labels=put(data.labels[item_idx].astype(np.int32)),
        n_items=len(item_idx),
    )


def _putter(device):
    """device="host" keeps numpy arrays (for concat_staged before a single
    place_staged transfer); anything else is a jax.device_put target."""
    if device == "host":
        return lambda x: x
    return partial(jax.device_put, device=device)


def stage_variable_corpus(
    data: CorpusData,
    item_idx: np.ndarray,
    rng: np.random.Generator,
    device: Any | None = None,
) -> StagedCorpus:
    """Stage the variable task: one row per ``@var_*`` alias of each item.

    Mirrors ``build_variable_epoch`` (model/dataset_builder.py:152-204):
    keep contexts touching the target variable, rename the target to
    ``@question`` (static per row, pre-applied here), shuffle once. The
    per-epoch index REMAP (shuffle_variable_indexes) cannot be pre-applied —
    it redraws each epoch — so the staged corpus carries ``remap_ids`` /
    ``remap_flags`` and the sampler permutes on device.
    """
    from code2vec_tpu.data.pipeline import variable_items

    label_stoi = data.label_vocab.stoi
    parts: list[np.ndarray] = []
    counts: list[int] = []
    labels: list[int] = []
    for i, alias_names, alias_idx, s, p, e in variable_items(data, item_idx):
        alias_map = data.aliases[i]
        for alias_name, var_idx in zip(alias_names, alias_idx):
            mine = (s == var_idx) | (e == var_idx)
            row = np.stack(
                [
                    np.where(s[mine] == var_idx, QUESTION_TOKEN_INDEX, s[mine]),
                    p[mine],
                    np.where(e[mine] == var_idx, QUESTION_TOKEN_INDEX, e[mine]),
                ],
                axis=1,
            ).astype(np.int32)
            parts.append(row[rng.permutation(len(row))])
            counts.append(len(row))
            labels.append(label_stoi[alias_map[alias_name]])

    contexts = (
        np.concatenate(parts) if parts else np.zeros((0, 3), np.int32)
    )
    row_splits = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=row_splits[1:])
    if device != "host" and int(row_splits[-1]) >= 2**31:
        # host-staged intermediates keep int64 splits (see
        # stage_method_corpus); the device cast enforces the int32 limit
        raise ValueError("staged variable corpus exceeds int32 row_splits")

    put = _putter(device)
    splits_dtype = np.int64 if device == "host" else np.int32
    return StagedCorpus(
        contexts=put(contexts),
        row_splits=put(row_splits.astype(splits_dtype)),
        labels=put(np.asarray(labels, np.int32)),
        n_items=len(labels),
        remap_ids=put(data.variable_indexes.astype(np.int32)),
        remap_flags=put(np.ones(len(labels), np.int32)),
    )


def concat_staged(a: StagedCorpus, b: StagedCorpus) -> StagedCorpus:
    """Method rows + variable rows in one staged corpus (the combined-task
    epoch, build_epoch's concatenation order). Host-side numpy concat; call
    before device_put-ing (stage with device="host", then place_staged)."""
    a_ctx, b_ctx = np.asarray(a.contexts), np.asarray(b.contexts)
    a_rs, b_rs = np.asarray(a.row_splits), np.asarray(b.row_splits)
    # int64 math: the host intermediate carries int64 splits (the combined
    # total may exceed int32 yet still shard fine); place_staged /
    # shard_staged enforce the device-side limits
    row_splits = np.concatenate(
        [a_rs.astype(np.int64), b_rs[1:].astype(np.int64) + int(a_rs[-1])]
    )
    flags_a = (
        np.asarray(a.remap_flags)
        if a.remap_flags is not None
        else np.zeros(a.n_items, np.int32)
    )
    flags_b = (
        np.asarray(b.remap_flags)
        if b.remap_flags is not None
        else np.zeros(b.n_items, np.int32)
    )
    remap_ids = a.remap_ids if a.remap_ids is not None else b.remap_ids
    return StagedCorpus(
        contexts=np.concatenate([a_ctx, b_ctx]),
        row_splits=row_splits,
        labels=np.concatenate([np.asarray(a.labels), np.asarray(b.labels)]),
        n_items=a.n_items + b.n_items,
        remap_ids=remap_ids,
        remap_flags=np.concatenate([flags_a, flags_b]),
    )


def place_staged(staged: StagedCorpus, device: Any | None = None) -> StagedCorpus:
    """Move a host staging onto a device (or mesh placement). The device
    sampler indexes with int32 ``row_splits``; a host staging past the
    int32 total must go through ``shard_staged`` instead (per-SHARD limit)."""
    rs = np.asarray(staged.row_splits)
    _check_device_total(int(rs[-1]) if len(rs) else 0)
    put = partial(jax.device_put, device=device)
    return StagedCorpus(
        contexts=put(staged.contexts),
        row_splits=put(rs.astype(np.int32)),
        labels=put(staged.labels),
        n_items=staged.n_items,
        remap_ids=None if staged.remap_ids is None else put(staged.remap_ids),
        remap_flags=(
            None if staged.remap_flags is None else put(staged.remap_flags)
        ),
    )


@dataclass
class ShardedStagedCorpus:
    """Train corpus partitioned over the ``data`` mesh axis: device HBM per
    shard is ~1/D of the replicated staging, the designed scaling path for
    corpora that don't fit one device (ARCHITECTURE.md "memory budget").

    Each data shard holds its own CSR block, padded to the uniform
    ``[D, ctx_cap, 3]`` / ``[D, items_cap(+1)]`` shapes GSPMD needs; the
    sampler runs under ``shard_map`` so every device gathers only from its
    local block — sampling adds no cross-device traffic. Batches come out
    stratified-by-shard (each shard contributes ``B/D`` rows), the same
    DDP semantics as host-sharded multi-host feeding.
    """

    contexts: jax.Array  # int32 [D, ctx_cap, 3], sharded P("data") on axis 0
    row_splits: jax.Array  # int32 [D, items_cap + 1]
    labels: jax.Array  # int32 [D, items_cap]
    n_items: int  # total real items across shards
    shard_counts: np.ndarray  # int64 [D] real items per shard (host)
    items_cap: int  # padded per-shard row count
    total_contexts: int  # real (unpadded) context count across shards
    # variable-task remap (see StagedCorpus): ids replicated, flags sharded
    remap_ids: jax.Array | None = None  # int32 [V]
    remap_flags: jax.Array | None = None  # int32 [D, items_cap]

    @property
    def n_contexts(self) -> int:
        return self.total_contexts

    def flat_labels(self) -> np.ndarray:
        """Valid labels in shard-concatenation order — the ``expected``
        array matching ``ShardedEpochRunner.run_eval_epoch``'s preds."""
        from code2vec_tpu.parallel.distributed import allgather_to_host

        lab = allgather_to_host(self.labels)
        return np.concatenate(
            [lab[s, : int(c)] for s, c in enumerate(self.shard_counts)]
        )


def partition_items_balanced(
    counts: np.ndarray, n_shards: int
) -> list[np.ndarray]:
    """Deal item positions to shards in a snake over descending context
    counts. Two balance criteria matter and this hits both: per-shard ITEM
    counts are equal ±1 (the largest shard's item count sets the epoch's
    step count — an item-imbalanced partition would pad every other shard
    with masked batches), and per-shard CONTEXT loads stay close (the
    uniform ``ctx_cap`` padding cost). Vectorized O(n log n)."""
    n = len(counts)
    order = np.argsort(-np.asarray(counts), kind="stable")
    pos_in_round = np.arange(n) % (2 * n_shards)
    shard = np.where(
        pos_in_round < n_shards, pos_in_round, 2 * n_shards - 1 - pos_in_round
    )
    return [np.sort(order[shard == s]).astype(np.int64) for s in range(n_shards)]


def _check_shard_ctx_cap(ctx_cap: int, n_shards: int) -> None:
    """Per-SHARD row_splits are int32 — the total may exceed 2^31 (the
    point of sharding: java-large's ~2.3G contexts at data_axis >= 2
    stays well under per shard), but one shard may not."""
    if ctx_cap >= 2**31:
        raise ValueError(
            f"largest shard holds {ctx_cap} contexts (int32 row_splits); "
            f"increase data_axis beyond {n_shards}"
        )


def _csr_blocks(
    groups: list[np.ndarray],
    counts: np.ndarray,
    rs_all: np.ndarray,
    ctx_all: np.ndarray,
    labels_all: np.ndarray,
    flags_all: np.ndarray | None,
    items_cap: int,
    ctx_cap: int,
):
    """Fill the uniform per-shard CSR blocks for one set of item groups
    (shared by the single-host and multi-process sharded stagings, so the
    padding rules can't diverge)."""
    n = len(groups)
    contexts = np.zeros((n, ctx_cap, 3), np.int32)
    row_splits = np.zeros((n, items_cap + 1), np.int32)
    labels = np.zeros((n, items_cap), np.int32)
    flags = np.zeros((n, items_cap), np.int32)
    for s, g in enumerate(groups):
        flat, _, _ = flat_context_indices(rs_all, g)
        block = ctx_all[flat]
        contexts[s, : block.shape[0]] = block
        splits = np.zeros(len(g) + 1, np.int64)
        np.cumsum(counts[g], out=splits[1:])
        row_splits[s, : len(splits)] = splits
        row_splits[s, len(splits):] = splits[-1]  # pad rows are empty
        labels[s, : len(g)] = labels_all[g]
        if flags_all is not None:
            flags[s, : len(g)] = flags_all[g]
    return contexts, row_splits, labels, flags


def shard_staged(staged: StagedCorpus, mesh) -> ShardedStagedCorpus:
    """Partition a HOST-staged corpus (method, variable, or concat — any
    :class:`StagedCorpus` still holding numpy arrays, i.e. staged with
    ``device="host"``) over the mesh's ``data`` axis: snake-dealt row
    partition, per-shard CSR blocks padded to uniform shapes, placed with
    ``P("data")`` shardings (remap ids replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_shards = mesh.shape["data"]
    ctx_all = np.asarray(staged.contexts)
    rs_all = np.asarray(staged.row_splits).astype(np.int64)
    labels_all = np.asarray(staged.labels)
    flags_all = (
        None if staged.remap_flags is None else np.asarray(staged.remap_flags)
    )
    counts = np.diff(rs_all)
    groups = partition_items_balanced(counts, n_shards)

    items_cap = max((len(g) for g in groups), default=1)
    ctx_cap = max((int(counts[g].sum()) for g in groups), default=1)
    items_cap, ctx_cap = max(items_cap, 1), max(ctx_cap, 1)
    _check_shard_ctx_cap(ctx_cap, n_shards)

    contexts, row_splits, labels, flags = _csr_blocks(
        groups, counts, rs_all, ctx_all, labels_all, flags_all,
        items_cap, ctx_cap,
    )

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    has_remap = staged.remap_ids is not None and len(
        np.asarray(staged.remap_ids)
    ) > 0
    return ShardedStagedCorpus(
        contexts=put(contexts, P("data", None, None)),
        row_splits=put(row_splits, P("data", None)),
        labels=put(labels, P("data", None)),
        n_items=staged.n_items,
        shard_counts=np.asarray([len(g) for g in groups], np.int64),
        items_cap=items_cap,
        total_contexts=int(counts.sum()),
        remap_ids=(
            put(np.asarray(staged.remap_ids, np.int32), P())
            if has_remap else None
        ),
        remap_flags=put(flags, P("data", None)) if has_remap else None,
    )


def stage_method_corpus_sharded(
    data: CorpusData,
    item_idx: np.ndarray,
    rng: np.random.Generator,
    mesh,
) -> ShardedStagedCorpus:
    """Method-task convenience wrapper: host staging + :func:`shard_staged`."""
    return shard_staged(
        stage_method_corpus(data, item_idx, rng, device="host"), mesh
    )


def shard_staged_multiprocess(
    staged_local: StagedCorpus, mesh
) -> ShardedStagedCorpus:
    """Pod-scale sharded staging (SURVEY §5.8 + §7.4 composed): each FEED
    GROUP stages only its own host-sharded corpus shard and partitions it
    over the group's OWN data-axis coords; the global ``[D, ...]`` arrays
    are assembled from process-local blocks with
    ``jax.make_array_from_process_local_data`` — no host ever materializes
    the full corpus (the point of sharded staging at java-large scale).

    ``staged_local`` must be host-staged (``device="host"``) from the
    items of THIS process's feed-group shard
    (``load_corpus(shard=feed_groups(mesh))``), with the same seed across
    the group's member processes — replicas of the same data coords must
    contribute identical blocks. Method task only, like host-sharded
    feeding (the variable expansion is data-dependent per shard).

    Single-process meshes delegate to :func:`shard_staged` (identical
    semantics, no collective needed).
    """
    import jax as _jax

    if _jax.process_count() == 1:
        return shard_staged(staged_local, mesh)
    if staged_local.remap_ids is not None and len(
        np.asarray(staged_local.remap_ids)
    ):
        raise ValueError(
            "multi-process sharded staging supports the method task only; "
            "stage the variable task replicated or use the host pipeline"
        )
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from code2vec_tpu.parallel.distributed import feed_groups

    group, n_groups = feed_groups(mesh)
    n_shards = int(mesh.shape["data"])
    if n_shards % n_groups:
        raise ValueError(
            f"data axis {n_shards} not divisible by {n_groups} feed groups"
        )
    local_d = n_shards // n_groups
    # feed_groups guarantees contiguous, equal, ascending coord ranges, so
    # group g owns data coords [g*local_d, (g+1)*local_d)
    ctx_all = np.asarray(staged_local.contexts)
    rs_all = np.asarray(staged_local.row_splits).astype(np.int64)
    labels_all = np.asarray(staged_local.labels)
    counts = np.diff(rs_all)
    groups_local = partition_items_balanced(counts, local_d)

    # one allgather settles everything cross-process: the global caps
    # (uniform block shapes are a GLOBAL property), every coord's
    # item/context counts (each process contributes its group's coords,
    # zeros elsewhere), and the contributor's feed-group id. Packed so
    # staging costs a single host barrier.
    local_items_cap = max((len(g) for g in groups_local), default=1)
    local_ctx_cap = max((int(counts[g].sum()) for g in groups_local), default=1)
    contrib = np.zeros(n_shards, np.int64)
    contrib[group * local_d : (group + 1) * local_d] = [
        len(g) for g in groups_local
    ]
    ctx_contrib = np.zeros(n_shards, np.int64)
    ctx_contrib[group * local_d : (group + 1) * local_d] = [
        int(counts[g].sum()) for g in groups_local
    ]
    gathered = multihost_utils.process_allgather(np.concatenate([
        np.asarray([local_items_cap, local_ctx_cap, group], np.int64),
        contrib, ctx_contrib,
    ]))  # [n_processes, 3 + 2 * n_shards]
    items_cap = max(int(gathered[:, 0].max()), 1)
    ctx_cap = max(int(gathered[:, 1].max()), 1)
    _check_shard_ctx_cap(ctx_cap, n_shards)
    proc_groups = gathered[:, 2]
    all_counts = gathered[:, 3 : 3 + n_shards]
    all_ctx = gathered[:, 3 + n_shards :]
    # replica processes of the same feed group MUST have contributed
    # identical count vectors — a mismatch means divergent staging (e.g. an
    # rng seeded by process instead of by group), which would assemble
    # silently wrong shards. Exact per-group equality, NOT a nonzero
    # heuristic: a replica staging zero items/contexts for a coord its
    # group owns while a peer stages >0 is precisely the divergence this
    # guard exists to catch.
    for g in np.unique(proc_groups):
        members = np.flatnonzero(proc_groups == g)
        for name, arr in (("item", all_counts), ("context", all_ctx)):
            if not (arr[members] == arr[members[0]][None, :]).all():
                raise ValueError(
                    f"feed-group {int(g)} replicas disagree on per-shard "
                    f"{name} counts ({arr[members].tolist()}); group "
                    "members must stage the SAME shard with the SAME seed "
                    "(seed the staging rng by feed group, not by process)"
                )
    shard_counts = all_counts.max(axis=0)
    total_contexts = int(all_ctx.max(axis=0).sum())

    contexts, row_splits, labels, _ = _csr_blocks(
        groups_local, counts, rs_all, ctx_all, labels_all, None,
        items_cap, ctx_cap,
    )

    def put(x, spec):
        return _jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), x
        )

    return ShardedStagedCorpus(
        contexts=put(contexts, P("data", None, None)),
        row_splits=put(row_splits, P("data", None)),
        labels=put(labels, P("data", None)),
        n_items=int(shard_counts.sum()),
        shard_counts=shard_counts,
        items_cap=items_cap,
        total_contexts=total_contexts,
    )


def _sample_batch(
    corpus_contexts: jax.Array,  # [total, 3]
    row_splits: jax.Array,  # [n_items + 1]
    labels: jax.Array,  # [n_items]
    rows: jax.Array,  # int32 [B] item indices (may repeat for padding)
    row_valid: jax.Array,  # f32 [B] example mask
    bag: int,
    key: jax.Array,
    remap_ids: jax.Array | None = None,  # int32 [V] sorted; [0] = remap off
    remap_flags: jax.Array | None = None,  # int32 [n_items]
) -> dict[str, jax.Array]:
    """Assemble one [B, bag] batch on device: rotation-window subsample,
    plus (variable task, shuffle_variable_indexes) a per-row random
    permutation of the ``@var_*`` terminal ids — the on-device equivalent
    of the host remap (model/dataset_builder.py:155-195; drawn per example
    rather than per method, same marginal distribution)."""
    batch_size = rows.shape[0]
    off = row_splits[rows]  # [B]
    n = row_splits[rows + 1] - off  # [B]
    n_safe = jnp.maximum(n, 1)[:, None]  # [B, 1]

    shift = jax.random.randint(key, (batch_size, 1), 0, 1 << 30)
    j = jnp.arange(bag, dtype=jnp.int32)[None, :]  # [1, bag]
    idx = (j + shift % n_safe) % n_safe  # [B, bag]
    valid = j < jnp.minimum(n, bag)[:, None]  # [B, bag]

    trip = corpus_contexts[jnp.where(valid, off[:, None] + idx, 0)]  # [B, bag, 3]
    pad = jnp.int32(PAD_INDEX)
    starts = jnp.where(valid, trip[..., 0], pad)
    ends = jnp.where(valid, trip[..., 2], pad)

    n_var = 0 if remap_ids is None else remap_ids.shape[0]
    if n_var > 0:  # static: traced only for corpora that carry remap ids
        u = jax.random.uniform(jax.random.fold_in(key, 1), (batch_size, n_var))
        mapped = remap_ids[jnp.argsort(u, axis=1)]  # [B, V] id -> permuted id
        apply_row = (remap_flags[rows] > 0)[:, None]  # variable rows only

        def remap(t: jax.Array) -> jax.Array:
            pos = jnp.clip(jnp.searchsorted(remap_ids, t), 0, n_var - 1)
            is_var = remap_ids[pos] == t
            permuted = jnp.take_along_axis(mapped, pos, axis=1)
            return jnp.where(is_var & apply_row, permuted, t)

        starts, ends = remap(starts), remap(ends)

    return {
        "starts": starts,
        "paths": jnp.where(valid, trip[..., 1], pad),
        "ends": ends,
        "labels": labels[rows],
        "example_mask": row_valid,
    }


def _scan_train_chunk(sample_i, raw_train, state, key, n_batches,
                      prefetch: bool):
    """The chunk's scan-over-batches, shared by the replicated and sharded
    runners (their ``sample_i`` closures differ, the control flow must not).

    ``prefetch=False``: sample then step, one batch per iteration.

    ``prefetch=True`` double-buffers: iteration i trains on the batch
    sampled during iteration i-1 while sampling batch i+1 — the two are
    data-independent, so the TPU scheduler can overlap the sampling
    gathers with the step's compute. The key split SEQUENCE is unchanged
    (batch 0 consumes split 1, the i=0 body's prefetch split 2, ...), so
    every sampled batch is bit-identical to the unprefetched path
    (tested); losses match up to float reassociation between the two
    compiled programs. The one dummy tail sample (clamped to the last
    block) is discarded.
    """
    if not prefetch:
        def body(carry, i):
            state, key = carry
            key, batch = sample_i(key, i)
            state, loss = raw_train(state, batch)
            return (state, key), loss

        (state, _), losses = jax.lax.scan(
            body, (state, key), jnp.arange(n_batches)
        )
        return state, jnp.sum(losses)

    def body(carry, i):
        state, key, batch = carry
        key, next_batch = sample_i(key, jnp.minimum(i + 1, n_batches - 1))
        state, loss = raw_train(state, batch)
        return (state, key, next_batch), loss

    key, batch0 = sample_i(key, jnp.int32(0))
    (state, _, _), losses = jax.lax.scan(
        body, (state, key, batch0), jnp.arange(n_batches)
    )
    return state, jnp.sum(losses)


def _scan_eval_chunk(sample_i, eval_body, key, n_batches, prefetch: bool):
    """Eval counterpart of :func:`_scan_train_chunk`: same key-walk
    identity, same double-buffering; ``eval_body(batch)`` returns the
    per-batch output tuple the scan stacks."""
    if not prefetch:
        def body(key, i):
            key, batch = sample_i(key, i)
            return key, eval_body(batch)

        _, outs = jax.lax.scan(body, key, jnp.arange(n_batches))
        return outs

    def body(carry, i):
        key, batch = carry
        key, next_batch = sample_i(key, jnp.minimum(i + 1, n_batches - 1))
        return (key, next_batch), eval_body(batch)

    key, batch0 = sample_i(key, jnp.int32(0))
    _, outs = jax.lax.scan(body, (key, batch0), jnp.arange(n_batches))
    return outs


class EpochRunner:
    """Scanned on-device train/eval epochs over a :class:`StagedCorpus`.

    One jitted program per (chunk length) — the full chunk plus one tail
    shape per distinct epoch size; split sizes are fixed for a run, so in
    practice two compilations each for train and eval.

    With ``mesh`` set, the fast path scales out: the staged corpus is
    replicated over the mesh (stage with ``device=NamedSharding(mesh, P())``),
    each scanned batch is sharding-constrained to the usual batch layout
    (batch dim over ``data``, bag dim over ``ctx`` — parallel.shardings), and
    the step runs SPMD with XLA inserting the gradient all-reduce. Each
    device gathers only its shard's rows from its local corpus copy, so the
    sampling adds no cross-device traffic. Corpus HBM cost is per-device
    (replication): top11 scale is ~0.9 GB; for corpora that don't fit,
    stream epochs from host instead (data.pipeline).
    """

    def __init__(
        self,
        model_config: Code2VecConfig,
        class_weights: jnp.ndarray,
        batch_size: int,
        bag: int,
        chunk_batches: int = 16,
        mesh=None,
        shuffle_variable_ids: bool = False,
        sample_prefetch: bool = False,
        table_update: str = "dense",
    ):
        self.batch_size = batch_size
        self.bag = bag
        self.chunk_batches = chunk_batches
        self.mesh = mesh
        self.shuffle_variable_ids = shuffle_variable_ids
        self.sample_prefetch = sample_prefetch
        if mesh is not None:
            from code2vec_tpu.parallel.shardings import cached_batch_shardings

            # shape-free, mesh-keyed: every bucket width's runner reuses
            # the same NamedSharding dict
            self._batch_shardings = cached_batch_shardings(mesh)
        # contract-checked once per chunk trace (the scan body traces once
        # per chunk shape) — the on-device sampler's batches obey the same
        # [B, bag] contract as host batches, so a sampler regression fails
        # at trace time, not as a recompile storm
        self._raw_train = contract_step(build_train_step_fn(
            model_config, class_weights, table_update
        ))
        self._raw_eval = contract_step(
            build_eval_step_fn(model_config, class_weights)
        )
        self._train_chunks: dict[int, Callable] = {}
        self._eval_chunks: dict[int, Callable] = {}

    def _remap_args(self, corpus: StagedCorpus) -> tuple[jax.Array, jax.Array]:
        """(remap_ids, remap_flags) for the chunk call — empty ids disable
        the remap at trace time (shape-static), so method-task corpora and
        no-shuffle runs compile the plain sampler."""
        if (
            not self.shuffle_variable_ids
            or corpus.remap_ids is None
            or int(corpus.remap_ids.shape[0]) == 0
        ):
            return (
                jnp.zeros(0, jnp.int32),
                jnp.zeros(max(corpus.n_items, 1), jnp.int32),
            )
        return corpus.remap_ids, corpus.remap_flags

    def _constrain(self, batch: dict[str, jax.Array]) -> dict[str, jax.Array]:
        if self.mesh is None:
            return batch
        return {
            k: jax.lax.with_sharding_constraint(v, self._batch_shardings[k])
            for k, v in batch.items()
        }

    # -- jitted chunk programs -------------------------------------------

    def _train_chunk(self, n_batches: int) -> Callable:
        if n_batches not in self._train_chunks:
            batch_size, bag = self.batch_size, self.bag

            @partial(jax.jit, donate_argnums=(0,), static_argnums=(5,))
            def run(state, contexts, row_splits, labels, perm_rows, n_valid,
                    key, remap_ids=None, remap_flags=None):
                perm_valid = (
                    jnp.arange(n_batches * batch_size) < n_valid
                ).astype(jnp.float32)

                def sample_i(key, i):
                    key, sample_key = jax.random.split(key)
                    sl = lambda a: jax.lax.dynamic_slice_in_dim(
                        a, i * batch_size, batch_size, 0
                    )
                    batch = self._constrain(_sample_batch(
                        contexts, row_splits, labels,
                        sl(perm_rows), sl(perm_valid), bag, sample_key,
                        remap_ids, remap_flags,
                    ))
                    return key, batch

                return _scan_train_chunk(
                    sample_i, self._raw_train, state, key, n_batches,
                    self.sample_prefetch,
                )

            self._train_chunks[n_batches] = run
        return self._train_chunks[n_batches]

    def _eval_chunk(self, n_batches: int) -> Callable:
        if n_batches not in self._eval_chunks:
            batch_size, bag = self.batch_size, self.bag

            @partial(jax.jit, static_argnums=(5,))
            def run(state, contexts, row_splits, labels, perm_rows, n_valid,
                    key, remap_ids=None, remap_flags=None):
                perm_valid = (
                    jnp.arange(n_batches * batch_size) < n_valid
                ).astype(jnp.float32)

                def sample_i(key, i):
                    key, sample_key = jax.random.split(key)
                    sl = lambda a: jax.lax.dynamic_slice_in_dim(
                        a, i * batch_size, batch_size, 0
                    )
                    batch = self._constrain(_sample_batch(
                        contexts, row_splits, labels,
                        sl(perm_rows), sl(perm_valid), bag, sample_key,
                        remap_ids, remap_flags,
                    ))
                    return key, batch

                def eval_body(batch):
                    out = self._raw_eval(state, batch)
                    return out["loss"], out["preds"], out["max_logit"]

                losses, preds, max_logits = _scan_eval_chunk(
                    sample_i, eval_body, key, n_batches, self.sample_prefetch
                )
                return jnp.sum(losses), preds.reshape(-1), max_logits.reshape(-1)

            self._eval_chunks[n_batches] = run
        return self._eval_chunks[n_batches]

    # -- host-facing epoch drivers ---------------------------------------

    def _chunk_plan(self, n_rows: int) -> list[tuple[int, int, int]]:
        """[(row_lo, n_batches, n_valid_rows)] covering ceil(n/B) batches."""
        n_batches_total = -(-n_rows // self.batch_size)
        plan = []
        lo = 0
        while lo < n_batches_total:
            nb = min(self.chunk_batches, n_batches_total - lo)
            row_lo = lo * self.batch_size
            n_valid = min(n_rows - row_lo, nb * self.batch_size)
            plan.append((row_lo, nb, n_valid))
            lo += nb
        return plan

    def _padded_rows(self, order: np.ndarray, row_lo: int, nb: int) -> np.ndarray:
        rows = order[row_lo : row_lo + nb * self.batch_size]
        if len(rows) < nb * self.batch_size:
            # repeat row 0 for the masked tail (same as iter_batches padding)
            fill = np.full(nb * self.batch_size - len(rows), order[0], rows.dtype)
            rows = np.concatenate([rows, fill])
        return rows.astype(np.int32)

    def run_train_epoch(
        self,
        state,
        corpus: StagedCorpus,
        rng: np.random.Generator,
        key: jax.Array,
    ) -> tuple[Any, float, int]:
        """One training epoch; returns (state, summed loss, n_batches).

        ``rng`` draws the epoch's method order on host (matching the host
        loop's seeded shuffle); ``key`` drives on-device context sampling.
        """
        order = rng.permutation(corpus.n_items)
        remap_ids, remap_flags = self._remap_args(corpus)
        chunk_losses = []  # device scalars; summed after the last dispatch
        n_batches = 0
        for row_lo, nb, n_valid in self._chunk_plan(corpus.n_items):
            key, chunk_key = jax.random.split(key)
            state, loss = self._train_chunk(nb)(
                state, corpus.contexts, corpus.row_splits, corpus.labels,
                self._padded_rows(order, row_lo, nb), n_valid, chunk_key,
                remap_ids, remap_flags,
            )
            chunk_losses.append(loss)
            n_batches += nb
        return state, float(np.sum(jax.device_get(chunk_losses))), n_batches

    # (ShardedEpochRunner below handles the data-axis-sharded staging)

    def run_eval_epoch(
        self,
        state,
        corpus: StagedCorpus,
        key: jax.Array,
    ) -> tuple[float, np.ndarray, np.ndarray]:
        """One eval pass in corpus order; returns (summed per-batch mean
        loss, preds [n_items], max_logits [n_items])."""
        order = np.arange(corpus.n_items)
        remap_ids, remap_flags = self._remap_args(corpus)
        total_loss = 0.0
        preds: list[np.ndarray] = []
        max_logits: list[np.ndarray] = []
        for row_lo, nb, n_valid in self._chunk_plan(corpus.n_items):
            key, chunk_key = jax.random.split(key)
            loss, p, m = self._eval_chunk(nb)(
                state, corpus.contexts, corpus.row_splits, corpus.labels,
                self._padded_rows(order, row_lo, nb), n_valid, chunk_key,
                remap_ids, remap_flags,
            )
            total_loss += float(loss)
            preds.append(np.asarray(p[:n_valid]))
            max_logits.append(np.asarray(m[:n_valid]))
        return (
            total_loss,
            np.concatenate(preds) if preds else np.zeros(0, np.int64),
            np.concatenate(max_logits) if max_logits else np.zeros(0, np.float32),
        )


@dataclass
class BucketedStagedCorpus:
    """A staged corpus partitioned by context count into a static ladder of
    bag widths (data.pipeline's bucketizer applied at staging): one
    :class:`StagedCorpus` per non-empty bucket, each sampled/scanned at its
    own width by :class:`BucketedEpochRunner`. Rows keep their full context
    lists (bucket width only bounds the SAMPLED window, exactly like the
    fixed-width runner's ``bag``)."""

    buckets: list[tuple[int, StagedCorpus]]  # (bag width, staged rows)

    @property
    def n_items(self) -> int:
        return sum(s.n_items for _, s in self.buckets)

    @property
    def n_contexts(self) -> int:
        return sum(s.n_contexts for _, s in self.buckets)

    @property
    def contexts(self):
        """First bucket's context array (device/placement introspection)."""
        return self.buckets[0][1].contexts

    def host_labels(self) -> np.ndarray:
        """Labels in bucket-concatenation order — the ``expected`` array
        matching :meth:`BucketedEpochRunner.run_eval_epoch`'s preds."""
        return np.concatenate(
            [np.asarray(s.labels) for _, s in self.buckets]
        ) if self.buckets else np.zeros(0, np.int32)


def _bucket_host_partition(
    staged: StagedCorpus, ladder: tuple[int, ...]
) -> list[tuple[int, StagedCorpus]]:
    """Partition a HOST-staged corpus's rows by context count into ladder
    buckets (host numpy sub-stagings; the caller places/shards each).
    Rows with more contexts than the top width land in the top bucket (the
    rotation-window sampler subsamples them, same as the fixed-width
    path). Empty buckets are dropped — they would only cost a compile —
    except the top one, which is always kept (possibly with zero rows) so
    an empty split behaves like the fixed-width path."""
    from code2vec_tpu.data.pipeline import assign_buckets

    rs = np.asarray(staged.row_splits).astype(np.int64)
    ctx = np.asarray(staged.contexts)
    labels = np.asarray(staged.labels)
    flags = (
        None if staged.remap_flags is None else np.asarray(staged.remap_flags)
    )
    counts = np.diff(rs)
    bucket_of = assign_buckets(counts, ladder)
    out: list[tuple[int, StagedCorpus]] = []
    for b, width in enumerate(ladder):
        members = np.flatnonzero(bucket_of == b)
        if not len(members) and width != ladder[-1]:
            continue
        flat, _, _ = flat_context_indices(rs, members)
        sub_splits = np.zeros(len(members) + 1, np.int64)
        np.cumsum(counts[members], out=sub_splits[1:])
        sub = StagedCorpus(
            contexts=ctx[flat],
            row_splits=sub_splits,
            labels=labels[members],
            n_items=len(members),
            remap_ids=(
                None
                if staged.remap_ids is None
                else np.asarray(staged.remap_ids)
            ),
            remap_flags=None if flags is None else flags[members],
        )
        out.append((width, sub))
    return out


def bucket_staged(
    staged: StagedCorpus,
    ladder: tuple[int, ...],
    device: Any | None = None,
) -> BucketedStagedCorpus:
    """Ladder-partition a host staging and place each bucket on ``device``
    (see :func:`_bucket_host_partition` for the membership rules);
    placement introspection (``.contexts``) works and the runners fall
    through their empty chunk plans."""
    return BucketedStagedCorpus(
        buckets=[
            (width, place_staged(sub, device=device))
            for width, sub in _bucket_host_partition(staged, ladder)
        ]
    )


@dataclass
class BucketedShardedStagedCorpus:
    """Bucketed x data-axis-sharded staging: each ladder bucket's rows are
    partitioned over the mesh's ``data`` axis (per-device HBM ~1/D of a
    replicated bucketed staging), and each bucket scans at its own
    ``[B, L_b]`` shape — the composition the bucketed-vs-shard_staged
    mutual-exclusion guard used to forbid."""

    buckets: list[tuple[int, ShardedStagedCorpus]]

    @property
    def n_items(self) -> int:
        return sum(s.n_items for _, s in self.buckets)

    @property
    def n_contexts(self) -> int:
        return sum(s.n_contexts for _, s in self.buckets)

    @property
    def contexts(self):
        """First bucket's context array (device/placement introspection)."""
        return self.buckets[0][1].contexts

    def flat_labels(self) -> np.ndarray:
        """Valid labels in bucket-major, shard-concatenation order — the
        ``expected`` array matching
        :meth:`BucketedShardedEpochRunner.run_eval_epoch`'s preds."""
        return (
            np.concatenate([s.flat_labels() for _, s in self.buckets])
            if self.buckets
            else np.zeros(0, np.int32)
        )


def bucket_shard_staged(
    staged: StagedCorpus, ladder: tuple[int, ...], mesh
) -> BucketedShardedStagedCorpus:
    """Ladder-partition a host staging, then shard EACH bucket over the
    mesh's ``data`` axis (:func:`shard_staged`)."""
    return BucketedShardedStagedCorpus(
        buckets=[
            (width, shard_staged(sub, mesh))
            for width, sub in _bucket_host_partition(staged, ladder)
        ]
    )


class BucketedEpochRunner:
    """Bucketed counterpart of :class:`EpochRunner`: one scanned sub-epoch
    per ladder width per epoch, each at its bucket's ``[B, L_b]`` shape —
    so every step pays for the bag its examples actually need instead of
    the worst-case width. Compiles exactly one chunk program per
    (width, chunk length): the ladder is the whole compile budget.

    Drop-in for the loop's ``(runner, staged)`` protocol: ``run_train_epoch``
    / ``run_eval_epoch`` take a :class:`BucketedStagedCorpus` where the
    fixed runner takes a :class:`StagedCorpus`. The train-pass bucket order
    is drawn from the epoch rng (seeded-deterministic interleave at bucket
    granularity); eval runs buckets in ladder order so preds align with
    :meth:`BucketedStagedCorpus.host_labels`.
    """

    def __init__(
        self,
        model_config: Code2VecConfig,
        class_weights: jnp.ndarray,
        batch_size: int,
        ladder: tuple[int, ...],
        chunk_batches: int = 16,
        mesh=None,
        shuffle_variable_ids: bool = False,
        sample_prefetch: bool = False,
        table_update: str = "dense",
    ):
        self.ladder = tuple(ladder)
        self._runners = {
            width: EpochRunner(
                model_config,
                class_weights,
                batch_size,
                width,
                chunk_batches,
                mesh=mesh,
                shuffle_variable_ids=shuffle_variable_ids,
                sample_prefetch=sample_prefetch,
                table_update=table_update,
            )
            for width in self.ladder
        }

    def run_train_epoch(
        self,
        state,
        corpus: BucketedStagedCorpus,
        rng: np.random.Generator,
        key: jax.Array,
    ) -> tuple[Any, float, int]:
        """One training epoch over all buckets; returns (state, summed
        loss, n_batches). The per-bucket sub-epochs shuffle their own rows
        (the same seeded rng the fixed runner uses)."""
        total_loss = 0.0
        n_batches = 0
        for i in rng.permutation(len(corpus.buckets)):
            width, staged = corpus.buckets[int(i)]
            key, sub_key = jax.random.split(key)
            state, loss, nb = self._runners[width].run_train_epoch(
                state, staged, rng, sub_key
            )
            total_loss += loss
            n_batches += nb
        return state, total_loss, n_batches

    def run_eval_epoch(
        self,
        state,
        corpus: BucketedStagedCorpus,
        key: jax.Array,
    ) -> tuple[float, np.ndarray, np.ndarray]:
        """One eval pass, buckets in ladder order; preds/max_logits align
        with :meth:`BucketedStagedCorpus.host_labels`."""
        total_loss = 0.0
        preds: list[np.ndarray] = []
        max_logits: list[np.ndarray] = []
        for width, staged in corpus.buckets:
            key, sub_key = jax.random.split(key)
            loss, p, m = self._runners[width].run_eval_epoch(
                state, staged, sub_key
            )
            total_loss += loss
            preds.append(p)
            max_logits.append(m)
        return (
            total_loss,
            np.concatenate(preds) if preds else np.zeros(0, np.int64),
            np.concatenate(max_logits) if max_logits else np.zeros(0, np.float32),
        )


class ShardedEpochRunner:
    """Scanned train epochs over a :class:`ShardedStagedCorpus`.

    The corpus lives partitioned over the ``data`` axis; batch assembly
    runs under ``shard_map`` so each device gathers exactly ``B/D`` rows
    from its OWN corpus block — per-device HBM is ~1/D of replicated
    staging and sampling adds no cross-device traffic. The assembled
    global batch (batch dim sharded over ``data``) then feeds the same raw
    train step as everywhere else; XLA inserts the gradient all-reduce.

    Sampling semantics: stratified-by-shard (each shard draws from its own
    item partition every batch) — the same DDP sampling the host-sharded
    multi-host feed uses, vs the replicated runner's global shuffle.
    Method and/or variable task (remap ids replicated, flags sharded with
    the rows); ``ctx_axis`` must be 1.
    """

    def __init__(
        self,
        model_config: Code2VecConfig,
        class_weights: jnp.ndarray,
        batch_size: int,
        bag: int,
        chunk_batches: int = 16,
        mesh=None,
        shuffle_variable_ids: bool = False,
        sample_prefetch: bool = False,
        table_update: str = "dense",
    ):
        if mesh is None:
            raise ValueError("ShardedEpochRunner needs a mesh")
        self.shuffle_variable_ids = shuffle_variable_ids
        self.sample_prefetch = sample_prefetch
        if mesh.shape.get("ctx", 1) > 1:
            raise ValueError(
                "sharded corpus staging composes with data/model axes; a "
                "ctx-sharded bag needs replicated staging or the host "
                "pipeline"
            )
        self.n_shards = int(mesh.shape["data"])
        if batch_size % self.n_shards:
            raise ValueError(
                f"batch_size {batch_size} not divisible by data axis "
                f"{self.n_shards}"
            )
        self.per_shard = batch_size // self.n_shards
        self.bag = bag
        self.chunk_batches = chunk_batches
        self.mesh = mesh
        # same trace-time contract as the replicated runner: the shard_map
        # sampler emits the GLOBAL [B, bag] batch, so the shared patterns
        # hold unchanged on the multi-host path
        self._raw_train = contract_step(build_train_step_fn(
            model_config, class_weights, table_update
        ))
        self._raw_eval = contract_step(
            build_eval_step_fn(model_config, class_weights)
        )
        self._train_chunks: dict[int, Callable] = {}
        self._eval_chunks: dict[int, Callable] = {}
        self._sampler_cache = None

    def _sampler(self) -> Callable:
        """The shard_map batch assembler (independent of chunk length):
        each shard's block samples its own rows, outputs concatenate over
        the data axis into the global [B, bag] batch."""
        if self._sampler_cache is None:
            try:
                from jax import shard_map
            except ImportError:  # moved to top level after jax 0.4.x
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            bag, mesh = self.bag, self.mesh

            def sample_shard(contexts, row_splits, labels, rows, valid, key,
                             remap_ids, remap_flags):
                # blocks carry a leading shard axis of length 1
                k = jax.random.fold_in(key, jax.lax.axis_index("data"))
                return _sample_batch(
                    contexts[0], row_splits[0], labels[0],
                    rows[0], valid[0], bag, k,
                    remap_ids, remap_flags[0],
                )

            batch_specs = {
                "starts": P("data", None),
                "paths": P("data", None),
                "ends": P("data", None),
                "labels": P("data"),
                "example_mask": P("data"),
            }
            self._sampler_cache = shard_map(
                sample_shard,
                mesh=mesh,
                in_specs=(P("data"), P("data"), P("data"),
                          P("data"), P("data"), P(), P(), P("data")),
                out_specs=batch_specs,
            )
        return self._sampler_cache

    def _train_chunk(self, n_batches: int) -> Callable:
        if n_batches not in self._train_chunks:
            per_shard = self.per_shard
            sampler = self._sampler()

            @partial(jax.jit, donate_argnums=(0,))
            def run(state, contexts, row_splits, labels, perm_rows,
                    perm_valid, key, remap_ids=None, remap_flags=None):
                if remap_ids is None:  # trace-time: remap compiled out
                    remap_ids = jnp.zeros(0, jnp.int32)
                if remap_flags is None:
                    remap_flags = jnp.zeros(
                        (row_splits.shape[0], row_splits.shape[1] - 1),
                        jnp.int32,
                    )

                def sample_i(key, i):
                    key, sample_key = jax.random.split(key)
                    sl = lambda a: jax.lax.dynamic_slice_in_dim(
                        a, i * per_shard, per_shard, 1
                    )
                    batch = sampler(
                        contexts, row_splits, labels,
                        sl(perm_rows), sl(perm_valid), sample_key,
                        remap_ids, remap_flags,
                    )
                    return key, batch

                return _scan_train_chunk(
                    sample_i, self._raw_train, state, key, n_batches,
                    self.sample_prefetch,
                )

            self._train_chunks[n_batches] = run
        return self._train_chunks[n_batches]

    def _eval_chunk(self, n_batches: int) -> Callable:
        if n_batches not in self._eval_chunks:
            sampler = self._sampler()
            per_shard = self.per_shard

            @jax.jit
            def run(state, contexts, row_splits, labels, perm_rows,
                    perm_valid, key, remap_ids=None, remap_flags=None):
                if remap_ids is None:
                    remap_ids = jnp.zeros(0, jnp.int32)
                if remap_flags is None:
                    remap_flags = jnp.zeros(
                        (row_splits.shape[0], row_splits.shape[1] - 1),
                        jnp.int32,
                    )

                def sample_i(key, i):
                    key, sample_key = jax.random.split(key)
                    sl = lambda a: jax.lax.dynamic_slice_in_dim(
                        a, i * per_shard, per_shard, 1
                    )
                    batch = sampler(
                        contexts, row_splits, labels,
                        sl(perm_rows), sl(perm_valid), sample_key,
                        remap_ids, remap_flags,
                    )
                    return key, batch

                def eval_body(batch):
                    out = self._raw_eval(state, batch)
                    return out["loss"], out["preds"], out["max_logit"]

                losses, preds, max_logits = _scan_eval_chunk(
                    sample_i, eval_body, key, n_batches, self.sample_prefetch
                )
                return losses, preds, max_logits  # [nb], [nb, B], [nb, B]

            self._eval_chunks[n_batches] = run
        return self._eval_chunks[n_batches]

    def run_eval_epoch(
        self,
        state,
        corpus: ShardedStagedCorpus,
        key: jax.Array,
    ) -> tuple[float, np.ndarray, np.ndarray]:
        """One eval pass, each shard in its natural row order. Returns
        (loss, preds, max_logits) where preds align with
        ``corpus.flat_labels()`` (shard-concatenation order).

        Loss scale: the sharded pass runs ``ceil(max_shard/per_shard)``
        batches — more than the replicated runner's ``ceil(N/B)`` when
        shards are imbalanced, with tail batches mixing masked rows — so a
        raw sum of per-batch means would not be comparable across paths.
        Instead the per-batch means are recombined weighted by their
        valid-row counts (exactly the global per-example mean under uniform
        class weights) and reported as ``mean × ceil(N/B)``: the same
        summed-per-batch-mean scale the replicated runner and the host
        pipeline report."""
        D, per_shard = self.n_shards, self.per_shard
        counts = corpus.shard_counts
        nb_total = max(-(-int(counts.max()) // per_shard), 1)
        # same remap gating as training: the replicated runner and the host
        # pipeline both apply the per-epoch @var remap at eval too
        use_remap = self.shuffle_variable_ids and corpus.remap_ids is not None
        remap_ids = corpus.remap_ids if use_remap else None
        remap_flags = corpus.remap_flags if use_remap else None

        weighted_loss = 0.0
        weight_total = 0.0
        shard_preds: list[list[np.ndarray]] = [[] for _ in range(D)]
        shard_logits: list[list[np.ndarray]] = [[] for _ in range(D)]
        lo = 0
        while lo < nb_total:
            nb = min(self.chunk_batches, nb_total - lo)
            span = nb * per_shard
            rows = np.zeros((D, span), np.int32)
            valid = np.zeros((D, span), np.float32)
            for s in range(D):
                start = lo * per_shard
                take = np.arange(start, min(start + span, int(counts[s])))
                rows[s, : len(take)] = take
                valid[s, : len(take)] = 1.0
            key, chunk_key = jax.random.split(key)
            losses, p, ml = self._eval_chunk(nb)(
                state, corpus.contexts, corpus.row_splits, corpus.labels,
                rows, valid, chunk_key, remap_ids, remap_flags,
            )
            # valid rows in global batch i of this chunk, across shards
            batch_valid = valid.reshape(D, nb, per_shard).sum(axis=(0, 2))
            weighted_loss += float(np.asarray(losses) @ batch_valid)
            weight_total += float(batch_valid.sum())
            p = np.asarray(p).reshape(nb, D, per_shard)
            ml = np.asarray(ml).reshape(nb, D, per_shard)
            for s in range(D):
                remaining = int(counts[s]) - lo * per_shard
                for i in range(nb):
                    take = min(max(remaining - i * per_shard, 0), per_shard)
                    if take:
                        shard_preds[s].append(p[i, s, :take])
                        shard_logits[s].append(ml[i, s, :take])
            lo += nb
        preds = np.concatenate(
            [np.concatenate(x) if x else np.zeros(0, np.int64) for x in shard_preds]
        )
        max_logits = np.concatenate(
            [np.concatenate(x) if x else np.zeros(0, np.float32) for x in shard_logits]
        )
        # replicated-equivalent scale: per-example mean × ceil(N/B)
        n_total = int(counts.sum())
        batch_size = per_shard * D
        mean_loss = weighted_loss / max(weight_total, 1.0)
        total_loss = mean_loss * max(-(-n_total // batch_size), 1)
        return total_loss, preds, max_logits

    def run_train_epoch(
        self,
        state,
        corpus: ShardedStagedCorpus,
        rng: np.random.Generator,
        key: jax.Array,
    ) -> tuple[Any, float, int]:
        """One stratified training epoch; returns (state, loss sum,
        n_batches). Epoch length covers the LARGEST shard; smaller shards
        pad with masked repeats at the tail (same masking rule as
        ``iter_batches``)."""
        D, per_shard = self.n_shards, self.per_shard
        counts = corpus.shard_counts
        orders = [rng.permutation(int(c)) for c in counts]
        nb_total = max(-(-int(counts.max()) // per_shard), 1)
        use_remap = (
            self.shuffle_variable_ids and corpus.remap_ids is not None
        )
        remap_ids = corpus.remap_ids if use_remap else None
        remap_flags = corpus.remap_flags if use_remap else None

        chunk_losses = []
        n_batches = 0
        lo = 0
        while lo < nb_total:
            nb = min(self.chunk_batches, nb_total - lo)
            span = nb * per_shard
            rows = np.zeros((D, span), np.int32)
            valid = np.zeros((D, span), np.float32)
            for s in range(D):
                start = lo * per_shard
                take = orders[s][start : start + span]
                rows[s, : len(take)] = take
                if len(take) < span:
                    rows[s, len(take):] = orders[s][0] if len(orders[s]) else 0
                valid[s, : max(min(int(counts[s]) - start, span), 0)] = 1.0
            key, chunk_key = jax.random.split(key)
            state, loss = self._train_chunk(nb)(
                state, corpus.contexts, corpus.row_splits, corpus.labels,
                rows, valid, chunk_key, remap_ids, remap_flags,
            )
            chunk_losses.append(loss)
            n_batches += nb
            lo += nb
        return state, float(np.sum(jax.device_get(chunk_losses))), n_batches


class BucketedShardedEpochRunner:
    """Bucketed counterpart of :class:`ShardedEpochRunner` (and the sharded
    counterpart of :class:`BucketedEpochRunner`): one data-axis-sharded
    scanned sub-epoch per ladder width per epoch. Drop-in for the loop's
    ``(runner, staged)`` protocol with a
    :class:`BucketedShardedStagedCorpus`; the train-pass bucket order is
    drawn from the epoch rng, eval runs buckets in ladder order so preds
    align with :meth:`BucketedShardedStagedCorpus.flat_labels`.
    """

    def __init__(
        self,
        model_config: Code2VecConfig,
        class_weights: jnp.ndarray,
        batch_size: int,
        ladder: tuple[int, ...],
        chunk_batches: int = 16,
        mesh=None,
        shuffle_variable_ids: bool = False,
        sample_prefetch: bool = False,
        table_update: str = "dense",
    ):
        self.ladder = tuple(ladder)
        self._runners = {
            width: ShardedEpochRunner(
                model_config,
                class_weights,
                batch_size,
                width,
                chunk_batches,
                mesh=mesh,
                shuffle_variable_ids=shuffle_variable_ids,
                sample_prefetch=sample_prefetch,
                table_update=table_update,
            )
            for width in self.ladder
        }

    def run_train_epoch(
        self,
        state,
        corpus: BucketedShardedStagedCorpus,
        rng: np.random.Generator,
        key: jax.Array,
    ) -> tuple[Any, float, int]:
        total_loss = 0.0
        n_batches = 0
        for i in rng.permutation(len(corpus.buckets)):
            width, staged = corpus.buckets[int(i)]
            key, sub_key = jax.random.split(key)
            state, loss, nb = self._runners[width].run_train_epoch(
                state, staged, rng, sub_key
            )
            total_loss += loss
            n_batches += nb
        return state, total_loss, n_batches

    def run_eval_epoch(
        self,
        state,
        corpus: BucketedShardedStagedCorpus,
        key: jax.Array,
    ) -> tuple[float, np.ndarray, np.ndarray]:
        total_loss = 0.0
        preds: list[np.ndarray] = []
        max_logits: list[np.ndarray] = []
        for width, staged in corpus.buckets:
            key, sub_key = jax.random.split(key)
            loss, p, m = self._runners[width].run_eval_epoch(
                state, staged, sub_key
            )
            total_loss += loss
            preds.append(p)
            max_logits.append(m)
        return (
            total_loss,
            np.concatenate(preds) if preds else np.zeros(0, np.int64),
            np.concatenate(max_logits)
            if max_logits
            else np.zeros(0, np.float32),
        )
