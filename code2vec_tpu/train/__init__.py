"""Training: config, jitted steps, epoch loop, HPO."""

from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_train_step,
)
