"""Training configuration — the Option equivalent (reference: main.py:93-115)
plus the flags Option reads straight from argparse. One frozen dataclass so
jitted code can hash it statically.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class TrainConfig:
    # reproducibility (reference --random_seed, main.py:38; unlike the
    # reference, the train/test split is ALSO derived from this seed)
    random_seed: int = 123

    # model dims (main.py:45-47)
    terminal_embed_size: int = 100
    path_embed_size: int = 100
    encode_size: int = 300
    # bag size: max path-contexts sampled per example per epoch (main.py:48)
    max_path_length: int = 200

    # optimizer (main.py:55-58) — torch-style Adam with coupled L2
    batch_size: int = 32
    max_epoch: int = 40
    lr: float = 0.01
    beta_min: float = 0.9
    beta_max: float = 0.999
    weight_decay: float = 0.0
    dropout_prob: float = 0.25

    # loss head (main.py:73-75)
    angular_margin_loss: bool = False
    angular_margin: float = 0.5
    inverse_temp: float = 30.0

    # tasks (main.py:77-79)
    infer_method_name: bool = True
    infer_variable_name: bool = False
    shuffle_variable_indexes: bool = False

    # eval + control (main.py:67-68; early stop main.py:233-242)
    eval_method: str = "subtoken"  # exact | subtoken | ave_subtoken
    print_sample_cycle: int = 10
    early_stop_patience: int = 10

    # class weighting: "reference" = 1/freq over the de-facto-uniform freq
    # table (SURVEY.md §2.2), "occurrence" = true inverse-occurrence weights,
    # "none" = unweighted
    class_weighting: str = "reference"

    # TPU-native knobs (no reference counterpart)
    compute_dtype: str = "float32"  # or "bfloat16"
    data_axis: int = 1  # mesh parallelism, see code2vec_tpu.parallel
    model_axis: int = 1
    context_axis: int = 1
    use_pallas: bool = False  # Pallas kernels on the hot path (ops/)
    pallas_block_b: int = 8  # the kernel's batch-tile size
    # which Pallas kernel serves the forward (ops/fused_encode_pool.py):
    # "pool_only" = fuse only score->softmax->pool (the original kernel);
    # "gather_split" = XLA gathers rows, kernel fuses encode->attend->pool;
    # "fused" = in-kernel DMA gather too — the full chain in VMEM;
    # "auto" = consult the autotuned schedule cache (ops/autotune.py) per
    # traced (batch, width) shape — zero search at trace time
    pallas_impl: str = "pool_only"
    pallas_dma_depth: int = 2  # fused-impl gather double-buffer slots
    pallas_chunk_l: int = 128  # fused-impl bag-chunk lane tile
    # bag-softmax numerics of the fused kernel (ops/fused_encode_pool.py):
    # "auto" (materialize at ladder widths, flash-style online above the
    # base top when --max_contexts 0 adds longbag rungs) | "materialize" |
    # "online" | "two_pass"
    pallas_softmax: str = "auto"
    # embedding-table storage dtype for SERVING/EVAL forwards: f32 (train
    # master weights; the only dtype train() accepts) | bf16 | int8 (per-row
    # scale, dequant on load — ops/quant.py). Export/predict accept it.
    table_dtype: str = "f32"
    # kernel-schedule cache path ("" = $C2V_AUTOTUNE_CACHE or
    # ~/.cache/code2vec_tpu/autotune_schedules.json)
    autotune_cache: str = ""
    attn_impl: str = "xla"  # attention-pool lowering: "xla" | "streaming"
    encoder_impl: str = "concat"  # context-encoder lowering: "concat" | "split"
    # device-epoch train chunks sample batch i+1 while stepping on batch i
    # (double-buffering; same batches in the same order — losses match up
    # to float reassociation across the two compiled programs)
    sample_prefetch: bool = False
    embed_grad: str = "dense"  # embedding backward formulation (ops.embed)
    # PRNG impl for the dropout stream: threefry2x32 (jax default,
    # reproducible everywhere) | rbg | unsafe_rbg (faster on TPU; different
    # stream, still seeded-deterministic per backend)
    rng_impl: str = "threefry2x32"
    # Adam first-moment storage dtype: float32 (torch parity, default) |
    # bfloat16 (opt-in HBM-traffic lever — mu is read-modify-written every
    # step, ~280 MB at top11 scale; nu always stays f32). Checkpoints
    # store whatever dtype was used; resume with the same setting.
    adam_mu_dtype: str = "float32"
    # embedding-table optimizer: "dense" (torch.optim.Adam parity — every
    # row's moments decay every step) | "lazy" (touched-rows updates with
    # torch.optim.SparseAdam semantics, train/table_opt.py — skips the
    # full-table gradient materialization and Adam RMW; the opt-state
    # structure differs, so resume with the same setting)
    table_update: str = "dense"
    # pad table/head vocab dims to this multiple so they shard evenly over
    # the model axis; 0 = auto (use model_axis). Checkpoint param shapes
    # depend on it — pin it explicitly to resume a run under a different
    # model_axis (the restore validates and explains a mismatch)
    vocab_pad_multiple: int = 0
    # length-aware bucketed batching (data/pipeline.py bucketizer): partition
    # each epoch's examples by REAL context count into a static ladder of bag
    # widths and emit [B, L_b] batches per bucket — on a skewed corpus most
    # steps stop paying embedding gathers / attention FLOPs / HBM traffic
    # for PAD slots. jit caches per shape, so a run compiles exactly
    # len(ladder) step variants (the recompile detector is budgeted
    # accordingly). Per-example forward math is unchanged (PAD carries zero
    # attention weight), so the per-example loss multiset is invariant.
    # Composes with every feed variant (PR 10): streaming epochs emit
    # ladder widths with per-bucket carry, host-sharded feeding follows a
    # globally-agreed width schedule, shard_staged_corpus shards each
    # bucket over the data axis, and mmap-CSR corpora gather per bucket.
    bucketed: bool = False
    # comma list of bag widths ending at max_path_length (e.g. "25,50,100,200");
    # empty = derive a geometric ladder from the corpus length histogram
    bucket_ladder: str = ""
    # per-example context cap: -1 = follow max_path_length (the historical
    # behavior — every path silently subsamples long bags down to the bag
    # width); 0 = UNBOUNDED (longbag mode, requires --bucketed): nothing is
    # truncated — the bucket ladder grows longbag rungs above
    # max_path_length (multiples of pallas_chunk_l, derived from the corpus
    # length histogram / CSR footer — data/pipeline.derive_longbag_ladder)
    # and widths above the base top stream through the fused kernel's
    # chunked softmax in bounded VMEM. A positive value is rejected: the
    # bounded cap IS max_path_length — two knobs for one cap would drift.
    max_contexts: int = -1
    # streaming epochs: build at most this many epoch rows at a time instead
    # of materializing the whole [N, L] epoch (0 = materialize). Bounds host
    # RSS at java-large scale — see docs/ARCHITECTURE.md memory budget
    stream_chunk_items: int = 0
    # parallel host ingest (data/parallel_feed.py): N forked worker
    # processes execute each epoch's batch PLAN while every RNG draw stays
    # on the coordinator — feed order, loss history, and mid-epoch resume
    # cursors are bitwise identical to 0 (= build on the coordinator, the
    # historical path). Batches travel through preallocated shared-memory
    # arenas as zero-copy views. Method task, host pipeline only; composes
    # with bucketed/streaming/mmap x prefetch; device_epoch, the variable
    # task, and host-sharded feeding reject it loudly.
    feed_workers: int = 0
    # host-epoch input pipeline (train/prefetch.py): a background thread
    # builds + transfers this many batches ahead of compute (0 = synchronous).
    # Identical batches in the identical order — the overlap is free of
    # semantic drift. The host pipeline is the only multi-host path, so this
    # is also the pod-scale lever; device_epoch runs ignore it (they have
    # their own on-device sample_prefetch).
    prefetch_batches: int = 0
    # step-time attribution (train/prefetch.py:StepProfiler): fence the
    # first N train steps of each epoch with block_until_ready and log the
    # host-build / H2D / device-compute split (0 = off). The first profiled
    # step of a run includes XLA compile in compute_ms.
    profile_steps: int = 0

    # checkpoint/resume (framework extension; the reference cannot resume,
    # SURVEY.md §5.4)
    resume: bool = False
    # also save every N epochs (0 = best-F1 only) — preemption safety for
    # pod runs; resume restores params/opt state/RNG/early-stop counters
    checkpoint_cycle: int = 0
    # elastic training (checkpoint.py / train/preempt.py / faultinject.py):
    # async checkpointing — the loop blocks only for the device-to-host
    # snapshot; persistence runs on a background thread with at-most-one
    # save in flight (single-process only; pods force sync saves)
    async_checkpoint: bool = False
    # ALSO save the `last` slot every N train steps, mid-epoch, with a data
    # cursor (epoch, step-in-epoch, host RNG state, per-bucket positions)
    # so --resume restarts INSIDE the epoch with bitwise-equal metrics
    # (host pipeline only; 0 = epoch-boundary saves only)
    checkpoint_every_steps: int = 0
    # deterministic fault-injection plan (faultinject.py grammar, e.g.
    # "train_step@10:sigterm,mid_save@1:raise"); empty = none. Tests and
    # drills only — it crashes the process on purpose.
    fault_plan: str = ""

    # device-resident epochs (train/device_epoch.py): stage the corpus in
    # HBM once and run whole scanned chunks of batches per dispatch, with
    # per-epoch context sampling on device. Biggest win when host->device
    # bandwidth is the bottleneck. Method-name task on a single device only;
    # other configurations fall back to the host pipeline.
    device_epoch: bool = False
    device_chunk_batches: int = 16
    # shard the staged TRAIN corpus over the data axis instead of
    # replicating it (per-device HBM ~1/data_axis; stratified-by-shard
    # sampling via shard_map). Method and/or variable task; ctx_axis == 1.
    shard_staged_corpus: bool = False

    def with_updates(self, **kw) -> "TrainConfig":
        return replace(self, **kw)
