"""Async double-buffered host→device input pipeline + step-time attribution.

The host-epoch loops (train/loop.py) are a bag-of-path-contexts feed: every
step gathers/pads variable-length context bags into fixed ``[B, L]`` numpy
tensors (data/pipeline.py). Run serially, the accelerator idles while the
host builds the next batch — the exact overlap gap VERDICT.md flagged as the
unexplained share of the measured step time. :class:`HostPrefetcher` moves
batch construction AND the host→device transfer (``to_device`` — identity,
``global_batch``, or ``local_to_global_batch``) onto a single background
thread that runs ``depth`` batches ahead of compute behind a bounded queue:

- **deterministic ordering** — one producer thread advancing the batch
  iterator in order through a FIFO queue yields bitwise-identical batches in
  the identical order to the synchronous loop (and all host-RNG draws happen
  in the same sequence, since the consumer never touches the epoch RNG while
  the producer is live);
- **exception propagation** — a producer failure is re-raised at the
  consumer's next pull, original traceback attached;
- **backpressure** — the queue holds at most ``depth`` ready batches, so a
  slow consumer bounds host memory at ``depth + 1`` in-flight batches;
- **clean shutdown** — :meth:`HostPrefetcher.close` (or exiting the context
  manager, including via an exception mid-epoch) stops the producer, closes
  the underlying generator (its ``finally`` blocks run), and joins the
  thread.

:class:`StepProfiler` attributes wall time per step into host-build /
H2D-transfer / device-compute buckets on ~``sample_steps`` STRIDED sample
steps per epoch: producer-side ``perf_counter`` stamps plus
``block_until_ready`` fencing on those steps, nothing on the rest — so
steady-state pipelining is not perturbed by the measurement. Surfaced as
``--profile_steps`` (cli.py), logged per epoch by the train loop, emitted
as ``step_sample`` events (obs/events.py), and carried in bench.py's JSON
detail.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
import traceback
from typing import Callable, Iterable, Iterator

import jax

from code2vec_tpu import faultinject
from code2vec_tpu.obs import handles
from code2vec_tpu.obs.trace import get_tracer
from code2vec_tpu.train.preempt import preemption_guard

__all__ = ["HostPrefetcher", "StepProfiler", "device_batches"]

_NO_SPAN = contextlib.nullcontext()
_SPAN_WARMUP_STEPS = 8
_SPAN_STRIDE = 64


def _span_step(step: int, profiler: "StepProfiler | None") -> bool:
    """Whether this step's host_build/h2d get trace spans. SAMPLED — the
    first steps, every ``_SPAN_STRIDE``-th after, and the profiler's
    fenced steps — because a java-large epoch is ~16k steps and per-batch
    spans would flood the tracer's bounded buffer (dropping exactly the
    late-run events a trace exists to show). Mirrors the train loop's
    train_step span policy."""
    return (
        step < _SPAN_WARMUP_STEPS
        or step % _SPAN_STRIDE == 0
        or (profiler is not None and profiler.sampled(step))
    )


class StepProfiler:
    """Per-step wall-time attribution: host-build / H2D / device-compute.

    ~``sample_steps`` steps per epoch are recorded: ``host_build_ms``
    (time building the numpy batch), ``h2d_ms`` (time in ``to_device``,
    fenced with ``jax.block_until_ready`` so it measures the real transfer
    rather than async dispatch), and ``compute_ms`` (the fenced step).
    Unsampled steps carry no stamps at all — a java-large epoch is ~16k
    steps, and unread records would be pure producer-side overhead. Note
    the first sampled step of a run includes XLA compile in ``compute_ms``.

    Sampling is STRIDED: the first epoch (stride 1, epoch length unknown)
    fences the first ``sample_steps`` steps; the loop reports each epoch's
    length via :meth:`observe_epoch_length`, and from the next
    :meth:`reset` on the samples spread every ``len // sample_steps``
    steps across the WHOLE epoch — so tail-of-epoch steps (allocator
    drift, shrinking streaming chunks) are attributable, not just warmup.
    :meth:`sampled` stays a pure function of the step index, so the
    producer and consumer threads agree on the sample set without
    coordination.

    The producer thread writes host/H2D stamps and the consumer writes
    compute stamps, but never for the same key and never concurrently with
    :meth:`summary` (the epoch loop reads after the producer joined), so
    plain dicts under the GIL suffice.
    """

    def __init__(self, sample_steps: int = 0, peak_flops: float | None = None):
        self.sample_steps = int(sample_steps)
        self.stride = 1
        self._next_stride = 1
        self._host: dict[int, tuple[float, float, float]] = {}
        self._compute: dict[int, tuple[float, float | None]] = {}
        # per-device peak FLOP/s (obs.costs.peak_flops); with it set and
        # per-step flops recorded, sampled steps gain an mfu column
        self.peak_flops = peak_flops

    def sampled(self, step: int) -> bool:
        """Whether ``step`` gets block_until_ready fencing."""
        if self.sample_steps <= 0:
            return False
        return step % self.stride == 0 and step // self.stride < self.sample_steps

    def observe_epoch_length(self, n_steps: int) -> None:
        """Record the just-finished epoch's step count; the NEXT
        :meth:`reset` spreads the samples across that many steps."""
        if self.sample_steps > 0 and n_steps > 0:
            self._next_stride = max(1, n_steps // self.sample_steps)

    def record_host(
        self,
        step: int,
        host_build_ms: float,
        h2d_ms: float,
        feed_wait_ms: float = 0.0,
    ) -> None:
        """``feed_wait_ms``: how long the pull blocked on the parallel
        feed pool for this batch (``--feed_workers``; 0.0 on the
        coordinator-build path). It is a SUBSET of ``host_build_ms`` —
        with workers on, the residual build time is plan generation plus
        delivery, so a shrinking feed_wait_ms is the direct evidence the
        pool keeps the consumer fed."""
        self._host[step] = (host_build_ms, h2d_ms, feed_wait_ms)

    def record_compute(
        self, step: int, compute_ms: float, flops: float | None = None
    ) -> None:
        """``flops``: the step's analytic FLOP cost (fwd+bwd), when the
        loop knows the batch shape — enables the mfu column."""
        self._compute[step] = (compute_ms, flops)

    def per_step(self) -> list[dict[str, float]]:
        """Attribution dicts for the fenced steps, in step order."""
        out = []
        for step in sorted(self._compute):
            build, h2d, feed_wait = self._host.get(step, (0.0, 0.0, 0.0))
            compute_ms, flops = self._compute[step]
            rec = {
                "step": step,
                "host_build_ms": round(build, 3),
                "h2d_ms": round(h2d, 3),
                "feed_wait_ms": round(feed_wait, 3),
                "compute_ms": round(compute_ms, 3),
            }
            if flops and self.peak_flops and compute_ms > 0:
                achieved = flops / (compute_ms / 1e3)
                rec["mfu"] = round(achieved / self.peak_flops, 9)
            out.append(rec)
        return out

    def summary(self) -> dict[str, float] | None:
        """Mean per bucket over the fenced steps; None before any sample."""
        steps = self.per_step()
        if not steps:
            return None
        n = len(steps)
        out = {
            "host_build_ms": round(sum(s["host_build_ms"] for s in steps) / n, 3),
            "h2d_ms": round(sum(s["h2d_ms"] for s in steps) / n, 3),
            "feed_wait_ms": round(sum(s["feed_wait_ms"] for s in steps) / n, 3),
            "compute_ms": round(sum(s["compute_ms"] for s in steps) / n, 3),
            "profiled_steps": n,
        }
        with_mfu = [s["mfu"] for s in steps if "mfu" in s]
        if with_mfu:
            out["mfu"] = round(sum(with_mfu) / len(with_mfu), 9)
        return out

    def reset(self) -> None:
        self._host.clear()
        self._compute.clear()
        self.stride = self._next_stride


class _End:
    """End-of-stream sentinel (the producer exhausted the iterator)."""


class _Raised:
    """Producer-exception carrier; the consumer re-raises ``exc`` with the
    producer's formatted traceback text attached as ``remote_traceback``
    (feed-worker errors arrive with their CHILD-process traceback already
    embedded — this extends the same courtesy across the thread
    boundary, where only the exception object survives cleanly)."""

    def __init__(self, exc: BaseException, traceback_text: str | None = None):
        self.exc = exc
        self.traceback_text = traceback_text


class HostPrefetcher:
    """Iterate ``(host_batch, device_batch)`` pairs built ``depth`` ahead.

    The producer thread pulls from ``batches`` in order, applies
    ``to_device`` (the step's in-shardings placement — ``jax.device_put``
    with NamedShardings, or the multi-host ``global_batch`` /
    ``local_to_global_batch`` assembly, both of which are process-local
    calls and safe off the main thread), and parks the pair in a bounded
    FIFO queue. The host batch rides along because eval needs its labels /
    example mask host-side without a device round-trip.
    """

    _PUT_POLL_S = 0.05  # stop-check cadence while the queue is full

    def __init__(
        self,
        batches: Iterable[dict],
        to_device: Callable[[dict], dict],
        depth: int = 2,
        profiler: StepProfiler | None = None,
        drain_on_preemption: bool = False,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._batches = batches
        self._to_device = to_device
        self._profiler = profiler
        # a parallel-feed stream (data/parallel_feed.py) delivering
        # zero-copy arena views recycles a slot at the NEXT pull; the
        # async H2D must be fenced before that (fence_h2d False on the
        # copy-delivery and coordinator-build paths)
        self._fence = bool(getattr(batches, "fence_h2d", False))
        # train streams only (see device_batches): an eval stream that
        # drained on SIGTERM would silently compute metrics over a partial
        # test set and record them as a completed epoch. Single-process
        # only: a per-process early stream end desynchronizes the
        # lockstep collectives of a multi-process epoch
        self._drain = drain_on_preemption and jax.process_count() == 1
        # deliberately lock-free (nothing for obs.sync.make_lock to
        # route): the producer/consumer handoff is entirely the Queue's
        # own internal condition plus a stop Event — this class never
        # holds one lock while acquiring another
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exhausted = False
        self._thread = threading.Thread(
            target=self._produce, name="c2v-host-prefetch", daemon=True
        )
        self._thread.start()
        handles.track(self, "prefetcher")

    # ---- producer side -------------------------------------------------
    def _put(self, item) -> bool:
        """Queue ``item``, polling the stop flag so close() never deadlocks
        against a full queue. Returns False when shutdown was requested."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=self._PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        it = iter(self._batches)
        step = 0
        tracer = get_tracer()
        guard = preemption_guard()
        try:
            while not self._stop.is_set():
                if self._drain and guard.requested():
                    # SIGTERM drain: stop building batches nobody will
                    # consume and END the stream — the consumer side is
                    # about to checkpoint and exit, and racing its
                    # shutdown (a closed/abandoned queue) helps no one
                    self._put(_End)
                    return
                faultinject.fault_point("prefetch_produce", step=step)
                # span args are evaluated at entry: qsize() IS the queue
                # depth at this enqueue attempt (how far ahead we run)
                spanned = _span_step(step, self._profiler)
                depth = self._queue.qsize()
                batch = _End  # sentinel: a yielded None must NOT end the epoch
                with (
                    tracer.span("host_build", step=step, queue_depth=depth)
                    if spanned
                    else _NO_SPAN
                ):
                    t0 = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        pass
                if batch is _End:
                    self._put(_End)
                    return
                feed_wait_ms = getattr(it, "last_wait_ms", 0.0)
                t1 = time.perf_counter()
                with (
                    tracer.span("h2d", step=step, queue_depth=depth)
                    if spanned
                    else _NO_SPAN
                ):
                    device_batch = self._to_device(batch)
                    if self._fence:
                        # views delivery: the next pull recycles this
                        # batch's arena slot, so the transfer must be done
                        jax.block_until_ready(device_batch)
                    if self._profiler is not None and self._profiler.sampled(step):
                        jax.block_until_ready(device_batch)
                        self._profiler.record_host(
                            step,
                            (t1 - t0) * 1e3,
                            (time.perf_counter() - t1) * 1e3,
                            feed_wait_ms,
                        )
                if not self._put((batch, device_batch)):
                    return
                step += 1
        except BaseException as exc:  # noqa: BLE001 - re-raised at the consumer
            self._put(_Raised(exc, traceback.format_exc()))
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()  # run the generator's finally blocks promptly

    # ---- consumer side -------------------------------------------------
    def __iter__(self) -> Iterator[tuple[dict, dict]]:
        return self

    def __next__(self) -> tuple[dict, dict]:
        if self._exhausted:
            raise StopIteration
        item = self._queue.get()
        if item is _End:
            self._exhausted = True
            self._thread.join()
            raise StopIteration
        if isinstance(item, _Raised):
            self._exhausted = True
            self._thread.join()
            if item.traceback_text and not getattr(
                item.exc, "remote_traceback", None
            ):
                try:
                    item.exc.remote_traceback = item.traceback_text
                except Exception:  # exceptions with __slots__ etc.
                    pass
            raise item.exc
        return item

    def close(self) -> None:
        """Stop the producer and reclaim the thread. Safe to call twice,
        and after exhaustion; the early-epoch-exit path (early stop, HPO
        pruning, a raising train step) must not leak a thread blocked on a
        full queue."""
        self._stop.set()
        while True:  # unblock a producer parked on put()
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10.0)
        self._exhausted = True
        handles.untrack(self)

    def __enter__(self) -> "HostPrefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


class _SyncBatches:
    """The synchronous twin of :class:`HostPrefetcher`: same
    ``(host_batch, device_batch)`` iteration contract and timing stamps, no
    thread — so the epoch loops are written once against one interface and
    the profiler attributes both paths identically."""

    def __init__(
        self,
        batches: Iterable[dict],
        to_device: Callable[[dict], dict],
        profiler: StepProfiler | None = None,
    ):
        self._it = iter(batches)
        self._to_device = to_device
        self._profiler = profiler
        self._step = 0
        self._fence = bool(getattr(batches, "fence_h2d", False))

    def __iter__(self) -> Iterator[tuple[dict, dict]]:
        return self

    def __next__(self) -> tuple[dict, dict]:
        tracer = get_tracer()
        spanned = _span_step(self._step, self._profiler)
        t0 = time.perf_counter()
        with (
            tracer.span("host_build", step=self._step) if spanned else _NO_SPAN
        ):
            batch = next(self._it)  # StopIteration ends the epoch
        feed_wait_ms = getattr(self._it, "last_wait_ms", 0.0)
        t1 = time.perf_counter()
        with tracer.span("h2d", step=self._step) if spanned else _NO_SPAN:
            device_batch = self._to_device(batch)
            if self._fence:
                # views delivery: the next pull recycles this batch's
                # arena slot (see HostPrefetcher._produce)
                jax.block_until_ready(device_batch)
            if self._profiler is not None and self._profiler.sampled(self._step):
                jax.block_until_ready(device_batch)
                self._profiler.record_host(
                    self._step,
                    (t1 - t0) * 1e3,
                    (time.perf_counter() - t1) * 1e3,
                    feed_wait_ms,
                )
        self._step += 1
        return batch, device_batch

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()

    def __enter__(self) -> "_SyncBatches":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


def device_batches(
    batches: Iterable[dict],
    to_device: Callable[[dict], dict],
    prefetch: int = 0,
    profiler: StepProfiler | None = None,
    drain_on_preemption: bool = False,
):
    """The epoch loops' single entry point: a context manager iterating
    ``(host_batch, device_batch)`` pairs — prefetched ``prefetch`` deep when
    > 0, synchronous otherwise. Both paths yield identical batches in
    identical order under a fixed seed.

    ``drain_on_preemption``: let the producer thread end the stream early
    once the SIGTERM guard is set — for TRAIN streams, whose consumer
    re-checks the guard at stream end and never records a truncated pass;
    eval streams must run to completion (partial metrics would silently
    enter the history)."""
    if prefetch > 0:
        return HostPrefetcher(
            batches, to_device, depth=prefetch, profiler=profiler,
            drain_on_preemption=drain_on_preemption,
        )
    return _SyncBatches(batches, to_device, profiler=profiler)
