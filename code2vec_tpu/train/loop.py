"""The experiment driver loop (reference: main.py:118-248).

Control-flow parity with ``_train``: per-epoch dataset refresh (fresh
context subsample), train pass, test pass, metric emission, best-F1
checkpoint + vector export, ``print_sample`` every N epochs, early stop when
``bad_count > patience`` with the reference's quirky improvement test
(train-loss OR accuracy improving resets the counter, main.py:233-242).

Extensions over the reference: seeded split, resumable checkpoints, an
injectable ``report_fn`` for HPO pruning, metric sinks (stdout JSON /
logging / TensorBoard), optional jax.profiler tracing, and the run-level
telemetry subsystem (``code2vec_tpu.obs``): every metric emission goes
through one event stream (sinks are consumers of it), phases are traced
as Chrome-trace spans, and a recompile detector + memory sampler watch
runtime health at epoch boundaries.

Elastic training (checkpoint.py + train/preempt.py + faultinject.py):
saves go through a :class:`~code2vec_tpu.checkpoint.CheckpointWriter`
(``--async_checkpoint`` overlaps the disk write with the next steps),
``--checkpoint_every_steps`` adds mid-epoch cursor-bearing saves, SIGTERM
finishes the in-flight step + saves + exits cleanly, and ``--resume``
replays the host batch stream to the checkpointed cursor so a resumed run
reproduces the uninterrupted run's metrics bitwise (see
docs/ARCHITECTURE.md "Elastic training").
"""

from __future__ import annotations

import copy
import logging
import os
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu import export as export_mod
from code2vec_tpu import faultinject
from code2vec_tpu.checkpoint import (
    CheckpointWriter,
    TrainMeta,
    clear_checkpoints,
    restore_checkpoint,
)
from code2vec_tpu.data.pipeline import (
    bucket_batch_counts,
    build_epoch,
    derive_bucket_ladder,
    derive_longbag_ladder,
    empty_batch,
    iter_batches,
    make_batch_source,
    oov_rate,
    pad_batch_stream,
    pad_stats,
    parse_bucket_ladder,
    skip_batches,
    split_items,
    truncated_fraction_of_counts,
)
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.metrics import evaluate
from code2vec_tpu.models.code2vec import Code2VecConfig
from code2vec_tpu.obs.events import EventLog, sink_consumer
from code2vec_tpu.obs.runtime import (
    RecompileDetector,
    RuntimeHealth,
    memory_snapshot,
)
from code2vec_tpu.obs.trace import get_tracer, set_tracer
from code2vec_tpu.sinks import MetricSink, logging_sink  # re-export: canonical home is sinks
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.preempt import (
    PreemptionStop,
    coordinated_stop,
    install_sigterm_handler,
    preemption_guard,
    restore_sigterm_handler,
)
from code2vec_tpu.train.prefetch import StepProfiler, device_batches
from code2vec_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_train_step,
)

logger = logging.getLogger(__name__)

# nullcontext is reusable/reentrant; one shared instance keeps the
# unsampled-step path of _train_pass allocation-free
_NO_SPAN = nullcontext()

# the train pass accumulates per-step losses DEVICE-side and syncs once per
# epoch (a per-step float() would serialize host and device — jaxlint
# JX007); this window bounds how far the host may run ahead of the device
# (each in-flight step pins its batch buffers, so an unbounded dispatch
# queue is an HBM leak on slow steps). 2 = classic double buffering.
_LOSS_SYNC_WINDOW = 2


@dataclass
class TrainResult:
    best_f1: float
    final_f1: float
    epochs_run: int
    history: list[dict] = field(default_factory=list)
    state: object | None = None


class StopTraining(Exception):
    """Raised by a report_fn to end training early (the optuna-prune hook,
    reference: main.py:207-211)."""


def _rng_state(np_rng: np.random.Generator) -> dict:
    """JSON-serializable snapshot of the host RNG (PCG64 state is plain
    ints; json round-trips them exactly)."""
    return copy.deepcopy(np_rng.bit_generator.state)


def _data_cursor(
    epoch: int,
    step: int,
    feed_batch: int,
    np_rng_state: dict,
    jax_rng,
    partial_train_loss: float = 0.0,
    bucket_positions: dict | None = None,
) -> dict:
    """THE cursor schema — the single constructor for both mid-epoch
    (:class:`_EpochCursorHook`) and epoch-boundary saves, so the resume
    path always finds the same key set regardless of which save wrote
    last. ``feed_batch`` pins the stream geometry: a replay under a
    different batch size would keep the bag width (so the per-width check
    alone cannot catch it) yet skip the wrong rows."""
    return {
        "epoch": int(epoch),
        "step": int(step),
        "feed_batch": int(feed_batch),
        "np_rng_state": np_rng_state,
        "jax_rng": [int(x) for x in np.asarray(jax_rng).ravel()],
        "partial_train_loss": float(partial_train_loss),
        "bucket_positions": dict(bucket_positions or {}),
    }


class _EpochCursorHook:
    """Per-step bookkeeping behind mid-epoch saves and graceful preemption.

    ``_train_pass`` calls :meth:`after_step` once per consumed batch. The
    hook tracks the epoch-global step count and per-width batch positions
    (cumulative across a resume — it starts from the replayed cursor), and
    triggers a cursor-bearing ``last``-slot save every
    ``checkpoint_every_steps`` steps and/or when the preemption guard is
    set — in which case it raises :class:`PreemptionStop` AFTER the save,
    so the loop unwinds with the checkpoint already on disk.

    The cursor it writes makes the save resumable *inside* the epoch:
    ``np_rng_state`` is the host RNG state at epoch start (everything the
    epoch streams is a pure function of it), ``step`` is how many batches
    were consumed, ``partial_train_loss`` is the float64 running loss with
    the same accumulation order the uninterrupted epoch uses, and
    ``bucket_positions`` are the per-width batch counts the replay
    cross-checks (a ladder/batch-size change cannot be honored silently).

    :meth:`after_pass` re-checks the guard once the stream ends: the
    prefetch producer drains on SIGTERM, so a stream can end *early* —
    without the re-check an incomplete epoch would masquerade as a
    finished one and its metrics would go into the history.
    """

    def __init__(
        self,
        writer: CheckpointWriter | None,
        meta: TrainMeta,
        epoch: int,
        epoch_rng_state: dict,
        jax_rng,
        guard,
        feed_batch: int,
        every_steps: int = 0,
        skip: int = 0,
        loss_offset: float = 0.0,
        widths: dict[int, int] | None = None,
        tracer=None,
    ):
        self.writer = writer
        self.meta = meta
        self.epoch = epoch
        self.epoch_rng_state = epoch_rng_state
        self.jax_rng = jax_rng
        self.guard = guard
        self.feed_batch = int(feed_batch)
        self.every_steps = int(every_steps)
        self.steps = int(skip)
        self.loss_offset = float(loss_offset)
        self.widths = {int(w): int(c) for w, c in (widths or {}).items()}
        self.tracer = tracer or get_tracer()
        # incremental left-fold state: the running float64 partial and how
        # many entries of `losses` it covers
        self._partial = float(loss_offset)
        self._summed = 0

    def _cursor(self, partial_loss: float) -> dict:
        return _data_cursor(
            self.epoch, self.steps, self.feed_batch, self.epoch_rng_state,
            self.jax_rng, partial_loss, self.widths,
        )

    def _partial_loss(self, losses: list) -> float:
        """Running float64 left-fold of the epoch's losses, STARTING from
        the resumed offset — the identical sequence of binary additions
        the uninterrupted epoch's total uses (chunked left folds associate
        identically to one left fold), so the resumed total is
        bitwise-equal. Incremental: each save fetches only the losses
        since the previous one, not the whole epoch so far."""
        new = losses[self._summed:]
        self._partial = float(
            sum(map(float, jax.device_get(new)), self._partial)
        )
        self._summed = len(losses)
        return self._partial

    def _save(self, state, losses) -> None:
        partial = self._partial_loss(losses)
        self.meta.epoch = self.epoch  # resume re-enters this epoch
        self.meta.cursor = self._cursor(partial)
        with self.tracer.span(
            "checkpoint_save", category="checkpoint",
            epoch=self.epoch, slot="last", mid_epoch=True,
        ):
            self.writer.save(
                state, self.meta, "last", epoch=self.epoch, mid_epoch=True
            )

    def _should_stop(self, at_collective_point: bool) -> bool:
        """Act on the guard — every step when single-process, but only at
        deterministic collective points under multi-process: the flag
        flips at signal-delivery time, which differs per process, and the
        save it triggers is a collective orbax write (mismatched
        participants deadlock in the commit barrier). `coordinated_stop`
        agrees on process 0's view at points all processes reach at the
        same step (periodic-save steps, stream end)."""
        if self.guard is None:
            return False
        if jax.process_count() == 1:
            return self.guard.requested()
        return at_collective_point and coordinated_stop(self.guard)

    def after_step(self, state, losses, width: int) -> None:
        self.widths[width] = self.widths.get(width, 0) + 1
        self.steps += 1
        periodic = bool(
            self.every_steps and self.steps % self.every_steps == 0
        )
        stop = self._should_stop(periodic)
        if self.writer is not None and (stop or periodic):
            self._save(state, losses)
        if stop:
            raise PreemptionStop(self.guard.reason or "requested")

    def after_pass(self, state, losses) -> None:
        if self._should_stop(True):
            if self.writer is not None:
                self._save(state, losses)
            raise PreemptionStop(self.guard.reason or "requested")


def model_config_from(config: TrainConfig, data: CorpusData) -> Code2VecConfig:
    return Code2VecConfig(
        terminal_count=len(data.terminal_vocab),
        path_count=len(data.path_vocab),
        label_count=len(data.label_vocab),
        terminal_embed_size=config.terminal_embed_size,
        path_embed_size=config.path_embed_size,
        encode_size=config.encode_size,
        dropout_prob=config.dropout_prob,
        angular_margin_loss=config.angular_margin_loss,
        angular_margin=config.angular_margin,
        inverse_temp=config.inverse_temp,
        dtype=jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32,
        use_pallas=config.use_pallas,
        pallas_block_b=config.pallas_block_b,
        pallas_impl=config.pallas_impl,
        pallas_dma_depth=config.pallas_dma_depth,
        pallas_chunk_l=config.pallas_chunk_l,
        pallas_softmax=config.pallas_softmax,
        # --max_contexts 0: widths above the base ladder top are longbag
        # shapes — the model forces them through the fused kernel's
        # chunked softmax (bounded VMEM) when Pallas is on
        longbag_width=(
            config.max_path_length if config.max_contexts == 0 else 0
        ),
        table_dtype=config.table_dtype,
        attn_impl=config.attn_impl,
        encoder_impl=config.encoder_impl,
        embed_grad=config.embed_grad,
        # pad table/head vocab dims so they shard evenly over the model axis
        # (a few dummy rows on a 360k-row table cost nothing; indivisible
        # dims would otherwise silently replicate — parallel.shardings);
        # explicit --vocab_pad_multiple pins shapes across mesh reconfigs
        vocab_pad_multiple=config.vocab_pad_multiple or max(config.model_axis, 1),
    )


def dummy_batch(config: TrainConfig) -> dict[str, np.ndarray]:
    """Shape-only batch for model init; avoids building a real epoch (which
    can be empty, e.g. a variable-task item with no @var aliases)."""
    return {
        "ids": np.zeros(config.batch_size, np.int64),
        "starts": np.zeros((config.batch_size, config.max_path_length), np.int32),
        "paths": np.zeros((config.batch_size, config.max_path_length), np.int32),
        "ends": np.zeros((config.batch_size, config.max_path_length), np.int32),
        "labels": np.zeros(config.batch_size, np.int32),
        "example_mask": np.ones(config.batch_size, np.float32),
    }


def build_mesh(config: TrainConfig):
    """The (data, model, ctx) mesh from the config axes, with the validity
    checks; None when every axis is 1. Shared by train() and the export
    pass so both build identical layouts."""
    if config.data_axis * config.model_axis * config.context_axis <= 1:
        return None
    from code2vec_tpu.parallel.mesh import make_mesh

    if config.use_pallas and config.context_axis > 1:
        # batch/model sharding composes with the kernels (they carry
        # custom_partitioning rules that shard the batch dim), but a
        # ctx-sharded bag needs the streaming-softmax decomposition
        # (parallel.context) which none of the Pallas kernels implement
        raise ValueError(
            "use_pallas with context_axis > 1 is not supported: every "
            "Pallas kernel variant (--pallas_impl pool_only | gather_split "
            "| fused | auto) pools the whole bag per device; drop "
            "--use_pallas (and its --pallas_impl/--pallas_block_b/"
            "--pallas_dma_depth knobs) to use the XLA path (default) for "
            "context parallelism"
        )
    if config.batch_size % config.data_axis:
        raise ValueError(
            f"batch_size {config.batch_size} not divisible by "
            f"data_axis {config.data_axis}"
        )
    if config.max_path_length % config.context_axis:
        raise ValueError(
            f"max_path_length {config.max_path_length} not divisible by "
            f"context_axis {config.context_axis}"
        )
    mesh = make_mesh(
        data=config.data_axis,
        model=config.model_axis,
        ctx=config.context_axis,
    )
    if mesh.size < jax.device_count():
        logger.warning(
            "mesh uses %d of %d devices — raise data_axis/model_axis/"
            "context_axis to use the whole slice",
            mesh.size,
            jax.device_count(),
        )
    return mesh


def class_weights_from(config: TrainConfig, data: CorpusData) -> jnp.ndarray:
    """1/freq over the de-facto-uniform freq table by default (reference
    behavior, main.py:129-130 + SURVEY.md §2.2); true inverse-occurrence or
    unweighted as opt-ins."""
    if config.class_weighting == "reference":
        freq = np.asarray(data.label_vocab.freq_list(), np.float32)
    elif config.class_weighting == "occurrence":
        freq = np.asarray(data.label_vocab.occurrence_list(), np.float32)
    elif config.class_weighting == "none":
        freq = np.ones(len(data.label_vocab), np.float32)
    else:
        raise ValueError(f"unknown class_weighting: {config.class_weighting!r}")
    return jnp.asarray(1.0 / np.maximum(freq, 1.0))




def _manifest_costs(config, model_config, bucket_ladder) -> dict:
    """Static cost block for the run manifest: one analytic fwd+bwd
    record per train-step variant (ladder rung at the configured batch
    size), plus the device peak MFU is measured against."""
    from code2vec_tpu.obs import costs as obs_costs

    kind = obs_costs.detect_device_kind()
    widths = (
        list(bucket_ladder) if bucket_ladder else [config.max_path_length]
    )
    per_width = {}
    for width in widths:
        fwd = obs_costs.analytic_forward_cost(
            config.batch_size, width,
            terminal_embed=model_config.terminal_embed_size,
            path_embed=model_config.path_embed_size,
            encode=model_config.encode_size,
            labels=model_config.padded(model_config.label_count),
        )
        per_width[str(width)] = obs_costs.train_step_cost(fwd)
    return {
        "device_kind": kind,
        "peak_flops_per_s": obs_costs.peak_flops(kind),
        "cost_source": "analytic",
        "train_step": per_width,
    }


def _train_pass(
    config: TrainConfig,
    state,
    train_step,
    batches,
    to_device,
    profiler: StepProfiler | None = None,
    tracer=None,
    epoch: int | None = None,
    step_hook: _EpochCursorHook | None = None,
    loss_offset: float = 0.0,
    step_flops=None,
):
    """One epoch of train steps over the host pipeline; returns
    ``(state, train_loss)``.

    ``config.prefetch_batches > 0`` feeds the steps from the background
    double-buffered producer (train/prefetch.py): batch construction and
    the ``to_device`` transfer run ahead of compute, with identical batches
    in the identical order — the loss trajectory is bitwise that of the
    synchronous path. ``profiler`` attributes per-step wall time into
    host-build / H2D / compute buckets on its sampled steps. Tracing: the
    whole pass is one ``train_pass`` span; step 0 (the compile-bearing
    step) and the profiler-sampled steps get ``train_step`` spans — never
    every step, so a 16k-step epoch doesn't flood the trace.

    ``step_hook`` (elastic training) is called after every step — it owns
    mid-epoch checkpointing and may raise :class:`PreemptionStop`, which
    unwinds through the stream context (producer joined, generator
    closed). ``loss_offset`` seeds the loss accumulation on a mid-epoch
    resume: the pass covers only the un-replayed tail of the epoch, and
    the total is accumulated in the uninterrupted run's exact order.
    """
    tracer = tracer or get_tracer()
    losses: list = []  # device scalars; ONE host sync after the last step
    step = 0
    with tracer.span("train_pass", category="train", epoch=epoch):
        with device_batches(
            batches, to_device, config.prefetch_batches, profiler,
            drain_on_preemption=step_hook is not None,
        ) as stream:
            for host_batch, device_batch in stream:
                sampled = profiler is not None and profiler.sampled(step)
                span = (
                    tracer.span("train_step", category="train", step=step)
                    if step == 0 or sampled
                    else _NO_SPAN
                )
                if sampled and losses:
                    # drain the ≤W-step dispatch backlog before timing:
                    # otherwise compute_ms for a sampled step would also
                    # cover prior in-flight steps' device work
                    jax.block_until_ready(losses[-1])
                with span:
                    t0 = time.perf_counter()
                    state, loss = train_step(state, device_batch)
                    if step == 0 or sampled:
                        # deliberate sampled-only sync: the compile span
                        # and compute_ms must cover the device work, which
                        # async dispatch would otherwise hide
                        jax.block_until_ready(loss)
                if sampled:
                    # the analytic step cost at this batch's exact shape —
                    # host-side arithmetic on a sampled step only, feeding
                    # the profiler's mfu column
                    flops = (
                        step_flops(
                            int(host_batch["paths"].shape[0]),
                            int(host_batch["paths"].shape[1]),
                        )
                        if step_flops is not None
                        else None
                    )
                    profiler.record_compute(
                        step, (time.perf_counter() - t0) * 1e3, flops=flops
                    )
                losses.append(loss)
                if step >= _LOSS_SYNC_WINDOW:
                    # wait on the loss from W steps AGO — host stays ≤W
                    # steps ahead of the device without ever idling it
                    jax.block_until_ready(losses[step - _LOSS_SYNC_WINDOW])
                faultinject.fault_point("train_step", step=step, epoch=epoch)
                if step_hook is not None:
                    step_hook.after_step(
                        state, losses, int(host_batch["paths"].shape[1])
                    )
                step += 1
        if step_hook is not None:
            # the stream may have ended EARLY (the prefetch producer drains
            # on SIGTERM); re-check before this pass is treated as complete
            step_hook.after_pass(state, losses)
    if profiler is not None:
        # the hook's count is epoch-GLOBAL (it starts from the replayed
        # cursor): a mid-epoch resume's tail-only `step` would otherwise
        # shrink the sampling stride for every later full epoch
        profiler.observe_epoch_length(
            step if step_hook is None else step_hook.steps
        )
    # sequential float64 accumulation, seeded with the resumed partial sum
    # — bitwise-identical to the old per-step `train_loss += float(loss)`
    # trajectory of an uninterrupted epoch
    train_loss = float(sum(map(float, jax.device_get(losses)), loss_offset))
    return state, train_loss


def train(
    config: TrainConfig,
    data: CorpusData,
    out_dir: str | None = None,
    vectors_path: str | None = None,
    test_result_path: str | None = None,
    sinks: tuple[MetricSink, ...] = (logging_sink,),
    report_fn: Callable[[int, float], None] | None = None,
    initial_state=None,
    train_step=None,
    eval_step=None,
    profile_dir: str | None = None,
    events: EventLog | None = None,
    tracer=None,
) -> TrainResult:
    """Run the full training loop on a loaded corpus.

    ``initial_state``/``train_step``/``eval_step`` may be injected (the HPO
    driver reuses jitted steps across trials; the parallel driver passes
    sharded variants).

    ``events``/``tracer`` wire the run into the telemetry subsystem
    (``code2vec_tpu.obs``; the CLI builds them from ``--events_dir`` /
    ``--trace_dir``). Defaults: a dispatch-only EventLog (no file) — the
    sinks are ALWAYS driven as consumers of the event stream — and the
    process-wide tracer (a no-op unless one was installed). The caller
    owns closing/exporting both. Sinks exposing ``close()`` (e.g.
    ``tensorboard_sink``) ARE closed by this function's finally block —
    pass close-less sinks to share one across train() calls.
    """
    # task selection is fixed at corpus-load time; catch silent mismatches
    # between the config's task flags and what the corpus was loaded with
    if config.infer_method_name != data.infer_method or (
        config.infer_variable_name != data.infer_variable
    ):
        raise ValueError(
            "task flags disagree with the loaded corpus: config has "
            f"infer_method_name={config.infer_method_name}, "
            f"infer_variable_name={config.infer_variable_name} but the corpus "
            f"was loaded with infer_method={data.infer_method}, "
            f"infer_variable={data.infer_variable}; pass matching flags to "
            "load_corpus"
        )

    # quantized tables are a serving/eval storage mode: training updates
    # f32 master weights only (the step contract enforces the same at
    # trace time — train/step.py:STEP_STATE_CONTRACT). export_only /
    # predict accept --table_dtype; the TRAIN loop never does.
    if config.table_dtype != "f32":
        raise ValueError(
            f"table_dtype={config.table_dtype!r} is not trainable: "
            "quantized (int8/bf16) tables serve eval/predict/export "
            "forwards; training keeps f32 master weights (the touched-rows "
            "optimizer isolates table updates). Drop --table_dtype for "
            "training, or pass it to predict/--export_only"
        )
    # pin the schedule cache for this process before any step traces so a
    # --pallas_impl auto run consults the configured file at trace time
    if config.autotune_cache:
        from code2vec_tpu.ops.autotune import get_cache

        get_cache(config.autotune_cache)

    if events is None:
        events = EventLog()  # dispatch-only: sinks still ride the stream
    if tracer is None:
        tracer = get_tracer()
    health = RuntimeHealth()
    recompile_detector = RecompileDetector(events=events, health=health)

    # elastic training: the fault plan (tests/drills), the SIGTERM guard
    # (finish the in-flight step, save, exit 0 — train/preempt.py), and
    # the save orchestrator. Each train() call (re)installs the plan from
    # its own config/env with counters at zero — a plan never leaks from
    # one run into the next
    faultinject.install_plan(
        config.fault_plan or os.environ.get(faultinject.ENV_VAR)
    )
    guard = preemption_guard()
    guard.clear()
    writer = (
        CheckpointWriter(
            out_dir,
            async_save=config.async_checkpoint,
            events=events,
            tracer=tracer,
        )
        if out_dir is not None
        else None
    )

    # length-aware bucketed batching: resolve the static ladder of bag
    # widths once at startup — explicit --bucket_ladder, or a geometric
    # ladder derived from the corpus length histogram (the per-method
    # counts; the variable task reuses it, its rows are subsets of method
    # bags). The ladder is the run's whole compile budget: the recompile
    # detector below is budgeted to len(ladder) expected compiles per step
    # function, so the bucket shapes count as warmup, not shape churn.
    bucket_ladder: tuple[int, ...] | None = None
    if config.bucket_ladder and not config.bucketed:
        raise ValueError(
            "--bucket_ladder was given but --bucketed is off — the ladder "
            "would be silently ignored; add --bucketed or drop the ladder"
        )
    if config.bucketed:
        bucket_ladder = parse_bucket_ladder(
            config.bucket_ladder, config.max_path_length
        )
        if bucket_ladder is None:
            bucket_ladder = derive_bucket_ladder(
                np.diff(data.row_splits), config.max_path_length
            )
        logger.info(
            "bucketed batching: ladder %s (%d step compiles expected)",
            list(bucket_ladder),
            len(bucket_ladder),
        )

    # --max_contexts 0: the longbag arm — nothing is truncated. The ladder
    # grows rungs above max_path_length (multiples of pallas_chunk_l,
    # derived from the corpus length histogram) and epoch builds cap at the
    # TOP rung, so every context of every method is fed; widths above the
    # base top stream through the fused kernel's chunked softmax (the
    # model's longbag_width dispatch) in bounded VMEM.
    if config.max_contexts > 0:
        raise ValueError(
            "--max_contexts accepts -1 (follow --max_path_length) or 0 "
            "(unbounded longbag mode); for a bounded cap set "
            "--max_path_length itself — two knobs for one cap would drift"
        )
    bag_width = config.max_path_length  # the epoch-build context cap
    if config.max_contexts == 0:
        if not config.bucketed:
            raise ValueError(
                "--max_contexts 0 (unbounded bags) requires --bucketed: "
                "without a ladder every example would pad to the longest "
                "bag in the corpus"
            )
        if config.device_epoch:
            raise ValueError(
                "--max_contexts 0 does not compose with --device_epoch "
                "(device staging samples at fixed ladder widths resolved "
                "before the longbag rungs existed); drop one flag"
            )
        if data.shard is not None:
            raise ValueError(
                "--max_contexts 0 with a host-sharded corpus would derive "
                "a different longbag ladder on every host (each sees only "
                "its shard's length histogram); load the corpus unsharded "
                "or pin the full ladder explicitly in a follow-up run"
            )
        lengths, weights = np.unique(
            np.diff(data.row_splits), return_counts=True
        )
        longbag_rungs = derive_longbag_ladder(
            lengths, weights, config.max_path_length,
            chunk_l=config.pallas_chunk_l,
        )
        if longbag_rungs:
            bucket_ladder = tuple(bucket_ladder) + longbag_rungs
            bag_width = bucket_ladder[-1]
            logger.info(
                "longbag: ladder extended to %s (rungs above %d stream "
                "through the chunked softmax; zero truncation)",
                list(bucket_ladder), config.max_path_length,
            )
        else:
            logger.info(
                "longbag: no bag exceeds max_path_length %d — ladder "
                "unchanged, truncation already zero",
                config.max_path_length,
            )

    np_rng = np.random.default_rng(config.random_seed)
    jax_rng = jax.random.PRNGKey(config.random_seed)

    if data.shard is None:
        train_idx, test_idx = split_items(data.n_items, np_rng)
        global_train = global_test = None
    else:
        # host-sharded corpus: every host computes the identical seeded
        # GLOBAL split, then keeps its round-robin share as local rows —
        # so the train/test membership of any method is host-independent
        global_train, global_test = split_items(data.global_n_items, np_rng)
        train_idx = data.local_rows_of_global(global_train)
        test_idx = data.local_rows_of_global(global_test)
    logger.info("train item size: %d", len(train_idx))
    logger.info("test item size: %d", len(test_idx))
    logger.info(
        "OOV rate: %s",
        oov_rate(data, train_idx, test_idx, exact=config.eval_method == "exact"),
    )

    # corpus-static truncation accounting (method-task row geometry): the
    # fraction of real contexts the per-example cap drops. The subsample
    # redraws per epoch but the capped LOSS is pure geometry, so one
    # computation serves every epoch's metrics/gauge; --max_contexts 0
    # drives it to exactly 0 (the longbag acceptance bar).
    truncated_ctx_fraction = None
    if data.infer_method and len(train_idx):
        truncated_ctx_fraction = truncated_fraction_of_counts(
            np.diff(data.row_splits)[train_idx], bag_width
        )
        if truncated_ctx_fraction > 0:
            logger.info(
                "context cap %d truncates %.2f%% of real train contexts "
                "(--max_contexts 0 feeds them all)",
                bag_width, 100.0 * truncated_ctx_fraction,
            )

    model_config = model_config_from(config, data)
    class_weights = class_weights_from(config, data)

    if out_dir is not None and jax.process_index() == 0:
        # persist what single-source inference (code2vec_tpu.predict)
        # needs beyond the checkpoint: model dims/flags + the label vocab
        from code2vec_tpu.predict import save_inference_meta

        # fixed-L runs still record a corpus-derived ladder: the serving
        # layer keys its AOT executables by these widths and should not
        # need the corpus (or a live-request histogram) to learn them
        save_inference_meta(
            out_dir, config, model_config, data,
            bucket_ladder=bucket_ladder
            or derive_bucket_ladder(
                np.diff(data.row_splits), config.max_path_length
            ),
        )

    state = initial_state
    if state is None:
        state = create_train_state(
            config, model_config, jax_rng, dummy_batch(config)
        )

    # mesh parallelism: any axis > 1 switches to sharded steps; the step
    # math is identical (see parallel.step), XLA inserts the collectives
    mesh = build_mesh(config)
    # the event log's first line: run id, config, process identity, mesh
    # shape, device kind, package version (idempotent if the caller wrote
    # one already — e.g. the HPO driver stamps the search's BASE config).
    # Skipped for an unobserved dispatch-only log: manifest construction
    # is not free (run-id broadcast on pods, config asdict)
    if events.observed:
        events.write_manifest(
            config=config,
            mesh=mesh,
            corpus={
                "n_items": data.n_items,
                "terminal_vocab": len(data.terminal_vocab),
                "path_vocab": len(data.path_vocab),
                "label_vocab": len(data.label_vocab),
                "shard": data.shard,
            },
            # static cost model for this run's step variants: analytic
            # fwd+bwd FLOPs per ladder rung at the configured batch size,
            # and the peak the mfu column is measured against
            costs=_manifest_costs(config, model_config, bucket_ladder),
        )
    if mesh is not None:
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.parallel.step import (
            make_parallel_eval_step,
            make_parallel_train_step,
        )

        state = shard_state(mesh, state)
        if train_step is None:
            train_step = make_parallel_train_step(
                model_config, class_weights, mesh, state,
                table_update=config.table_update,
            )
        if eval_step is None:
            # host numpy batches are auto-placed by the in_shardings
            eval_step = make_parallel_eval_step(
                model_config, class_weights, mesh, state
            )

    if train_step is None:
        train_step = make_train_step(
            model_config, class_weights, table_update=config.table_update
        )
    if eval_step is None:
        eval_step = make_eval_step(model_config, class_weights)

    # recompile watch: static [B, L] shapes are the design invariant —
    # jit-cache growth after the warmup compile means shape churn is
    # silently recompiling the step (seconds each). Checked per epoch;
    # non-jitted injected steps are ignored by track(). Bucketed runs
    # legitimately compile once per ladder width, so the budget makes
    # those count as warmup while anything beyond still fires.
    expected_compiles = len(bucket_ladder) if bucket_ladder else None
    recompile_detector.track(
        "train_step", train_step, expected_compiles=expected_compiles
    )
    recompile_detector.track(
        "eval_step", eval_step, expected_compiles=expected_compiles
    )

    # multi-host feeding:
    # - replicated corpus (data.shard is None): every process builds the
    #   same full batch (epochs are seeded identically) and serves the
    #   slices its devices own;
    # - host-sharded corpus: each FEED GROUP builds only its local
    #   sub-batch of batch_size/n_groups rows from its own shard, assembled
    #   into the global array (stratified-by-group sampling, standard DDP
    #   semantics). A feed group is the processes covering the same
    #   data-axis coords (parallel.distributed.feed_groups) — with model/
    #   ctx axes inside one process that is just "one group per process",
    #   but a model axis SPANNING processes makes those processes replicas
    #   of the same rows: they must load the same shard and feed
    #   identically.
    n_hosts = jax.process_count()
    sharded_feed = data.shard is not None and n_hosts > 1
    if bucket_ladder is not None and sharded_feed and config.stream_chunk_items:
        # the global width schedule below needs random access to each
        # bucket's rows; a text stream builds chunks in item order and
        # would have to buffer unboundedly to follow it. The mmap-CSR
        # source IS random access — the out-of-core format makes the
        # 3-way composition work.
        raise ValueError(
            "--bucketed + host-sharded feeding + --stream_chunk_items: a "
            "chunked text stream cannot follow the global bucket-width "
            "schedule; convert the corpus with tools/corpus_convert.py and "
            "feed it as --corpus_format csr (mmap batches are random-"
            "access, so the combination needs no streaming), or drop one "
            "flag"
        )
    feed_batch = config.batch_size
    feed_group = 0
    n_feed_groups = 1
    if sharded_feed:
        if mesh is None:
            raise ValueError("a host-sharded corpus requires mesh axes")
        from code2vec_tpu.parallel.distributed import feed_groups

        feed_group, n_feed_groups = feed_groups(mesh)
        if data.shard != (feed_group, n_feed_groups):
            raise ValueError(
                f"corpus shard {data.shard} does not match this process's "
                f"feed group ({feed_group}, {n_feed_groups}); shard the "
                "corpus with load_corpus(shard=feed_groups(mesh)) — NOT by "
                "process index when the model/ctx axes span processes"
            )
        if config.batch_size % n_feed_groups:
            raise ValueError(
                f"batch_size {config.batch_size} not divisible by "
                f"{n_feed_groups} feed groups"
            )
        if data.infer_variable:
            # the variable task expands each method into a data-dependent
            # number of examples, so per-host step counts cannot be derived
            # from the global split alone — unsupported under sharded feed
            raise ValueError(
                "host-sharded feeding supports the method task only; load "
                "the corpus unsharded for infer_variable runs"
            )
        feed_batch = config.batch_size // n_feed_groups
        from code2vec_tpu.parallel.distributed import local_to_global_batch

        def to_device(batch):
            return local_to_global_batch(mesh, batch)
    elif mesh is not None:
        # single- or multi-process: global_batch covers both (one process
        # is a cached-sharding device_put). Explicit placement — vs letting
        # jit copy at dispatch — means the prefetch producer starts the
        # real H2D transfer ahead of compute and the profiler's h2d_ms
        # measures it instead of silently folding it into compute_ms.
        from code2vec_tpu.parallel.distributed import global_batch

        def to_device(batch):
            return global_batch(mesh, batch)
    else:
        def to_device(batch):
            return jax.device_put(batch)

    # every host must run the same number of (collective) steps; the split
    # is a random permutation, so per-group membership is hypergeometric —
    # compute the true max share from the global split (identical on every
    # host), and short groups pad with fully-masked batches up to it
    def synced_steps(global_idx: np.ndarray) -> int:
        shares = np.bincount(
            np.asarray(global_idx) % n_feed_groups, minlength=n_feed_groups
        )
        return max(-(-int(shares.max()) // feed_batch), 1)

    # bucketed x host-sharded: collective shapes must agree per step across
    # hosts, so the epoch's WIDTH SCHEDULE is agreed globally once — each
    # group's per-width batch counts are corpus-static for the method task
    # (the only task sharded feeding supports), the per-width max across
    # groups is allgathered at startup, and short groups pad with masked
    # empty batches of the scheduled width (pipeline:
    # iter_scheduled_bucketed_batches / MmapCorpusSource.scheduled_batches)
    train_width_counts = test_width_counts = None
    if sharded_feed and bucket_ladder is not None:
        from jax.experimental import multihost_utils

        def _global_width_counts(local_idx: np.ndarray) -> np.ndarray:
            local_counts = (
                data.row_splits[np.asarray(local_idx) + 1]
                - data.row_splits[np.asarray(local_idx)]
            )
            mine = bucket_batch_counts(
                np.minimum(local_counts, bucket_ladder[-1]),
                bucket_ladder, feed_batch,
            )
            every = np.asarray(
                multihost_utils.process_allgather(np.asarray(mine, np.int64))
            )
            return every.reshape(jax.process_count(), -1).max(axis=0)

        train_width_counts = _global_width_counts(train_idx)
        test_width_counts = _global_width_counts(test_idx)

    def width_schedule(width_counts: np.ndarray, epoch: int, shuffled: bool):
        """The epoch's global bucket-width sequence — identical on every
        host: per-width multiplicities from the allgathered maxima,
        interleaved by a generator seeded from (run seed, epoch) alone (the
        per-host ``np_rng`` streams diverge under sharded feeding, so the
        schedule cannot ride on them)."""
        widths = np.repeat(np.asarray(bucket_ladder), width_counts)
        if shuffled:
            srng = np.random.default_rng([config.random_seed, 0x5EED, epoch])
            widths = widths[srng.permutation(len(widths))]
        return widths

    # device-resident epochs: corpus staged to HBM once, whole chunks of
    # batches per dispatch (train/device_epoch.py). Composes with the mesh:
    # the corpus is replicated over the devices and each scanned batch is
    # sharding-constrained to the data/ctx layout, so the flagship fast path
    # scales out (SURVEY §7.4-7.5). Method and/or variable task (the
    # variable expansion is corpus-static, so it stages as rows; the
    # per-epoch @var remap runs on device), single process; multi-host
    # falls back to the host pipeline.
    if config.shard_staged_corpus and not config.device_epoch:
        raise ValueError(
            "--shard_staged_corpus shards the device-staged corpus; it "
            "requires --device_epoch"
        )
    if config.sample_prefetch and not config.device_epoch:
        raise ValueError(
            "--sample_prefetch double-buffers the device-epoch sampler; "
            "it requires --device_epoch"
        )
    device_runner = None
    sharded_train_runner = None  # (ShardedEpochRunner, ShardedStagedCorpus)
    use_device_epoch = False  # gates the epoch-loop branch for both runners
    if config.device_epoch:
        if jax.process_count() == 1:
            use_device_epoch = True
            from code2vec_tpu.train.device_epoch import (
                BucketedEpochRunner,
                BucketedShardedEpochRunner,
                EpochRunner,
                ShardedEpochRunner,
                bucket_shard_staged,
                bucket_staged,
                concat_staged,
                place_staged,
                shard_staged,
                stage_method_corpus,
                stage_variable_corpus,
            )

            if config.bucketed and not config.shard_staged_corpus:
                # one scanned sub-epoch per ladder width per epoch; each
                # bucket samples/steps at its own [B, L_b] shape
                device_runner = BucketedEpochRunner(
                    model_config,
                    class_weights,
                    config.batch_size,
                    bucket_ladder,
                    config.device_chunk_batches,
                    mesh=mesh,
                    shuffle_variable_ids=config.shuffle_variable_indexes,
                    sample_prefetch=config.sample_prefetch,
                    table_update=config.table_update,
                )
            elif not config.shard_staged_corpus:
                # the replicated runner is unused in sharded-staging mode;
                # don't build it (and its step closures) there
                device_runner = EpochRunner(
                    model_config,
                    class_weights,
                    config.batch_size,
                    config.max_path_length,
                    config.device_chunk_batches,
                    mesh=mesh,
                    shuffle_variable_ids=config.shuffle_variable_indexes,
                    sample_prefetch=config.sample_prefetch,
                    table_update=config.table_update,
                )
            corpus_placement = None
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                corpus_placement = NamedSharding(mesh, PartitionSpec())

            def stage_host(item_idx):
                # parts stay host-side; ONE device transfer at the end
                with tracer.span(
                    "stage_corpus", category="train", items=len(item_idx)
                ):
                    parts = []
                    if data.infer_method:
                        parts.append(
                            stage_method_corpus(data, item_idx, np_rng, device="host")
                        )
                    if data.infer_variable:
                        parts.append(
                            stage_variable_corpus(data, item_idx, np_rng, device="host")
                        )
                    staged = parts[0]
                    for p in parts[1:]:
                        staged = concat_staged(staged, p)
                    return staged

            def stage(item_idx):
                return place_staged(stage_host(item_idx), device=corpus_placement)

            if config.shard_staged_corpus:
                # train AND test corpora partitioned over `data` (per-
                # device HBM ~1/data_axis); eval preds come back in
                # shard-concatenation order, aligned with flat_labels().
                # --bucketed composes: each ladder bucket shards over the
                # data axis and scans at its own [B, L_b] shape
                if mesh is None:
                    raise ValueError(
                        "--shard_staged_corpus needs mesh axes "
                        "(--data_axis > 1)"
                    )
                runner_args = (
                    model_config,
                    class_weights,
                    config.batch_size,
                    bucket_ladder
                    if config.bucketed
                    else config.max_path_length,
                    config.device_chunk_batches,
                )
                runner_kw = dict(
                    mesh=mesh,
                    shuffle_variable_ids=config.shuffle_variable_indexes,
                    sample_prefetch=config.sample_prefetch,
                    table_update=config.table_update,
                )
                if config.bucketed:
                    sharded_train_runner = (
                        BucketedShardedEpochRunner(*runner_args, **runner_kw),
                        bucket_shard_staged(
                            stage_host(train_idx), bucket_ladder, mesh
                        ),
                    )
                    staged_test = bucket_shard_staged(
                        stage_host(test_idx), bucket_ladder, mesh
                    )
                else:
                    sharded_train_runner = (
                        ShardedEpochRunner(*runner_args, **runner_kw),
                        shard_staged(stage_host(train_idx), mesh),
                    )
                    # the test split shards too (it's 20% of the corpus —
                    # at the scales this flag targets, replicating it
                    # would undo much of the HBM win)
                    staged_test = shard_staged(stage_host(test_idx), mesh)
                staged_train = None
                # static for the run: fetch the shard-order labels once,
                # not once per epoch
                sharded_test_expected = staged_test.flat_labels()
            elif config.bucketed:
                staged_train = bucket_staged(
                    stage_host(train_idx), bucket_ladder,
                    device=corpus_placement,
                )
                staged_test = bucket_staged(
                    stage_host(test_idx), bucket_ladder,
                    device=corpus_placement,
                )
                device_test_expected = staged_test.host_labels()
                # pad accounting is corpus-static on device: the sampler
                # fills min(count, width) slots per row every epoch
                device_train_pad = pad_stats(
                    np.concatenate([
                        np.diff(np.asarray(jax.device_get(s.row_splits)))
                        for _, s in staged_train.buckets
                    ]) if staged_train.buckets else np.zeros(0, np.int64),
                    bucket_ladder,
                    config.batch_size,
                )
            else:
                staged_train = stage(train_idx)
                staged_test = stage(test_idx)
                device_test_expected = np.asarray(staged_test.labels)
                device_train_pad = pad_stats(
                    np.diff(np.asarray(jax.device_get(staged_train.row_splits))),
                    (config.max_path_length,),
                    config.batch_size,
                )
            logger.info(
                "device epochs: staged %d train / %d test contexts to %s",
                sharded_train_runner[1].n_contexts
                if sharded_train_runner
                else staged_train.n_contexts,
                staged_test.n_contexts,
                staged_test.contexts.devices(),
            )
        else:
            logger.warning(
                "device_epoch requested but unsupported here (multi-host); "
                "using the host pipeline"
            )

    meta = TrainMeta()
    resume_cursor: dict | None = None
    if config.resume and out_dir is not None:
        # mesh-aware restore: the checkpoint's PartitionSpecs re-bind to
        # THIS run's mesh, so a run killed on one topology resumes on
        # another (checkpoint.py "mesh-reshape restore")
        restored = restore_checkpoint(
            out_dir, state, vocab_pad_multiple=model_config.vocab_pad_multiple,
            mesh=mesh,
        )
        if restored is not None:
            state, meta = restored.state, restored.meta
            events.emit(
                "checkpoint_restored",
                slot=restored.slot,
                path=restored.path,
                step=int(jax.device_get(state.step)),
                mesh_shape=dict(mesh.shape) if mesh is not None else None,
                saved_mesh_shape=restored.saved_mesh_shape,
                resharded=restored.resharded,
            )
            logger.info("resumed from epoch %d (best_f1=%s)", meta.epoch, meta.best_f1)
            resume_cursor, meta.cursor = meta.cursor, None
            if resume_cursor is not None and sharded_feed:
                # the cursor records ONE host RNG state (process 0's), but
                # each feed group draws its own stream — honoring it would
                # silently desynchronize the hosts' epochs
                logger.warning(
                    "ignoring the checkpoint's data cursor under host-"
                    "sharded feeding; resuming at the epoch boundary"
                )
                resume_cursor = None
            if resume_cursor is not None:
                cursor_step = int(resume_cursor.get("step", 0))
                if use_device_epoch and cursor_step > 0:
                    raise ValueError(
                        "the checkpoint carries a mid-epoch cursor (a host-"
                        "pipeline save), which --device_epoch cannot replay; "
                        "resume without --device_epoch, or restart from an "
                        "epoch-boundary checkpoint"
                    )
                cursor_batch = int(
                    resume_cursor.get("feed_batch", feed_batch)
                )
                if cursor_step > 0 and cursor_batch != feed_batch:
                    raise ValueError(
                        f"the mid-epoch cursor was saved at batch size "
                        f"{cursor_batch} but this run feeds {feed_batch} "
                        "rows per batch — the replay would skip the wrong "
                        "examples; resume with the original batch size (the "
                        "batching config changed since the checkpoint was "
                        "saved), or restart without --resume"
                    )
                # the cursor's RNG state is the interrupted epoch's START
                # state: everything that epoch streams (context subsample,
                # batch order, bucket plan) is a pure function of it
                np_rng.bit_generator.state = resume_cursor["np_rng_state"]
                jax_rng = jnp.asarray(resume_cursor["jax_rng"], jnp.uint32)
                if int(resume_cursor.get("step", 0)) > 0:
                    logger.info(
                        "mid-epoch resume: replaying epoch %d to batch %d",
                        resume_cursor["epoch"], resume_cursor["step"],
                    )
    elif out_dir is not None:
        # fresh run: clear any checkpoints from a previous run in the same
        # model_path (the reference likewise overwrites its model file,
        # main.py:231) — otherwise a stale periodic `last_N` save could
        # outrank this run's `step_N` saves at a later --resume
        clear_checkpoints(out_dir)

    # recorded with every save so restore can validate table shapes; also
    # refreshes metas from checkpoints that predate the field
    meta.vocab_pad_multiple = model_config.vocab_pad_multiple

    # step-time attribution (train/prefetch.py): the host-pipeline loops
    # fence ~--profile_steps train steps of each epoch — the first N on
    # epoch 0, then strided across the whole epoch once its length is
    # known, so tail steps are attributable too; device-epoch runs
    # dispatch whole chunks, so the per-step host/H2D/compute split does
    # not apply there
    profiler = None
    step_flops = None
    if config.profile_steps > 0:
        if use_device_epoch:
            logger.warning(
                "--profile_steps attributes the host input pipeline; "
                "device-epoch mode dispatches fused chunks and is not "
                "profiled per step"
            )
        else:
            profiler = StepProfiler(config.profile_steps)
            # MFU on the sampled steps: analytic fwd+bwd FLOPs at each
            # sampled batch's exact shape over the per-device-kind peak
            from code2vec_tpu.obs import costs as obs_costs

            profiler.peak_flops = obs_costs.peak_flops(
                obs_costs.detect_device_kind()
            )

            def step_flops(batch, width, _mc=model_config):
                fwd = obs_costs.analytic_forward_cost(
                    batch, width,
                    terminal_embed=_mc.terminal_embed_size,
                    path_embed=_mc.path_embed_size,
                    encode=_mc.encode_size,
                    labels=_mc.padded(_mc.label_count),
                )
                return obs_costs.train_step_cost(fwd)["flops"]

    if config.checkpoint_every_steps:
        if sharded_feed:
            raise ValueError(
                "--checkpoint_every_steps does not compose with host-sharded "
                "feeding: the mid-epoch cursor records one host RNG state, "
                "but each feed group draws its own stream; use "
                "--checkpoint_cycle (epoch-boundary saves) instead"
            )
        if use_device_epoch:
            logger.warning(
                "--checkpoint_every_steps is a host-pipeline feature; "
                "device-epoch runs dispatch whole chunks and save at epoch "
                "boundaries only"
            )

    f1 = 0.0
    start_epoch = meta.epoch
    epoch = start_epoch
    epochs_completed = 0
    # sinks consume the SAME event stream the JSONL log records, so the
    # two can never disagree. Subscribed HERE — immediately before the
    # try whose finally unsubscribes — so no exception path can leave the
    # consumer attached (a shared EventLog across HPO trials must not
    # accumulate duplicate consumers); every sink-visible event (epoch /
    # best_f1) is emitted inside the loop below
    sinks_on_stream = events.subscribe(sink_consumer(sinks))
    # a caller-supplied tracer must serve the WHOLE stack: the deeper
    # layers (pipeline builds, the prefetch producer, recompile marks)
    # fetch the process-wide tracer, so install it for the loop — every
    # get_tracer()-dependent span in train() fires inside it — and
    # restore in the same finally (no exception path can leak the
    # install). The CLI pre-installs, making this a no-op there.
    restore_tracer = tracer is not get_tracer()
    previous_tracer = set_tracer(tracer) if restore_tracer else None
    # host epoch feeding goes through ONE BatchSource per split
    # (data/pipeline.py): the factory picks in-RAM, streaming, or
    # mmap-gather per the corpus backing and flags, and the epoch loop
    # below no longer cares which variant it got — bucketing, prefetch,
    # sharded lockstep padding, and mid-epoch resume compose with all of
    # them through the same four protocol points
    train_source = test_source = None
    feed_pool = None
    if config.feed_workers < 0:
        raise ValueError(
            f"--feed_workers must be >= 0, got {config.feed_workers}"
        )
    if config.feed_workers:
        # loud rejects for the non-composable paths: the parallel feed
        # executes host batch PLANS, so anything without a host batch
        # stream (device_epoch) or whose rng draws can't be planned ahead
        # (the variable expansion) or whose lockstep schedule pads
        # per-host (sharded feeding) must fail at startup, not mid-epoch
        if config.device_epoch:
            raise ValueError(
                "--feed_workers parallelizes the HOST batch pipeline; "
                "--device_epoch samples batches on device and has no host "
                "builds to parallelize — drop one flag"
            )
        if data.infer_variable:
            raise ValueError(
                "--feed_workers supports the method task only: the "
                "variable-name expansion interleaves per-item rng draws "
                "with data-dependent row counts, so its builds cannot be "
                "planned ahead for workers; run variable-task corpora "
                "with --feed_workers 0"
            )
        if sharded_feed:
            raise ValueError(
                "--feed_workers does not compose with host-sharded "
                "feeding (the lockstep width schedule pads per host); "
                "each host already builds only 1/n_groups of every batch "
                "— drop --feed_workers or feed unsharded"
            )
    if not use_device_epoch:
        source_kw = dict(
            ladder=bucket_ladder,
            stream_chunk_items=config.stream_chunk_items,
            shuffle_variable_indexes=config.shuffle_variable_indexes,
        )
        # bag_width, not max_path_length: in longbag mode the epoch builds
        # cap at the TOP rung, so nothing is truncated
        train_source = make_batch_source(
            data, train_idx, feed_batch, bag_width, **source_kw
        )
        test_source = make_batch_source(
            data, test_idx, feed_batch, bag_width, **source_kw
        )
        if config.feed_workers:
            # parallel host ingest (data/parallel_feed.py): one worker
            # pool + shared-memory arena serves both splits; the wrappers
            # keep the BatchSource protocol, so everything downstream
            # (prefetch, resume replay, pad accounting) is unchanged
            from code2vec_tpu.data.parallel_feed import FeedPool, ParallelFeed

            feed_pool = FeedPool(
                data,
                config.feed_workers,
                feed_batch,
                int(train_source.ladder[-1]),
                events=events,
                health=health,
                tracer=tracer,
            )
            train_source = ParallelFeed(train_source, feed_pool)
            test_source = ParallelFeed(test_source, feed_pool)
            logger.info(
                "parallel host ingest: %d feed workers, %d arena slots, "
                "%s delivery",
                feed_pool.n_workers, feed_pool.slots,
                feed_pool.deliver_mode(),
            )
        logger.info(
            "host feed: %s (ladder %s)",
            type(train_source).__name__, list(train_source.ladder),
        )
    def _boundary_cursor(next_epoch: int) -> dict:
        """Epoch-boundary cursor: step 0 plus the CURRENT RNG states — the
        state the next epoch will start from — so even a boundary resume
        continues the uninterrupted run's stream bitwise."""
        return _data_cursor(
            next_epoch, 0, feed_batch, _rng_state(np_rng), jax_rng
        )

    # installed HERE — immediately before the try whose finally restores
    # it — so none of the setup/validation raises above can leave the
    # handler (which only sets a flag nobody would poll) installed in a
    # long-lived host process. A SIGTERM during setup takes the default
    # disposition: terminate, leaving the previous checkpoint intact —
    # the same state any setup crash leaves
    previous_sigterm = install_sigterm_handler()
    try:
        for epoch in range(start_epoch, config.max_epoch):
            faultinject.fault_point("epoch_start", epoch=epoch)
            # epoch boundaries are deterministic collective points, so the
            # check is process-coordinated (multi-process runs must not
            # split into "saves and exits" vs "trains another epoch")
            if coordinated_stop(guard):
                # preempted between epochs (or in a mode without per-step
                # hooks, e.g. device_epoch): checkpoint at the boundary
                # and exit cleanly
                if writer is not None and report_fn is None:
                    meta.epoch = epoch
                    # a resume cursor still pending here (SIGTERM landed
                    # during restore/pipeline setup, before the first
                    # resumed epoch consumed it) MUST be re-persisted:
                    # `state` holds that cursor's mid-epoch arrays, and a
                    # step-0 boundary cursor would make the next resume
                    # replay the epoch's head on top of them
                    meta.cursor = (
                        resume_cursor
                        if resume_cursor is not None
                        else _boundary_cursor(epoch)
                    )
                    with tracer.span(
                        "checkpoint_save", category="checkpoint",
                        epoch=epoch, slot="last",
                    ):
                        writer.save(state, meta, "last", epoch=epoch)
                raise PreemptionStop(guard.reason or "requested")
            if profile_dir is not None and epoch == start_epoch + 1:
                jax.profiler.start_trace(profile_dir)
            epoch_start = time.perf_counter()
            if profiler is not None:
                profiler.reset()

            # mid-epoch resume bookkeeping: the host RNG state everything
            # this epoch streams derives from (recorded in every mid-epoch
            # cursor), plus the replayed cursor's offsets on the first
            # resumed epoch
            epoch_rng_state = _rng_state(np_rng)
            skip = 0
            loss_offset = 0.0
            cursor_widths: dict | None = None
            if resume_cursor is not None and epoch == start_epoch:
                skip = int(resume_cursor.get("step", 0))
                loss_offset = float(
                    resume_cursor.get("partial_train_loss", 0.0)
                )
                cursor_widths = resume_cursor.get("bucket_positions") or None
                resume_cursor = None

            def _replay(batches, skip=skip, widths=cursor_widths):
                """Replay the epoch stream to the cursor: the iterator is a
                pure function of the epoch arrays + the RNG state restored
                above, so discarding the first `skip` batches puts it
                bitwise where the interrupted run stopped (host batch
                builds only; no device work)."""
                with tracer.span(
                    "resume_replay", category="train", epoch=epoch, skip=skip,
                ):
                    return skip_batches(batches, skip, expect_widths=widths)

            step_hook = None
            if not use_device_epoch:
                step_hook = _EpochCursorHook(
                    writer=writer if report_fn is None else None,
                    meta=meta,
                    epoch=epoch,
                    epoch_rng_state=epoch_rng_state,
                    jax_rng=jax_rng,
                    guard=guard,
                    feed_batch=feed_batch,
                    every_steps=config.checkpoint_every_steps,
                    skip=skip,
                    loss_offset=loss_offset,
                    widths=cursor_widths,
                    tracer=tracer,
                )

            train_epoch = None  # host epoch arrays, built lazily in device mode
            test_epoch = None
            pad_efficiency = None  # real contexts / padded slots this epoch
            if use_device_epoch:
                jax_rng, train_key, eval_key = jax.random.split(jax_rng, 3)
                if sharded_train_runner is not None:
                    runner, staged = sharded_train_runner
                    with tracer.span(
                        "train_pass", category="train", epoch=epoch,
                        mode="device_epoch",
                    ):
                        state, train_loss, _ = runner.run_train_epoch(
                            state, staged, np_rng, train_key
                        )
                    with tracer.span(
                        "eval_pass", category="eval", epoch=epoch,
                        mode="device_epoch",
                    ):
                        test_loss, preds, _ = runner.run_eval_epoch(
                            state, staged_test, eval_key
                        )
                    expected = sharded_test_expected
                else:
                    with tracer.span(
                        "train_pass", category="train", epoch=epoch,
                        mode="device_epoch",
                    ):
                        state, train_loss, _ = device_runner.run_train_epoch(
                            state, staged_train, np_rng, train_key
                        )
                    with tracer.span(
                        "eval_pass", category="eval", epoch=epoch,
                        mode="device_epoch",
                    ):
                        test_loss, preds, _ = device_runner.run_eval_epoch(
                            state, staged_test, eval_key
                        )
                    # staged labels: per-EXAMPLE (one per @var alias in
                    # the variable task), not per-item; fetched once at
                    # staging (bucketed stagings concatenate per bucket)
                    expected = device_test_expected
                    real, slots = device_train_pad
                    pad_efficiency = real / slots if slots else 1.0
                accuracy, precision, recall, f1 = evaluate(
                    config.eval_method, expected, preds, data.label_vocab
                )
            else:
                # the unified host path: whatever variant the factory
                # picked (in-RAM fixed-L/bucketed, streaming, mmap-gather),
                # the stream is a pure function of np_rng's state here —
                # which is what makes _replay (mid-epoch resume) and the
                # prefetcher compose with all of them. Sources build
                # lazily at first pull, so the host RNG draw order is
                # bitwise the historical one.
                def sharded_wrap(batches, global_idx):
                    """Host-sharded lockstep (fixed-L): pad the short
                    groups with masked template batches. The bucketed
                    variant pads inside scheduled_batches instead."""
                    if not sharded_feed:
                        return batches
                    return pad_batch_stream(
                        batches,
                        synced_steps(global_idx),
                        empty_batch(feed_batch, config.max_path_length),
                    )

                if sharded_feed and bucket_ladder is not None:
                    train_batches = train_source.scheduled_batches(
                        np_rng,
                        width_schedule(train_width_counts, epoch, True),
                    )
                else:
                    train_batches = sharded_wrap(
                        train_source.batches(np_rng, shuffle=True),
                        global_train,
                    )
                if skip:
                    train_batches = _replay(train_batches)
                state, train_loss = _train_pass(
                    config, state, train_step, train_batches, to_device,
                    profiler, tracer=tracer, epoch=epoch,
                    step_hook=step_hook, loss_offset=loss_offset,
                    step_flops=step_flops,
                )
                # pad accounting comes from the source — exact corpus
                # geometry for the in-RAM/mmap variants, stream-tallied
                # for chunked streaming (which used to silently drop the
                # honesty metric)
                source_pad = train_source.pad_stats()
                if source_pad is not None:
                    real, slots = source_pad
                    pad_efficiency = real / slots if slots else 1.0

                if sharded_feed and bucket_ladder is not None:
                    # eval schedule in deterministic ladder order
                    test_batches = test_source.scheduled_batches(
                        np_rng,
                        width_schedule(test_width_counts, epoch, False),
                        shuffle=False,
                    )
                else:
                    test_batches = sharded_wrap(
                        test_source.batches(np_rng, shuffle=False),
                        global_test,
                    )
                test_loss, accuracy, precision, recall, f1 = _evaluate_batches(
                    config, data, state, eval_step, test_batches, to_device,
                    gather_processes=sharded_feed,
                    feed_group=(feed_group, n_feed_groups),
                    tracer=tracer, epoch=epoch,
                )
                # in-RAM sources expose the built epoch for the export /
                # print_sample reuse below; out-of-core sources leave these
                # None and host_epoch() builds on demand
                train_epoch = train_source.last_epoch
                test_epoch = test_source.last_epoch

            metrics = {
                "train_loss": train_loss,
                "test_loss": test_loss,
                "accuracy": accuracy,
                "precision": precision,
                "recall": recall,
                "f1": f1,
                "epoch_seconds": time.perf_counter() - epoch_start,
            }
            if pad_efficiency is not None:
                # the padding-waste gauge behind the bucketed-batching win:
                # real context slots / padded slots fed this epoch (1.0 =
                # no wasted gathers/FLOPs/HBM traffic on PAD)
                metrics["pad_efficiency"] = pad_efficiency
                health.gauge("pad_efficiency").set(pad_efficiency)
            if truncated_ctx_fraction is not None:
                # the truncation-loss gauge the longbag arm drives to 0:
                # fraction of the corpus's REAL contexts the per-example
                # cap silently drops — invisible until PR 13
                metrics["truncated_context_fraction"] = truncated_ctx_fraction
                health.gauge("truncated_context_fraction").set(
                    truncated_ctx_fraction
                )
            if profiler is not None:
                attribution = profiler.summary()
                if attribution is not None:
                    metrics.update(attribution)
                    logger.info(
                        "step-time attribution (%d sampled train steps, "
                        "stride %d): host_build %.2f ms | h2d %.2f ms | "
                        "feed_wait %.2f ms | compute %.2f ms%s",
                        attribution["profiled_steps"],
                        profiler.stride,
                        attribution["host_build_ms"],
                        attribution["h2d_ms"],
                        attribution["feed_wait_ms"],
                        attribution["compute_ms"],
                        (
                            " | mfu %.4f" % attribution["mfu"]
                            if "mfu" in attribution
                            else ""
                        ),
                    )
                for rec in profiler.per_step():
                    events.emit("step_sample", epoch=epoch, **rec)
            epochs_completed += 1
            meta.history.append({"epoch": epoch, **metrics})
            # sliced from the SAME dict the epoch event carries — a
            # renamed metric fails loudly here instead of diverging
            events.emit("eval", epoch=epoch, metrics={
                k: metrics[k]
                for k in ("test_loss", "accuracy", "precision", "recall", "f1")
            })
            # recompile check FIRST (warmup = epoch 0's expected compiles;
            # growth on any later epoch is shape churn and emits a
            # `recompile` warning event) so this epoch's own recompiles are
            # already in the health counters its epoch event reports
            recompile_detector.check(epoch)
            # the sinks consume this SAME emission (sink_consumer above) —
            # the epoch event and every sink's output share one dict.
            # memory_snapshot mirrors into the health gauges first, so the
            # health block carries current gauges + cumulative counters
            memory = memory_snapshot(health)
            events.emit(
                "epoch",
                epoch=epoch,
                metrics=metrics,
                memory=memory,
                health=health.snapshot(),
            )

            if report_fn is not None:
                report_fn(epoch, f1)  # may raise StopTraining (HPO pruning)

            def host_epoch(item_idx):
                # device mode skips host epoch builds; exports still need
                # them. Note: this draws a FRESH context subsample, so for
                # methods with more contexts than the bag size an exported
                # prediction can differ from the one behind the logged F1
                # (host mode re-runs forward on the same sampled epoch).
                # bag_width = the ladder top, so longbag exports embed the
                # UNTRUNCATED bags. The draw comes from a SIDE rng seeded
                # by (run seed, epoch) — not np_rng — so whether a path
                # materializes epochs (in-RAM reuses last_epoch; mmap/
                # streaming/parallel-feed rebuild here) cannot shift the
                # main feed stream: --feed_workers N histories stay
                # bitwise --feed_workers 0 even with exports enabled.
                return build_epoch(
                    data,
                    item_idx,
                    bag_width,
                    np.random.default_rng(
                        [config.random_seed, 0xE902, epoch]
                    ),
                    config.shuffle_variable_indexes,
                )

            if (
                epoch > 1
                and config.print_sample_cycle
                and epoch % config.print_sample_cycle == 0
                and report_fn is None
                and not sharded_feed  # samples need full-batch epochs
            ):
                if test_epoch is None:
                    test_epoch = host_epoch(test_idx)
                export_mod.print_sample(
                    data, state, eval_step, test_epoch, config.batch_size,
                    to_device,
                )

            if meta.best_f1 is None or meta.best_f1 < f1:
                events.emit("best_f1", epoch=epoch, metrics={"best_f1": f1})
                meta.best_f1 = f1
                if sharded_feed and vectors_path is not None:
                    logger.warning(
                        "vector export is not supported with host-sharded "
                        "feeding (each feed group holds 1/%d of the corpus); "
                        "run a single-host export pass on the saved checkpoint",
                        n_feed_groups,
                    )
                elif report_fn is None and vectors_path is not None:
                    if train_epoch is None:
                        train_epoch = host_epoch(train_idx)
                    if test_epoch is None:
                        test_epoch = host_epoch(test_idx)
                    with tracer.span(
                        "export_vectors", category="export", epoch=epoch
                    ):
                        export_mod.write_code_vectors(
                            data,
                            state,
                            eval_step,
                            train_epoch,
                            test_epoch,
                            config.batch_size,
                            vectors_path,
                            config.encode_size,
                            test_result_path,
                            to_device,
                        )
                save_slot = (
                    "best" if report_fn is None and out_dir is not None else None
                )
            else:
                # periodic save for preemption safety: pod slices get
                # reclaimed mid-run; best-F1-only saves (the reference's
                # policy, main.py:231) would lose every epoch since the
                # last improvement on resume. Goes to the separate "last"
                # slot so it never overwrites the best model.
                periodic = (
                    report_fn is None
                    and out_dir is not None
                    and bool(config.checkpoint_cycle)
                    and (epoch + 1) % config.checkpoint_cycle == 0
                )
                save_slot = "last" if periodic else None

            # early stop: the counter resets whenever train loss OR accuracy
            # improves (reference quirk, main.py:233-242)
            if (
                meta.last_loss is None
                or train_loss < meta.last_loss
                or meta.last_accuracy is None
                or meta.last_accuracy < accuracy
            ):
                meta.last_loss = train_loss
                meta.last_accuracy = accuracy
                meta.bad_count = 0
            else:
                meta.bad_count += 1

            if save_slot is not None:
                meta.epoch = epoch + 1
                meta.cursor = _boundary_cursor(epoch + 1)
                # the writer runs the save (sync, or snapshot + background
                # persist under --async_checkpoint) and emits the
                # checkpoint_saved event with async provenance
                with tracer.span(
                    "checkpoint_save", category="checkpoint",
                    epoch=epoch, slot=save_slot,
                ):
                    writer.save(state, meta, save_slot, epoch=epoch)

            if meta.bad_count > config.early_stop_patience:
                logger.info(
                    "early stop loss:%s, bad:%d", train_loss, meta.bad_count
                )
                if not sharded_feed:
                    if test_epoch is None:
                        test_epoch = host_epoch(test_idx)
                    export_mod.print_sample(
                        data, state, eval_step, test_epoch,
                        config.batch_size, to_device,
                    )
                break
        # drain the in-flight async save before declaring the run done —
        # a persist failure must fail the run, not vanish with the thread
        if writer is not None:
            writer.finish()
    except StopTraining:
        if writer is not None:
            writer.finish()
    except PreemptionStop as stop:
        # the checkpoint (when there is an out_dir) is already on disk —
        # drain it, report, and fall through to a NORMAL return: the
        # graceful half of the SIGTERM contract is exit code 0
        if writer is not None:
            writer.finish()
        events.emit("preempted", epoch=epoch, reason=str(stop))
        # saves happen exactly when the hook/boundary had a writer (no
        # out_dir, or an HPO trial, stops WITHOUT a checkpoint)
        logger.warning(
            "preemption (%s): %s; exiting cleanly after %d "
            "completed epochs", stop,
            "state saved"
            if writer is not None and report_fn is None
            else "NO checkpoint written (no --model_path / trial run)",
            epochs_completed,
        )
    except Exception as exc:
        try:
            events.emit(
                "error", epoch=epoch, error=f"{type(exc).__name__}: {exc}"
            )
        except Exception:  # telemetry must not mask the real failure
            logger.warning("could not emit error event", exc_info=True)
        raise
    finally:
        restore_sigterm_handler(previous_sigterm)
        if feed_pool is not None:
            feed_pool.close()
        if writer is not None:
            # exception-path drain: joins the persist thread and LOGS any
            # stored failure (finish() above already raised on the normal
            # paths; raising here would mask the unwinding exception)
            writer.close()
        if restore_tracer:
            set_tracer(previous_tracer)
        events.unsubscribe(sinks_on_stream)
        # sinks with buffered backends expose close() (tensorboard_sink:
        # the SummaryWriter's final flush must not depend on interpreter
        # exit); best-effort so one failing sink can't mask the result
        for sink in sinks:
            closer = getattr(sink, "close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:
                    logger.warning("sink close() failed", exc_info=True)
        # last: may raise (e.g. profile_dir on a full disk) — the telemetry
        # cleanup above must already have run by then
        if profile_dir is not None and epoch > start_epoch:
            jax.profiler.stop_trace()

    if epochs_completed == 0 and meta.history:
        # resumed a finished run: report the last recorded score, not 0
        f1 = meta.history[-1].get("f1", 0.0)
    return TrainResult(
        best_f1=meta.best_f1 if meta.best_f1 is not None else f1,
        final_f1=f1,
        epochs_run=epochs_completed,
        history=meta.history,
        state=state,
    )


def _evaluate_epoch(
    config: TrainConfig,
    data: CorpusData,
    state,
    eval_step,
    test_epoch,
    to_device=lambda batch: batch,
) -> tuple[float, float, float, float, float]:
    return _evaluate_batches(
        config,
        data,
        state,
        eval_step,
        iter_batches(test_epoch, config.batch_size, rng=None, pad_final=True),
        to_device,
    )


def _evaluate_batches(
    config: TrainConfig,
    data: CorpusData,
    state,
    eval_step,
    batches,
    to_device=lambda batch: batch,
    gather_processes: bool = False,
    feed_group: tuple[int, int] = (0, 1),
    tracer=None,
    epoch: int | None = None,
) -> tuple[float, float, float, float, float]:
    """Test pass: accumulate per-batch mean losses (reference semantics,
    main.py:283-284) and pooled predictions, then dispatch the matcher.

    ``gather_processes``: host-sharded feeding — each feed group saw only
    its own sub-batch rows, so expected/actual are all-gathered across
    processes before computing the (global) metrics. The group's rows sit
    at ``[group * feed, (group + 1) * feed)`` of the global prediction
    vector (feed groups are ordered by their data-axis coords, which is how
    local_to_global_batch laid the rows out). Processes replicating a group
    (a model/ctx axis spanning processes) contribute duplicate rows to the
    gather — uniform duplication, under which every pooled metric is
    unchanged.
    """
    import jax as _jax

    from code2vec_tpu.parallel.distributed import allgather_to_host

    tracer = tracer or get_tracer()
    losses: list = []  # device scalars; converted once after the pass
    expected, actual = [], []
    # the host batch rides along with its device placement so labels and
    # the example mask stay host-side (no device round-trip); prefetching
    # overlaps eval batch construction with the forward passes
    with tracer.span("eval_pass", category="eval", epoch=epoch):
        with device_batches(
            batches, to_device, config.prefetch_batches
        ) as stream:
            for batch, device_batch in stream:
                out = eval_step(state, device_batch)
                losses.append(out["loss"])
                valid = batch["example_mask"].astype(bool)
                preds = allgather_to_host(out["preds"])
                if gather_processes and len(preds) != len(valid):
                    feed = len(valid)
                    lo = feed_group[0] * feed
                    preds = preds[lo : lo + feed]
                expected.append(batch["labels"][valid])
                actual.append(preds[valid])
    # same sequential float64 accumulation the old per-batch float() did
    test_loss = float(sum(map(float, jax.device_get(losses))))
    expected = np.concatenate(expected) if expected else np.zeros(0, np.int32)
    actual = np.concatenate(actual) if actual else np.zeros(0, np.int32)
    if gather_processes and _jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # per-process row counts differ (round-robin shards); pad to the
        # max with a -1 sentinel so the allgather shapes agree, then drop
        n = len(expected)
        max_n = int(multihost_utils.process_allgather(np.asarray(n)).max())
        pad = np.full(max_n - n, -1, expected.dtype)
        expected = np.asarray(
            multihost_utils.process_allgather(
                np.concatenate([expected, pad]), tiled=True
            )
        )
        actual = np.asarray(
            multihost_utils.process_allgather(
                np.concatenate([actual, pad.astype(actual.dtype)]), tiled=True
            )
        )
        keep = expected >= 0
        expected, actual = expected[keep], actual[keep]
    accuracy, precision, recall, f1 = evaluate(
        config.eval_method, expected, actual, data.label_vocab
    )
    return test_loss, accuracy, precision, recall, f1
