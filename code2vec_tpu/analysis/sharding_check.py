"""Sharding-contract checker: PartitionSpec literals vs the declared mesh.

An invalid ``PartitionSpec`` is a run-time-only failure class — and on a
real pod it fails *late* (at the first dispatch that touches the spec, 20
minutes into staging) or worse, silently replicates. This pass
cross-validates every ``PartitionSpec``/``P`` literal in the scanned files
against the axis names the mesh module declares (``parallel/mesh.py``'s
``AXIS_* = "..."`` constants), entirely statically:

- **SC001**: a spec references an axis name the mesh does not declare
  (typo'd ``"bath"``, stale axis after a mesh refactor).
- **SC002**: the same axis appears twice in one spec — a mesh axis may
  shard at most one dimension of an array.
- **SC003**: the ``ctx`` axis appears in a spec built inside a function
  whose name marks it as a parameter/state sharding rule — the context
  axis shards the bag dimension of *batches*; partitioning vocab tables or
  encoder params over it over-partitions known-small dims.

Axis names are resolved through a small constant propagation: string
literals, ``None``, names assigned from either, ``AXIS_*`` names imported
from the mesh module, and ``a if cond else b`` over resolvable branches.
Anything else (helper-call results, arbitrary expressions) is UNKNOWN and
skipped — the checker never guesses.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from code2vec_tpu.analysis.jaxlint import (
    Finding,
    _apply_suppressions,
    _collect_imports,
    _dotted,
    _tail,
)

__all__ = [
    "declared_axes",
    "check_source",
    "check_paths",
    "validate_runtime_spec",
]

_UNKNOWN = object()


def declared_axes(mesh_source: str) -> dict[str, str]:
    """Parse the mesh module for ``AXIS_<ROLE> = "<name>"`` declarations.
    Returns ``{"AXIS_DATA": "data", ...}`` — the var names matter too
    (SC003 keys off ``AXIS_CTX``'s value)."""
    tree = ast.parse(mesh_source)
    out: dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.startswith("AXIS_")
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _axis_env(
    tree: ast.Module, imports: dict[str, str], axis_decls: dict[str, str]
) -> dict[str, frozenset]:
    """Name -> possible axis values (strings / None), or UNKNOWN-bearing.
    One flat pass over every assignment in the file — scope-blind, which
    is safe: a name bound to two different resolvable values yields the
    union, and any unresolvable binding poisons it to UNKNOWN."""
    env: dict[str, object] = {}
    # names imported from the mesh module resolve to their declared values
    for bound, target in imports.items():
        leaf = target.rsplit(".", 1)[-1]
        if leaf in axis_decls and ".mesh." in f".{target}":
            env[bound] = frozenset({axis_decls[leaf]})

    def resolve(node: ast.AST, depth: int = 0) -> object:
        if depth > 8:
            return _UNKNOWN
        if isinstance(node, ast.Constant) and (
            node.value is None or isinstance(node.value, str)
        ):
            return frozenset({node.value})
        if isinstance(node, ast.Name):
            return env.get(node.id, _UNKNOWN)
        if isinstance(node, ast.IfExp):
            a = resolve(node.body, depth + 1)
            b = resolve(node.orelse, depth + 1)
            if a is _UNKNOWN or b is _UNKNOWN:
                return _UNKNOWN
            return a | b
        return _UNKNOWN

    # iterate to a small fixed point so chained aliases resolve regardless
    # of their order in the file
    for _ in range(3):
        changed = False
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            name = node.targets[0].id
            val = resolve(node.value)
            prev = env.get(name)
            if val is _UNKNOWN:
                if name not in env:
                    env[name] = _UNKNOWN
                    changed = True
                continue
            merged = val if prev in (None, _UNKNOWN) else prev | val
            # a name with BOTH resolvable and unresolvable bindings stays
            # unknown only if it was never resolvable; prefer the union of
            # what we can see (lint-grade, not a type system)
            if prev is _UNKNOWN:
                merged = val
            if merged != prev:
                env[name] = merged
                changed = True
        if not changed:
            break
    return {k: v for k, v in env.items()}


def _spec_arg_values(node: ast.AST, env: dict) -> list[object]:
    """Possible axis values of ONE PartitionSpec positional arg: a list of
    frozensets (one per axis slot — tuple args shard one dim over several
    axes) or UNKNOWN entries."""
    if isinstance(node, ast.Tuple):
        out: list[object] = []
        for elt in node.elts:
            out.extend(_spec_arg_values(elt, env))
        return out
    if isinstance(node, ast.Constant) and (
        node.value is None or isinstance(node.value, str)
    ):
        return [frozenset({node.value})]
    if isinstance(node, ast.Name):
        return [env.get(node.id, _UNKNOWN)]
    if isinstance(node, ast.IfExp):
        a = _spec_arg_values(node.body, env)
        b = _spec_arg_values(node.orelse, env)
        if len(a) == len(b) == 1 and a[0] is not _UNKNOWN and b[0] is not _UNKNOWN:
            return [a[0] | b[0]]
        return [_UNKNOWN]
    return [_UNKNOWN]


def check_source(
    source: str,
    rel_path: str,
    axis_decls: dict[str, str],
    tree: ast.Module | None = None,
) -> list[Finding]:
    """Run SC001-SC003 over one file. ``axis_decls`` comes from
    :func:`declared_axes` (or a test-supplied mapping). Pass ``tree`` to
    reuse an already-parsed AST."""
    lines = source.splitlines()
    try:
        if tree is None:
            tree = ast.parse(source, filename=rel_path)
    except SyntaxError:
        return []  # jaxlint already reports unparseable files
    imports = _collect_imports(tree)
    env = _axis_env(tree, imports, axis_decls)
    declared = set(axis_decls.values())
    ctx_axis = axis_decls.get("AXIS_CTX")
    findings: list[Finding] = []

    # map each PartitionSpec call to its innermost enclosing function name
    # chain (SC003 context)
    parents: dict[int, str] = {}

    def tag(node: ast.AST, fn_chain: str) -> None:
        for child in ast.iter_child_nodes(node):
            chain = fn_chain
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                chain = f"{fn_chain}.{child.name}" if fn_chain else child.name
            parents[id(child)] = chain
            tag(child, chain)

    tag(tree, "")

    flagged: set[tuple[str, int, int]] = set()

    def emit(rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        # same (rule, line, col) dedup as _ModuleLint.emit: one spec
        # repeating a bad axis is one defect, not one per slot (duplicates
        # would also inflate the fingerprint's baseline count)
        if (rule, line, col) in flagged:
            return
        flagged.add((rule, line, col))
        snippet = (
            lines[line - 1].strip() if 0 < line <= len(lines) else ""
        )
        findings.append(
            Finding(
                rule=rule,
                path=rel_path,
                line=line,
                col=col,
                message=message,
                snippet=snippet,
            )
        )

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _tail(_dotted(node.func, imports)) != "PartitionSpec":
            continue
        slots = []
        for arg in node.args:
            slots.extend(_spec_arg_values(arg, env))
        definite: list[str] = []
        for values in slots:
            if values is _UNKNOWN:
                continue
            for v in values:
                if v is None:
                    continue
                if v not in declared:
                    emit(
                        "SC001",
                        node,
                        f"PartitionSpec references axis {v!r} but the mesh "
                        f"declares only {sorted(declared)}",
                    )
                if len(values) == 1:
                    definite.append(v)
        dups = {v for v in definite if definite.count(v) > 1}
        for v in sorted(dups):
            emit(
                "SC002",
                node,
                f"axis {v!r} appears {definite.count(v)} times in one "
                "PartitionSpec — a mesh axis shards at most one dimension",
            )
        chain = parents.get(id(node), "")
        if (
            ctx_axis is not None
            and ctx_axis in definite
            and any(k in chain.lower() for k in ("param", "state"))
        ):
            emit(
                "SC003",
                node,
                f"ctx axis {ctx_axis!r} in `{chain}` — parameter/state "
                "sharding rules must not partition over the context axis",
            )

    _apply_suppressions(findings, lines)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def validate_runtime_spec(
    entries, declared: Iterable[str], context: str = "spec"
) -> list[str]:
    """SC001/SC002 semantics applied to one *live* spec at restore time.

    The static pass above validates PartitionSpec literals in source; the
    mesh-reshape restore path (checkpoint.py) deserializes specs from a
    checkpoint sidecar and re-binds them to a *new* mesh — axis names that
    were valid at save time may not exist anymore. ``entries`` is the
    sidecar form (one item per dim: None, an axis name, or a list of
    names); ``declared`` is the new mesh's axis-name set. Returns
    human-readable problems (empty = valid), so the caller can fail with
    guidance instead of a late XLA sharding error.
    """
    declared = set(declared)
    problems: list[str] = []
    flat: list[str] = []
    for entry in entries:
        if entry is None:
            continue
        flat.extend(entry if isinstance(entry, (list, tuple)) else [entry])
    for axis in dict.fromkeys(flat):  # stable de-dup
        if axis not in declared:
            problems.append(
                f"{context}: axis {axis!r} is not declared by the restore "
                f"mesh (axes: {sorted(declared)}) [SC001]"
            )
        if flat.count(axis) > 1:
            problems.append(
                f"{context}: axis {axis!r} appears {flat.count(axis)} times "
                "in one PartitionSpec — a mesh axis shards at most one "
                "dimension [SC002]"
            )
    return problems


def check_paths(
    paths: Iterable[Path],
    root: Path | None = None,
    axis_decls: dict[str, str] | None = None,
    mesh_file: Path | None = None,
) -> list[Finding]:
    """Check every ``.py`` under ``paths``. Axis declarations come from
    ``axis_decls``, else from ``mesh_file``, else from the first
    ``parallel/mesh.py`` found under the scanned paths; no mesh found →
    no findings (nothing to validate against)."""
    from code2vec_tpu.analysis.jaxlint import iter_py_files

    root = Path(root) if root is not None else Path.cwd()
    files = iter_py_files(paths)
    if axis_decls is None:
        if mesh_file is None:
            mesh_file = next(
                (f for f in files if f.as_posix().endswith("parallel/mesh.py")),
                None,
            )
        if mesh_file is None:
            return []
        axis_decls = declared_axes(Path(mesh_file).read_text())
    findings: list[Finding] = []
    for file in files:
        try:
            rel = file.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = file.as_posix()
        findings.extend(check_source(file.read_text(), rel, axis_decls))
    return findings
