"""jaxlint: a Python-AST static-analysis pass for JAX footguns.

The defect classes this catches are the ones that never raise — they show up
as mystery recompiles (PR-4's weak-`int32` flax ``step`` double-compiled
every batch shape), multi-host hangs (pytree structure diverging across
processes), or a silently serialized device (host syncs in the step loop).
Pure stdlib (``ast``) — no jax import — so the CI job and the
``python -m code2vec_tpu.analysis`` runner cost parse time only.

Rules
-----
- **JX000 parse-error** (error): the file does not parse; nothing else in
  it can be checked. The SyntaxError message is the finding's snippet, so
  distinct syntax errors fingerprint separately.
- **JX001 weak-type-literal** (warning): a bare Python scalar literal
  entering jitted state/carries — ``lax.scan``/``while_loop``/``fori_loop``
  carry inits, or ``jnp.array/asarray/full`` without an explicit ``dtype``.
  Weak-typed scalars key the jit cache differently from the strong-typed
  arrays a step returns, so the same function silently compiles twice per
  shape (the PR-4 recompile bug class).
- **JX002 host-sync-in-trace** (error): ``float()``/``int()``/``bool()``
  on traced values, ``.item()``/``.tolist()``, ``np.asarray``/``np.array``
  of traced values, ``jax.device_get``, or ``print`` inside a
  ``@jit``/``scan``/``shard_map`` body. These either fail at trace time or
  freeze a trace-time constant into the compiled program.
- **JX003 tracer-branch** (error): Python ``if``/``while`` branching on a
  traced function's array arguments (``is None``/``isinstance``/shape
  attribute tests excluded — those are static). Branch on tracers with
  ``lax.cond``/``jnp.where``, or lift the value to a static argument.
- **JX004 impure-trace** (error): ``time.*``/stdlib ``random``/
  ``np.random``/``datetime.now``/``uuid``/``os.urandom`` inside a traced
  body — the value freezes at trace time and silently never changes again.
- **JX005 missing-donate** (info): a jitted function that returns an
  updated version of one of its arguments (``state = state.apply_gradients(
  ...); return state``) without ``donate_argnums`` — the old buffers stay
  live across the step, doubling peak HBM for the state.
- **JX006 set-iteration-order** (warning): iterating a ``set`` to build
  containers — set order varies across processes (hash randomization), so
  a pytree built from it can diverge across hosts (collective hangs) or
  across runs (cache-key churn). Sort first.
- **JX007 host-sync-step-loop** (warning): ``float()``/``.item()`` inside
  a loop that also invokes a step function — one device round-trip per
  step serializes host and device; accumulate device-side and sync once
  per epoch.

Each finding carries a stable fingerprint (rule | file | source-line text)
so a checked-in baseline survives unrelated line shifts. Suppress a single
line with ``# jaxlint: disable=JX001`` (or a bare ``disable`` for all
rules); suppress pre-existing debt with the baseline file
(``--write-baseline``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "RECOMPILE_HINT_RULES",
    "lint_source",
    "lint_paths",
    "fingerprint",
    "load_baseline",
    "write_baseline",
    "apply_baseline",
]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: str  # "error" | "warning" | "info"
    summary: str
    hint: str


RULES: dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            "JX000",
            "parse-error",
            "error",
            "file does not parse — nothing in it can be checked",
            "fix the SyntaxError; the file is unanalyzed until it parses",
        ),
        Rule(
            "JX001",
            "weak-type-literal",
            "warning",
            "weak-typed scalar literal entering jitted state/carries",
            "give the literal an explicit dtype (jnp.asarray(x, jnp.int32), "
            "jnp.float32(x)) so the carry/state dtype is strong and the jit "
            "cache keys stably",
        ),
        Rule(
            "JX002",
            "host-sync-in-trace",
            "error",
            "host-sync conversion of a traced value inside a traced body",
            "move the conversion outside the jitted function, or use "
            "jax.debug.print / jax.debug.callback for trace-safe inspection",
        ),
        Rule(
            "JX003",
            "tracer-branch",
            "error",
            "Python control flow branching on a traced value",
            "use jax.lax.cond / jnp.where, or mark the argument static "
            "(static_argnums) if it is genuinely compile-time",
        ),
        Rule(
            "JX004",
            "impure-trace",
            "error",
            "impure host call (time/random/uuid) inside a traced body",
            "the value freezes at trace time; thread PRNG keys / timestamps "
            "in as arguments instead",
        ),
        Rule(
            "JX005",
            "missing-donate",
            "info",
            "jitted function returns an updated argument without donation",
            "pass donate_argnums so XLA aliases the old buffers instead of "
            "keeping both copies live (peak-HBM halves for the state)",
        ),
        Rule(
            "JX006",
            "set-iteration-order",
            "warning",
            "iteration over a set feeding container construction",
            "iterate sorted(...) — set order varies across processes/runs, "
            "which diverges pytree structure (collective hangs, cache churn)",
        ),
        Rule(
            "JX007",
            "host-sync-step-loop",
            "warning",
            "per-step host sync (float()/.item()) inside a step loop",
            "append the device scalar to a list and convert once after the "
            "loop — one sync per epoch instead of one per step",
        ),
        Rule(
            "SC001",
            "undeclared-mesh-axis",
            "error",
            "PartitionSpec references an axis the mesh does not declare",
            "use one of the declared mesh axis names (parallel/mesh.py "
            "AXES) — an undeclared axis fails only at run time, on the pod",
        ),
        Rule(
            "SC002",
            "duplicate-spec-axis",
            "error",
            "the same mesh axis appears twice in one PartitionSpec",
            "a mesh axis may shard at most one dimension of an array; drop "
            "one of the duplicate references",
        ),
        Rule(
            "SC003",
            "ctx-axis-on-params",
            "warning",
            "context axis used in a parameter/state sharding rule",
            "the ctx axis shards the bag dimension of batches; vocab tables "
            "and encoder params must shard over model/data or replicate",
        ),
    )
}

# the lint rules whose defect class surfaces at run time as silent jit-cache
# growth; obs.runtime.RecompileDetector stamps these ids into its
# `recompile` warning/event so the telemetry links back to the static pass
RECOMPILE_HINT_RULES: dict[str, str] = {
    "JX001": "weak-typed scalar entering jitted state/carries (dtype churn)",
    "JX006": "set-order-dependent pytree construction (structure churn)",
}

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<ids>[A-Za-z0-9_,\s]+))?"
)

# --------------------------------------------------------------------------
# findings


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, posix
    line: int
    col: int
    message: str
    snippet: str  # stripped source line (fingerprint component)
    suppressed: bool = False
    baselined: bool = False

    @property
    def severity(self) -> str:
        return RULES[self.rule].severity

    @property
    def name(self) -> str:
        return RULES[self.rule].name

    @property
    def hint(self) -> str:
        return RULES[self.rule].hint

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "name": self.name,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "snippet": self.snippet,
            "fingerprint": fingerprint(self),
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }

    def text(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.severity}] {self.message}\n    {self.snippet}\n"
            f"    fix: {self.hint}"
        )


def fingerprint(finding: Finding) -> str:
    """Line-shift-stable identity: rule + file + the flagged source line.
    Identical lines in one file share a fingerprint; the baseline stores a
    COUNT per fingerprint, so k pre-existing occurrences stay suppressed
    while a (k+1)-th new one fails."""
    return f"{finding.rule}|{finding.path}|{finding.snippet}"


# --------------------------------------------------------------------------
# import + name resolution helpers


def _collect_imports(tree: ast.Module) -> dict[str, str]:
    """Bound name -> dotted module/object path, for disambiguating
    ``jax.random`` from stdlib ``random`` and resolving aliases
    (``import jax.numpy as jnp``, ``from jax.sharding import
    PartitionSpec as P``)."""
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    table[alias.asname] = alias.name
                else:
                    table[alias.name.split(".")[0]] = alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for alias in node.names:
                table[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return table


def _dotted(node: ast.AST, imports: dict[str, str]) -> str | None:
    """Resolve ``jnp.asarray`` / ``jax.lax.scan`` / ``scan`` to a dotted
    path through the import table; None when the root is not a plain name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Call):  # f(...)(...) — resolve the inner target
        return _dotted(node.func, imports)
    if not isinstance(node, ast.Name):
        return None
    root = imports.get(node.id, node.id)
    return ".".join([root] + list(reversed(parts)))


def _tail(path: str | None) -> str:
    return path.rsplit(".", 1)[-1] if path else ""


_JIT_TAILS = {"jit", "pjit"}
_TRACE_TAILS = _JIT_TAILS | {
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "checkpoint",
    "remat",
    "custom_vjp",
    "custom_jvp",
    "shard_map",
    "scan",
    "while_loop",
    "fori_loop",
    "cond",
    "switch",
    "associative_scan",
}


def _is_jax_path(path: str | None) -> bool:
    return bool(path) and (path.split(".")[0] == "jax" or path in _TRACE_TAILS)


def _trace_entry(path: str | None) -> bool:
    """Does calling this transform trace its function arguments?"""
    if not path:
        return False
    return _tail(path) in _TRACE_TAILS and path.split(".")[0] == "jax"


def _jit_like(node: ast.AST, imports: dict[str, str]) -> ast.Call | bool | None:
    """Classify a decorator / call target as jit-family. Returns the
    ``partial(...)`` call node when wrapped (so donate kwargs can be read
    off it), True for a bare jit reference, None otherwise."""
    if isinstance(node, ast.Call):
        path = _dotted(node.func, imports)
        if _tail(path) == "partial" and node.args:
            inner = _dotted(node.args[0], imports)
            if _tail(inner) in _JIT_TAILS and _is_jax_path(inner):
                return node
            return None
        if _tail(path) in _JIT_TAILS and _is_jax_path(path):
            return node
        return None
    path = _dotted(node, imports)
    if _tail(path) in _JIT_TAILS and _is_jax_path(path):
        return True
    return None


# --------------------------------------------------------------------------
# the per-module linter


class _ModuleLint:
    def __init__(self, tree: ast.Module, rel_path: str, lines: list[str]):
        self.tree = tree
        self.path = rel_path
        self.lines = lines
        self.imports = _collect_imports(tree)
        self.findings: list[Finding] = []
        self._flagged: set[tuple[str, int, int]] = set()
        # name -> FunctionDef nodes anywhere in the module (scope-blind —
        # a lint over-approximation, precise enough at module granularity)
        self.fn_defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fn_defs.setdefault(node.name, []).append(node)

    # -- plumbing --------------------------------------------------------

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if (rule, line, col) in self._flagged:
            return
        self._flagged.add((rule, line, col))
        snippet = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=line,
                col=col,
                message=message,
                snippet=snippet,
            )
        )

    def run(self) -> list[Finding]:
        traced = self._traced_functions()
        seen: set[int] = set()
        for root, reason in traced:
            if id(root) in seen:
                continue
            self._walk_traced(root, self._params_of(root), reason, seen)
        self._check_weak_literals()
        self._check_missing_donate()
        self._check_set_iteration()
        self._check_step_loops()
        return self.findings

    # -- traced-context discovery ----------------------------------------

    def _traced_functions(self) -> list[tuple[ast.AST, str]]:
        """(function node, why-it-is-traced) for every trace root in the
        module: jit-family decorators, plus functions/lambdas passed by
        name to jax transforms (jit/scan/shard_map/...). Tracing is NOT
        propagated through ordinary calls — module-local precision beats
        interprocedural false positives for a lint pass."""
        roots: list[tuple[ast.AST, str]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if _jit_like(deco, self.imports) is not None:
                        roots.append((node, "@jit"))
            elif isinstance(node, ast.Call):
                path = _dotted(node.func, self.imports)
                if not _trace_entry(path):
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        for fn in self.fn_defs.get(arg.id, ()):
                            roots.append((fn, _tail(path)))
                    elif isinstance(arg, ast.Lambda):
                        roots.append((arg, _tail(path)))
        return roots

    @staticmethod
    def _params_of(fn: ast.AST) -> set[str]:
        args = fn.args
        names = [
            a.arg
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
            )
        ]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return {n for n in names if n != "self"}

    def _walk_traced(
        self, node: ast.AST, params: set[str], reason: str, seen: set[int]
    ) -> None:
        """Visit a traced function body; nested functions extend the live
        traced-parameter set (their closures capture enclosing tracers)."""
        seen.add(id(node))
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                self._walk_traced(
                    child, params | self._params_of(child), reason, seen
                )
                continue
            self._check_traced_node(child, params, reason)
            self._walk_traced(child, params, reason, seen)

    # -- dynamic-value analysis ------------------------------------------

    _SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
    _STATIC_FNS = {"len", "isinstance", "hasattr", "callable", "getattr", "type"}

    def _dynamic(self, node: ast.AST, params: set[str]) -> bool:
        """Could this expression hold a tracer rooted at a traced param?
        Shape/dtype accesses and identity/isinstance tests are static."""
        if isinstance(node, ast.Name):
            return node.id in params
        if isinstance(node, ast.Attribute):
            if node.attr in self._SHAPE_ATTRS:
                return False
            return self._dynamic(node.value, params)
        if isinstance(node, ast.Call):
            fn_path = _dotted(node.func, self.imports)
            if _tail(fn_path) in self._STATIC_FNS:
                return False
            children: list[ast.AST] = list(node.args) + [
                kw.value for kw in node.keywords
            ]
            if not isinstance(node.func, ast.Name):
                children.append(node.func)
            return any(self._dynamic(c, params) for c in children)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return any(
                self._dynamic(c, params)
                for c in [node.left] + list(node.comparators)
            )
        if isinstance(node, ast.Constant):
            return False
        return any(
            self._dynamic(c, params) for c in ast.iter_child_nodes(node)
        )

    # -- rules inside traced bodies --------------------------------------

    _SYNC_BUILTINS = {"float", "int", "bool", "complex"}
    _SYNC_METHODS = {"item", "tolist"}
    _NUMPY_ROOTS = {"numpy", "onp"}

    def _check_traced_node(
        self, node: ast.AST, params: set[str], reason: str
    ) -> None:
        if isinstance(node, ast.Call):
            self._check_host_sync(node, params, reason)
            self._check_impurity(node, reason)
        elif isinstance(node, (ast.If, ast.While)):
            if self._dynamic(node.test, params):
                kind = "while" if isinstance(node, ast.While) else "if"
                self.emit(
                    "JX003",
                    node,
                    f"`{kind}` branches on a traced value inside a "
                    f"{reason}-traced function — raises at trace time or "
                    "bakes in one branch",
                )

    def _check_host_sync(
        self, node: ast.Call, params: set[str], reason: str
    ) -> None:
        func = node.func
        path = _dotted(func, self.imports)
        if (
            isinstance(func, ast.Name)
            and func.id in self._SYNC_BUILTINS
            and node.args
            and self._dynamic(node.args[0], params)
        ):
            self.emit(
                "JX002",
                node,
                f"`{func.id}()` forces a traced value to host inside a "
                f"{reason}-traced body",
            )
        elif (
            isinstance(func, ast.Attribute) and func.attr in self._SYNC_METHODS
        ):
            self.emit(
                "JX002",
                node,
                f"`.{func.attr}()` inside a {reason}-traced body is a "
                "host sync (or trace-time failure)",
            )
        elif (
            path
            and path.split(".")[0] in self._NUMPY_ROOTS
            and _tail(path) in {"array", "asarray"}
            and node.args
            and self._dynamic(node.args[0], params)
        ):
            self.emit(
                "JX002",
                node,
                f"`{_tail(path)}` materializes a traced value as numpy "
                f"inside a {reason}-traced body",
            )
        elif path == "jax.device_get":
            self.emit(
                "JX002",
                node,
                f"`jax.device_get` inside a {reason}-traced body",
            )
        elif isinstance(func, ast.Name) and func.id == "print":
            self.emit(
                "JX002",
                node,
                f"`print` inside a {reason}-traced body runs at trace time "
                "only (use jax.debug.print)",
            )

    _IMPURE = {
        "time": {
            "time",
            "perf_counter",
            "monotonic",
            "time_ns",
            "perf_counter_ns",
            "monotonic_ns",
        },
        "random": None,  # any attr of stdlib random
        "secrets": None,
        "uuid": None,
    }

    def _check_impurity(self, node: ast.Call, reason: str) -> None:
        path = _dotted(node.func, self.imports)
        if not path:
            return
        parts = path.split(".")
        root, tail = parts[0], parts[-1]
        impure = (
            root in self._IMPURE
            and (self._IMPURE[root] is None or tail in self._IMPURE[root])
        )
        # numpy's global RNG (np.random.*) — jax.random is keyed and pure
        impure = impure or (
            root in self._NUMPY_ROOTS and len(parts) >= 3 and parts[1] == "random"
        )
        impure = impure or path.endswith("datetime.now") or path == "os.urandom"
        if impure:
            self.emit(
                "JX004",
                node,
                f"`{path}` inside a {reason}-traced body freezes its value "
                "at trace time",
            )

    # -- JX001: weak scalar literals into carries/arrays -----------------

    _WEAK_CTORS = {"array", "asarray", "full"}
    _CARRY_ARG = {"scan": (1, "init"), "while_loop": (2, "init_val"),
                  "fori_loop": (3, "init_val")}

    def _check_weak_literals(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            path = _dotted(node.func, self.imports)
            if not path:
                continue
            parts = path.split(".")
            tail = parts[-1]
            if (
                tail in self._WEAK_CTORS
                and "numpy" in parts[:-1]
                and parts[0] == "jax"
            ):
                self._check_weak_ctor(node, tail)
            elif tail in self._CARRY_ARG and parts[0] == "jax":
                pos, kw = self._CARRY_ARG[tail]
                init = None
                if len(node.args) > pos:
                    init = node.args[pos]
                else:
                    init = next(
                        (k.value for k in node.keywords if k.arg == kw), None
                    )
                if init is not None:
                    for lit in self._bare_literals(init):
                        self.emit(
                            "JX001",
                            lit,
                            f"bare `{lit.value!r}` in a `{tail}` carry init "
                            "is weak-typed — the first iteration's output "
                            "dtype won't match and the carry re-promotes "
                            "(or jit recompiles)",
                        )

    def _check_weak_ctor(self, node: ast.Call, tail: str) -> None:
        has_dtype = any(k.arg == "dtype" for k in node.keywords)
        value_pos = 1 if tail == "full" else 0
        has_dtype = has_dtype or len(node.args) > value_pos + 1
        if has_dtype or len(node.args) <= value_pos:
            return
        value = node.args[value_pos]
        if isinstance(value, ast.Constant) and isinstance(
            value.value, (int, float)
        ) and not isinstance(value.value, bool):
            self.emit(
                "JX001",
                node,
                f"`jnp.{tail}` of a scalar literal without `dtype` builds a "
                "weak-typed array — entering jitted state/carries it keys "
                "the cache differently from the strong array a step returns",
            )

    @staticmethod
    def _bare_literals(node: ast.AST) -> list[ast.Constant]:
        """Numeric literals sitting directly in the init expression or its
        tuple/list/dict containers — calls (jnp.zeros(...)) are opaque."""
        out: list[ast.Constant] = []
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, ast.Constant):
                if isinstance(cur.value, (int, float)) and not isinstance(
                    cur.value, bool
                ):
                    out.append(cur)
            elif isinstance(cur, (ast.Tuple, ast.List)):
                stack.extend(cur.elts)
            elif isinstance(cur, ast.Dict):
                stack.extend(cur.values)
        return out

    # -- JX005: missing donate_argnums -----------------------------------

    def _check_missing_donate(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    jit = _jit_like(deco, self.imports)
                    if jit is None:
                        continue
                    kws = jit.keywords if isinstance(jit, ast.Call) else []
                    if any(
                        k.arg in ("donate_argnums", "donate_argnames")
                        for k in kws
                    ):
                        continue
                    if self._returns_updated_arg(node):
                        # anchor on the decorator line so an inline
                        # suppression sits next to the `@jax.jit` it excuses
                        self.emit(
                            "JX005",
                            deco,
                            f"jitted `{node.name}` returns an updated "
                            "version of an argument but donates nothing",
                        )
            elif isinstance(node, ast.Call):
                path = _dotted(node.func, self.imports)
                if not (
                    _tail(path) in _JIT_TAILS and _is_jax_path(path)
                ):
                    continue
                if any(
                    k.arg in ("donate_argnums", "donate_argnames")
                    for k in node.keywords
                ):
                    continue
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue
                for fn in self.fn_defs.get(node.args[0].id, ()):
                    if self._returns_updated_arg(fn):
                        self.emit(
                            "JX005",
                            node,
                            f"`jax.jit({node.args[0].id})` — the function "
                            "returns an updated argument but donates nothing",
                        )
                        break

    def _returns_updated_arg(self, fn: ast.AST) -> bool:
        params = self._params_of(fn)
        reassigned: set[str] = set()
        returns: list[ast.Return] = []
        for sub in self._body_nodes(fn):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            reassigned.add(n.id)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)) and isinstance(
                sub.target, ast.Name
            ):
                reassigned.add(sub.target.id)
            elif isinstance(sub, ast.Return) and sub.value is not None:
                returns.append(sub)
        for ret in returns:
            elts = (
                ret.value.elts
                if isinstance(ret.value, ast.Tuple)
                else [ret.value]
            )
            for e in elts:
                if (
                    isinstance(e, ast.Name)
                    and e.id in params
                    and e.id in reassigned
                ):
                    return True
                if (
                    isinstance(e, ast.Call)
                    and isinstance(e.func, ast.Attribute)
                    and e.func.attr in {"replace", "apply_gradients"}
                    and isinstance(e.func.value, ast.Name)
                    and e.func.value.id in params
                ):
                    return True
        return False

    @staticmethod
    def _body_nodes(fn: ast.AST) -> Iterable[ast.AST]:
        """Walk a function body without descending into nested functions."""
        return _ModuleLint._body_nodes_of_stmts(
            list(ast.iter_child_nodes(fn))
        )

    # -- JX006: set iteration feeding containers -------------------------

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            return _tail(_dotted(node.func, self.imports)) in {
                "set",
                "frozenset",
            }
        return False

    def _check_set_iteration(self) -> None:
        for node in ast.walk(self.tree):
            iters: list[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if self._is_set_expr(it):
                    self.emit(
                        "JX006",
                        it,
                        "iterating a set: order varies across processes "
                        "(hash randomization) — containers/pytrees built "
                        "from it diverge across hosts",
                    )

    # -- JX007: per-step host syncs in step loops ------------------------

    def _check_step_loops(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = [
                n
                for stmt in node.body
                for n in self._body_nodes_of_stmts([stmt])
            ]
            is_step_loop = any(
                isinstance(n, ast.Call)
                and "step" in _tail(_dotted(n.func, self.imports)).lower()
                and len(n.args) + len(n.keywords) >= 2
                for n in body
            )
            if not is_step_loop:
                continue
            for n in body:
                if not isinstance(n, ast.Call):
                    continue
                func = n.func
                if isinstance(func, ast.Name) and func.id == "float" and n.args:
                    self.emit(
                        "JX007",
                        n,
                        "`float()` in a step loop blocks the host on the "
                        "device every iteration",
                    )
                elif isinstance(func, ast.Attribute) and func.attr == "item":
                    self.emit(
                        "JX007",
                        n,
                        "`.item()` in a step loop blocks the host on the "
                        "device every iteration",
                    )

    @staticmethod
    def _body_nodes_of_stmts(stmts: list[ast.AST]) -> Iterable[ast.AST]:
        stack = list(stmts)
        while stack:
            cur = stack.pop()
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield cur
            stack.extend(ast.iter_child_nodes(cur))


# --------------------------------------------------------------------------
# file-level driving


def _apply_suppressions(findings: list[Finding], lines: list[str]) -> None:
    for f in findings:
        if not (0 < f.line <= len(lines)):
            continue
        m = _SUPPRESS_RE.search(lines[f.line - 1])
        if not m:
            continue
        ids = m.group("ids")
        if ids is None or f.rule in {
            s.strip().upper() for s in ids.split(",")
        }:
            f.suppressed = True


def lint_source(
    source: str, rel_path: str, tree: ast.Module | None = None
) -> list[Finding]:
    """Lint one file's source; returns findings with inline suppressions
    applied (suppressed findings are kept, marked). Pass ``tree`` to reuse
    an already-parsed AST (the CLI parses each file once for both the lint
    and the sharding checker)."""
    lines = source.splitlines()
    try:
        if tree is None:
            tree = ast.parse(source, filename=rel_path)
    except SyntaxError as exc:
        # the message doubles as the snippet so each distinct syntax error
        # fingerprints separately (a baselined one can't mask the next)
        return [
            Finding(
                rule="JX000",
                path=rel_path,
                line=exc.lineno or 1,
                col=exc.offset or 0,
                message=f"file does not parse: {exc.msg}",
                snippet=str(exc.msg or ""),
            )
        ]
    findings = _ModuleLint(tree, rel_path, lines).run()
    _apply_suppressions(findings, lines)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_py_files(paths: Iterable[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    # dedupe, keep order
    seen: set[Path] = set()
    uniq = []
    for p in out:
        rp = p.resolve()
        if rp not in seen:
            seen.add(rp)
            uniq.append(p)
    return uniq


def lint_paths(
    paths: Iterable[Path], root: Path | None = None
) -> list[Finding]:
    """Lint every ``.py`` under ``paths``; finding paths are relative to
    ``root`` (posix) so fingerprints are machine-independent."""
    root = Path(root) if root is not None else Path.cwd()
    findings: list[Finding] = []
    for file in iter_py_files(paths):
        try:
            rel = file.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = file.as_posix()
        findings.extend(lint_source(file.read_text(), rel))
    return findings


# --------------------------------------------------------------------------
# baseline


def load_baseline(path: Path) -> dict[str, int]:
    """fingerprint -> allowed occurrence count; empty when absent."""
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {str(k): int(v) for k, v in data.get("fingerprints", {}).items()}


def write_baseline(findings: list[Finding], path: Path) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        if f.suppressed:
            continue
        fp = fingerprint(f)
        counts[fp] = counts.get(fp, 0) + 1
    Path(path).write_text(
        json.dumps(
            {
                "version": 1,
                "tool": "jaxlint",
                "fingerprints": dict(sorted(counts.items())),
            },
            indent=2,
        )
        + "\n"
    )


def apply_baseline(findings: list[Finding], baseline: dict[str, int]) -> None:
    """Mark the first N occurrences of each baselined fingerprint; anything
    beyond the recorded count stays a NEW finding."""
    remaining = dict(baseline)
    for f in findings:
        if f.suppressed:
            continue
        fp = fingerprint(f)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            f.baselined = True
