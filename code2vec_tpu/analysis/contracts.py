"""Trace-time shape/dtype/weakness contracts for jitted step functions.

``@shape_contract`` validates a function's inputs when its Python body
runs — which under ``jit``/``scan``/``shard_map`` is exactly once per
trace (one per static shape signature). After the jit cache hit the
wrapper never executes again, so the steady-state cost is zero: no host
sync, no per-step Python, nothing staged into the compiled program. A
violation raises :class:`ContractError` at trace time — where the bad
batch/state is still attributable to its producer — instead of
surfacing 10k steps later as a mystery recompile or a wrong-dtype carry.

The weakness check is the trace-time twin of jaxlint's JX001: a
weak-typed scalar (flax's fresh ``step``, a bare Python literal in a
carry) keys the jit cache differently from the strong array the step
returns, silently doubling compiles per shape (the PR-4 bug class).

Specs
-----
Each argument spec is one of:

- ``"B,L"`` — a shape pattern: comma-separated dims, each an int literal
  (exact), a symbol (``B``/``L``/... — all uses of one symbol must bind
  the same size within a single call), or ``?`` (any). ``""`` means
  rank-0 scalar. Symbols bind per call: bucketed runs trace once per
  ladder width and each trace binds its own ``L`` — the contract
  validates internal consistency at every width without pinning one.
- a dtype (``jnp.int32``) — dtype-only check.
- ``("B,L", jnp.int32)`` — shape + dtype.
- :func:`spec` for the full form: ``spec("B,L", "int", allow_weak=True)``.
  ``dtype`` accepts a concrete dtype, a tuple of dtypes, or a category
  (``"int"`` / ``"float"`` / ``"bool"``).
- a dict — for dict-valued args (a batch) the entries are checked by
  key; for other objects (a TrainState) by attribute. Missing keys are
  violations; extra keys are ignored.
- ``None`` — skip this argument.

Any checked value must be strong-typed unless its spec passes
``allow_weak=True``.

Example::

    @shape_contract(state={"step": spec("", jnp.int32)},
                    batch={"starts": ("B,L", "int")})
    def train_step(state, batch): ...
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
from typing import Any, Mapping

import numpy as np

__all__ = ["ContractError", "ArgSpec", "spec", "shape_contract"]


class ContractError(TypeError):
    """A step-function input violated its shape/dtype/weakness contract."""


_CATEGORIES = {
    "int": np.integer,
    "float": np.floating,
    "bool": np.bool_,
}


def _in_category(dtype: np.dtype, category: str) -> bool:
    """Category membership via jax's extended dtype lattice when available
    — numpy's ``issubdtype`` does not know the ml_dtypes floats (bfloat16
    compute is a supported recipe), so the plain-numpy check is only the
    no-jax fallback."""
    try:
        import jax.numpy as jnp

        by_cat = {"int": jnp.integer, "float": jnp.floating, "bool": jnp.bool_}
        return bool(jnp.issubdtype(dtype, by_cat[category]))
    except ImportError:  # pragma: no cover - contracts without jax
        return bool(np.issubdtype(dtype, _CATEGORIES[category]))
_WILDCARDS = {"?", "_"}


@dataclasses.dataclass(frozen=True)
class ArgSpec:
    dims: tuple | None  # ints / symbol strs / wildcards; None = any shape
    dtypes: tuple | str | None  # dtype tuple, category str, or None
    allow_weak: bool = False


def spec(shape: str | None = None, dtype=None, *, allow_weak: bool = False) -> ArgSpec:
    """Build one argument spec; see the module docstring for the forms."""
    dims = None
    if shape is not None:
        shape = shape.strip()
        if shape == "":
            dims = ()
        else:
            dims = tuple(
                int(tok) if tok.lstrip("-").isdigit() else tok
                for tok in (t.strip() for t in shape.split(","))
            )
    dtypes: tuple | str | None = None
    if dtype is not None:
        if isinstance(dtype, str):
            if dtype not in _CATEGORIES:
                raise ValueError(
                    f"dtype category must be one of {sorted(_CATEGORIES)}, "
                    f"got {dtype!r}"
                )
            dtypes = dtype
        elif isinstance(dtype, (tuple, list)):
            dtypes = tuple(np.dtype(d) for d in dtype)
        else:
            dtypes = (np.dtype(dtype),)
    return ArgSpec(dims=dims, dtypes=dtypes, allow_weak=allow_weak)


def _coerce(s: Any):
    """Shorthand -> ArgSpec (or dict of them, or None)."""
    if s is None or isinstance(s, ArgSpec):
        return s
    if isinstance(s, Mapping):
        return {k: _coerce(v) for k, v in s.items()}
    if isinstance(s, str):
        return spec(shape=s)
    if isinstance(s, (tuple, list)):
        return spec(*s)
    try:
        return spec(dtype=np.dtype(s))
    except TypeError:
        raise TypeError(f"cannot interpret {s!r} as a contract spec") from None


def _aval(value) -> tuple[tuple, np.dtype, bool]:
    """(shape, dtype, weak_type) of a value — tracers included, so the
    check works on the abstract values jit hands the traced body."""
    try:
        import jax

        aval = jax.core.get_aval(value)
        return (
            tuple(aval.shape),
            np.dtype(aval.dtype),
            bool(getattr(aval, "weak_type", False)),
        )
    except Exception:
        arr = np.asarray(value)
        return arr.shape, arr.dtype, isinstance(value, (bool, int, float, complex))


def _check_value(fn_name: str, where: str, value, s: ArgSpec, env: dict) -> None:
    shape, dtype, weak = _aval(value)
    if s.dims is not None:
        if len(shape) != len(s.dims):
            raise ContractError(
                f"{fn_name}: {where} has rank {len(shape)} (shape {shape}), "
                f"contract expects rank {len(s.dims)} ({_dims_str(s.dims)})"
            )
        for i, d in enumerate(s.dims):
            if isinstance(d, int):
                if shape[i] != d:
                    raise ContractError(
                        f"{fn_name}: {where} dim {i} is {shape[i]}, "
                        f"contract pins it to {d}"
                    )
            elif d in _WILDCARDS:
                continue
            else:
                bound = env.setdefault(d, shape[i])
                if bound != shape[i]:
                    raise ContractError(
                        f"{fn_name}: {where} dim {i} ({d}) is {shape[i]} but "
                        f"{d}={bound} was bound by an earlier argument — "
                        "inconsistent shapes within one call"
                    )
    if s.dtypes is not None:
        if isinstance(s.dtypes, str):
            ok = _in_category(dtype, s.dtypes)
            expect = f"category {s.dtypes!r}"
        else:
            ok = dtype in s.dtypes
            expect = "/".join(str(d) for d in s.dtypes)
        if not ok:
            raise ContractError(
                f"{fn_name}: {where} has dtype {dtype}, contract expects "
                f"{expect}"
            )
    if weak and not s.allow_weak:
        raise ContractError(
            f"{fn_name}: {where} is WEAK-typed (a bare Python scalar or a "
            "dtype-less literal). Weak values key the jit cache differently "
            "from the strong arrays a step returns, so the function "
            "silently compiles twice per shape — give it an explicit dtype "
            "(e.g. jnp.asarray(x, jnp.int32)). [jaxlint JX001]"
        )


def _dims_str(dims: tuple) -> str:
    return ",".join(str(d) for d in dims) if dims else "scalar"


def _check_arg(fn_name: str, where: str, value, s, env: dict) -> None:
    if s is None:
        return
    if isinstance(s, dict):
        is_map = isinstance(value, Mapping)
        for key, sub in s.items():
            if is_map:
                if key not in value:
                    raise ContractError(
                        f"{fn_name}: {where} is missing required key {key!r}"
                    )
                item = value[key]
            else:
                try:
                    item = getattr(value, key)
                except AttributeError:
                    raise ContractError(
                        f"{fn_name}: {where} has no attribute {key!r} "
                        "required by its contract"
                    ) from None
            _check_arg(fn_name, f"{where}[{key!r}]", item, sub, env)
        return
    _check_value(fn_name, where, value, s, env)


def shape_contract(*pos_specs, **named_specs):
    """Decorator: validate the wrapped function's inputs at trace time.

    Positional specs align with positional parameters; keyword specs
    bind by parameter name (and also cover keyword calls). The wrapper
    counts its own executions in ``.contract_checks`` — under jit that
    is the TRACE count, which is how tests assert the check adds no
    steady-state work.
    """
    pos = [_coerce(s) for s in pos_specs]
    named = {k: _coerce(v) for k, v in named_specs.items()}

    def decorate(fn):
        fn_name = getattr(fn, "__name__", "<fn>")
        try:
            params = [
                p.name
                for p in inspect.signature(fn).parameters.values()
                if p.kind
                in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                )
            ]
        except (TypeError, ValueError):  # builtins / C callables
            params = []
        by_index: dict[int, Any] = {
            i: s for i, s in enumerate(pos) if s is not None
        }
        names_of: dict[int, str] = {
            i: name for i, name in enumerate(params)
        }
        for name, s in named.items():
            if name in params:
                idx = params.index(name)
                if idx in by_index:
                    raise TypeError(
                        f"shape_contract: parameter {name!r} of {fn_name} "
                        "has both a positional and a named spec"
                    )
                by_index[idx] = s

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            wrapper.contract_checks += 1
            env: dict = {}
            for i, value in enumerate(args):
                s = by_index.get(i)
                if s is not None:
                    _check_arg(
                        fn_name, names_of.get(i, f"arg{i}"), value, s, env
                    )
            for key, value in kwargs.items():
                s = named.get(key)
                if s is not None:
                    _check_arg(fn_name, key, value, s, env)
            return fn(*args, **kwargs)

        wrapper.contract_checks = 0
        wrapper.__contract__ = (tuple(pos), dict(named))
        return wrapper

    return decorate
