"""Static analysis for JAX footguns + trace-time step contracts.

Three layers, one defect class: bugs that never raise — they surface as
mystery recompiles, silent host syncs in the step loop, or multi-host
hangs, usually at step 10k on a real pod instead of in review.

- :mod:`code2vec_tpu.analysis.jaxlint` — pure-``ast`` lint rules
  (JX001-JX007): weak-typed literals entering jitted state/carries, host
  syncs and impurity inside traced bodies, tracer branching, missing
  donation, set-iteration-order pytree hazards, per-step host syncs in
  step loops.
- :mod:`code2vec_tpu.analysis.sharding_check` — every ``PartitionSpec``
  literal cross-validated against the mesh module's declared axis names
  (SC001-SC003).
- :mod:`code2vec_tpu.analysis.contracts` — ``@shape_contract``:
  shape/dtype/weakness validation of step-function inputs at trace time
  (zero steady-state cost); wired into ``train/step.py``,
  ``train/device_epoch.py``, ``parallel/step.py``, and ``ops/``.

Run the static layers with ``python -m code2vec_tpu.analysis`` (thin
wrapper: ``tools/jaxlint.py``); CI runs the same entry point against the
checked-in baseline (``analysis/baseline.json``). The lint layers import
only the stdlib — no jax — so the whole pass costs parse time.
"""

from code2vec_tpu.analysis.jaxlint import (  # noqa: F401
    RECOMPILE_HINT_RULES,
    RULES,
    Finding,
    Rule,
    lint_paths,
    lint_source,
)
from code2vec_tpu.analysis.sharding_check import (  # noqa: F401
    check_paths,
    check_source,
    declared_axes,
)

# the contract layer imports numpy (and, lazily, jax) — loaded on demand
# (PEP 562) so `python -m code2vec_tpu.analysis` stays runnable on a bare
# interpreter with zero third-party installs (the CI job relies on this)
_CONTRACT_EXPORTS = ("ArgSpec", "ContractError", "shape_contract", "spec")


def __getattr__(name: str):
    if name in _CONTRACT_EXPORTS:
        from code2vec_tpu.analysis import contracts

        return getattr(contracts, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
