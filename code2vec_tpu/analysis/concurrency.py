"""Concurrency lint: the CX rule family on the jaxlint engine.

The serving/training stacks are lock-based concurrent code (batcher,
router, swap controller, result cache, replica pipes, checkpoint writer,
fork-based feed pool), and their review history is a catalog of
hand-caught bugs of exactly five shapes. This pass makes those shapes
machine-checked, riding the same AST / fingerprint / inline-suppression /
baseline machinery as the JX/SC rules:

- **CX001 unguarded-shared-state** (warning): an attribute written from a
  thread-entry callable (``Thread(target=self.m)``, ``executor.submit(
  self.m)``) and also read/written in a public method outside any
  ``with self.<lock>`` region, in a class that owns locks. Attributes
  typed as thread-safe primitives (``Event``/``Queue``/``deque``/locks)
  are exempt.
- **CX002 lock-order-cycle** (error): the repo-wide lock acquisition
  graph — built from nested ``with``-lock regions plus cross-class edges
  through ``self.<attr>.<method>()`` calls whose target class acquires
  its own lock — contains a cycle: two code paths can acquire the same
  locks in opposite orders, i.e. a potential deadlock. Reentrant
  re-acquisition of an ``RLock`` is not an edge.
- **CX003 blocking-call-under-lock** (warning): ``time.sleep``, future
  ``.result()``, blocking ``queue.get/put``, pipe/socket I/O,
  ``subprocess`` waits, ``Thread.join``, ``block_until_ready`` /
  ``jax.device_get`` inside a held-lock region — the latency/deadlock
  class reviewers keep catching by hand.
- **CX004 condition-wait-no-predicate** (error): ``Condition.wait()``
  outside a ``while``-predicate loop and without a timeout — spurious
  wakeups and missed notifies make that a hang.
- **CX005 fork-after-threads** (error): requesting the ``fork``
  start-method (``multiprocessing.get_context("fork")`` /
  ``set_start_method("fork")``) without a ``guard_fork_safety`` call in
  the same scope — a forked child inherits any lock a live thread holds,
  permanently frozen.

The per-file rules run in :func:`check_source`; CX002 is inherently
repo-wide, so each file contributes a :class:`ModuleFragment` (class lock
tables, per-method acquisition summaries, edge events) and
:func:`finalize` joins them, resolves cross-class calls, and reports
cycles. Findings carry the standard fingerprint and honor inline
``# jaxlint: disable=CXnnn`` comments; a CX002 cycle is suppressed when
any edge line participating in the cycle carries one.

Everything here is heuristic over-approximation tuned to this codebase's
idioms (locks live in ``self.<attr>``; regions are ``with`` blocks);
manual ``.acquire()``/``.release()`` pairs and locks passed between
objects are out of scope by design — a lint pass earns its keep by being
quiet when it is unsure.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from code2vec_tpu.analysis import jaxlint
from code2vec_tpu.analysis.jaxlint import (
    _SUPPRESS_RE,
    Finding,
    Rule,
    _collect_imports,
    _dotted,
    _tail,
)

__all__ = [
    "CX_RULES",
    "ModuleFragment",
    "check_source",
    "finalize",
    "lint_concurrency",
]

CX_RULES: tuple[Rule, ...] = (
    Rule(
        "CX001",
        "unguarded-shared-state",
        "warning",
        "attribute shared between a thread-entry method and a public "
        "method without the class's lock",
        "guard both sides with the owning lock (`with self._lock:`), or "
        "switch the attribute to a thread-safe primitive "
        "(Event/Queue/deque)",
    ),
    Rule(
        "CX002",
        "lock-order-cycle",
        "error",
        "two code paths acquire the same locks in opposite orders "
        "(potential deadlock)",
        "pick one global acquisition order and restructure the later "
        "acquisition out of the held region (snapshot under one lock, "
        "call out after releasing)",
    ),
    Rule(
        "CX003",
        "blocking-call-under-lock",
        "warning",
        "blocking call (sleep/result/queue/pipe/subprocess/device) "
        "inside a held-lock region",
        "move the blocking call outside the `with` block — snapshot the "
        "state you need under the lock, block after releasing it",
    ),
    Rule(
        "CX004",
        "condition-wait-no-predicate",
        "error",
        "Condition.wait() without a predicate loop or timeout",
        "wrap the wait in `while not <predicate>:` (spurious wakeups are "
        "allowed by the memory model) or pass a timeout",
    ),
    Rule(
        "CX005",
        "fork-after-threads",
        "error",
        "fork start-method requested without a fork-safety guard",
        "call code2vec_tpu.obs.sync.guard_fork_safety(...) immediately "
        "before requesting the fork context — forked children inherit "
        "locks held by live threads, permanently frozen",
    ),
)

# register into the shared rule table so Finding.severity/.hint resolve and
# `--list-rules` shows the family
jaxlint.RULES.update({r.id: r for r in CX_RULES})


def _line_suppresses(line: str, rule: str) -> bool:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return False
    ids = m.group("ids")
    return ids is None or rule in {s.strip().upper() for s in ids.split(",")}


# ---------------------------------------------------------------------------
# per-class model
# ---------------------------------------------------------------------------

# ctor tails -> internal type tags; anything tagged here is considered
# thread-safe enough to exempt from CX001 (and types CX003 receivers)
_CTOR_TYPES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "make_lock": "lock",
    "make_rlock": "rlock",
    "make_condition": "condition",
    "Semaphore": "sync",
    "BoundedSemaphore": "sync",
    "Barrier": "sync",
    "Event": "event",
    "Queue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "deque": "deque",
    "defaultdict": "plain",
    "OrderedDict": "plain",
    "Thread": "thread",
    "Popen": "popen",
}

_LOCK_KINDS = {"lock", "rlock", "condition"}
_SAFE_TYPES = _LOCK_KINDS | {"sync", "event", "queue", "deque", "thread", "popen"}

_PIPE_ATTRS = {"stdin", "stdout", "stderr"}
_PIPE_METHODS = {"write", "flush", "read", "readline", "readlines"}
_SOCKET_METHODS = {"recv", "recv_into", "accept", "sendall", "connect"}
_SUBPROCESS_WAITS = {"run", "call", "check_call", "check_output"}


@dataclasses.dataclass
class EdgeEvent:
    """One potential acquisition-order edge source, recorded inside a
    held-lock region: either a directly nested ``with self.<lock>`` or a
    call that may acquire locks (resolved in :func:`finalize`)."""

    cls: str
    held: str  # own lock attr currently held (the edge source)
    kind: str  # "lock" | "selfcall" | "attrcall"
    target: str  # lock attr (kind=lock) or method name (calls)
    attr: str | None  # for attrcall: the self-attribute being called through
    path: str
    line: int
    snippet: str
    suppressed: bool


@dataclasses.dataclass
class ClassSummary:
    name: str
    path: str
    locks: dict[str, str]  # lock attr -> "lock" | "rlock" | "condition"
    attr_class: dict[str, str]  # attr -> candidate class name (ctor tail)
    method_acquires: dict[str, set[str]]  # method -> own lock attrs acquired
    method_calls: dict[str, set[tuple]]  # method -> {("self", m) | ("attr", a, m)}
    edge_events: list[EdgeEvent]


@dataclasses.dataclass
class ModuleFragment:
    """Everything CX002 needs from one file (the rest of the rules report
    inside :func:`check_source` directly)."""

    path: str
    classes: dict[str, ClassSummary]


# ---------------------------------------------------------------------------
# the per-class scanner
# ---------------------------------------------------------------------------


class _ClassScan:
    def __init__(self, mod: "_ModuleScan", node: ast.ClassDef) -> None:
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n
            for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.locks: dict[str, str] = {}
        self.attr_types: dict[str, str] = {}
        self.attr_class: dict[str, str] = {}
        self.entry_methods: set[str] = set()
        self.method_acquires: dict[str, set[str]] = {}
        self.method_calls: dict[str, set[tuple]] = {}
        self.edge_events: list[EdgeEvent] = []
        # (method, attr, unguarded, is_write, node), in source order
        self.accesses: list[tuple[str, str, bool, bool, ast.AST]] = []

    # -- pass 1: attribute typing + thread entries -----------------------

    def collect_types(self) -> None:
        for fn in self.methods.values():
            ann = {
                a.arg: self._ann_tail(a.annotation)
                for a in (
                    list(fn.args.posonlyargs)
                    + list(fn.args.args)
                    + list(fn.args.kwonlyargs)
                )
                if a.annotation is not None
            }
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    self._type_attr(tgt.attr, sub.value, ann)
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Call):
                continue
            entry = self._entry_target(sub)
            if entry is not None and entry in self.methods:
                self.entry_methods.add(entry)

    def _ann_tail(self, annotation: ast.AST) -> str:
        """Annotation -> class-name tail; quoted forward references
        (``b: "FleetRouter"``) arrive as string constants."""
        if isinstance(annotation, ast.Constant) and isinstance(
            annotation.value, str
        ):
            return annotation.value.strip("'\" ").rsplit(".", 1)[-1]
        return _tail(_dotted(annotation, self.mod.imports))

    def _type_attr(self, attr: str, value: ast.AST, ann: dict) -> None:
        if isinstance(value, ast.Call):
            tail = _tail(_dotted(value.func, self.mod.imports))
            tag = _CTOR_TYPES.get(tail)
            if tag in _LOCK_KINDS:
                self.locks[attr] = tag
                self.attr_types[attr] = tag
            elif tag is not None:
                self.attr_types.setdefault(attr, tag)
            elif tail and tail[:1].isupper():
                # candidate class instance — resolved against the global
                # class table in finalize() for cross-class lock edges
                self.attr_class.setdefault(attr, tail)
        elif isinstance(value, ast.Name) and value.id in ann:
            tail = ann[value.id]
            if tail and tail[:1].isupper():
                self.attr_class.setdefault(attr, tail)

    def _entry_target(self, call: ast.Call) -> str | None:
        """Method name when this call registers a thread entry:
        ``Thread(target=self.m)`` or ``<executor>.submit(self.m, ...)``."""
        tail = _tail(_dotted(call.func, self.mod.imports))
        if tail == "Thread":
            for kw in call.keywords:
                if (
                    kw.arg == "target"
                    and isinstance(kw.value, ast.Attribute)
                    and isinstance(kw.value.value, ast.Name)
                    and kw.value.value.id == "self"
                ):
                    return kw.value.attr
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"
            and call.args
            and isinstance(call.args[0], ast.Attribute)
            and isinstance(call.args[0].value, ast.Name)
            and call.args[0].value.id == "self"
        ):
            return call.args[0].attr
        return None

    # -- pass 2: held-region walk ----------------------------------------

    def scan_methods(self) -> None:
        for name, fn in self.methods.items():
            self.method_acquires[name] = set()
            self.method_calls[name] = set()
            for stmt in fn.body:
                self._walk(stmt, held=[], fn=name, in_while=False)

    def _self_lock_attr(self, expr: ast.AST) -> str | None:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.locks
        ):
            return expr.attr
        return None

    def _edge(self, held: str, kind: str, target: str, attr, node) -> None:
        line = getattr(node, "lineno", 1)
        snippet = self.mod.line(line)
        self.edge_events.append(
            EdgeEvent(
                cls=self.name,
                held=held,
                kind=kind,
                target=target,
                attr=attr,
                path=self.mod.path,
                line=line,
                snippet=snippet,
                suppressed=_line_suppresses(snippet, "CX002"),
            )
        )

    def _walk(self, node: ast.AST, held: list, fn: str, in_while: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # closures run on their own schedule; held doesn't transfer
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                attr = self._self_lock_attr(item.context_expr)
                if attr is not None:
                    self.method_acquires[fn].add(attr)
                    for h in held:
                        self._edge(h, "lock", attr, None, item.context_expr)
                    held.append(attr)
                    acquired.append(attr)
                else:
                    self._walk(item.context_expr, held, fn, in_while)
            for child in node.body:
                self._walk(child, held, fn, in_while)
            for _ in acquired:
                held.pop()
            return
        if isinstance(node, ast.While):
            self._walk(node.test, held, fn, True)
            for child in node.body + node.orelse:
                self._walk(child, held, fn, True)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                    ):
                        self.accesses.append(
                            (fn, sub.attr, not held, True, sub)
                        )
        if isinstance(node, ast.Call):
            self._check_call(node, held, fn, in_while)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "self" and isinstance(node.ctx, ast.Load):
                self.accesses.append((fn, node.attr, not held, False, node))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, fn, in_while)

    # -- call classification (CX002 events, CX003, CX004) ----------------

    def _check_call(
        self, node: ast.Call, held: list, fn: str, in_while: bool
    ) -> None:
        func = node.func
        # self.m(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            self.method_calls[fn].add(("self", func.attr))
            for h in held:
                self._edge(h, "selfcall", func.attr, None, node)
        # self.<attr>.m(...)
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
        ):
            attr, meth = func.value.attr, func.attr
            atype = self.attr_types.get(attr)
            if attr in self.attr_class:
                self.method_calls[fn].add(("attr", attr, meth))
                for h in held:
                    self._edge(h, "attrcall", meth, attr, node)
            if atype == "condition" and meth == "wait":
                self._check_condition_wait(node, in_while)
            elif held and atype == "queue" and meth in {"get", "put"}:
                self.mod.emit(
                    "CX003",
                    node,
                    f"blocking `{attr}.{meth}()` while holding "
                    f"`self.{held[-1]}` — the lock is held for the full "
                    "wait (use the _nowait variant or move it out)",
                )
            elif held and atype == "popen" and meth in {"wait", "communicate"}:
                self.mod.emit(
                    "CX003",
                    node,
                    f"subprocess `{meth}()` while holding `self.{held[-1]}` "
                    "waits on another process under the lock",
                )
            elif held and atype == "thread" and meth == "join":
                self.mod.emit(
                    "CX003",
                    node,
                    f"`{attr}.join()` while holding `self.{held[-1]}` — if "
                    "the joined thread needs the lock, this deadlocks",
                )
        if not isinstance(func, ast.Attribute) and not isinstance(
            func, ast.Name
        ):
            return
        if held:
            self._check_blocking(node, held)

    def _check_condition_wait(self, node: ast.Call, in_while: bool) -> None:
        has_timeout = bool(node.args) or any(
            kw.arg == "timeout" for kw in node.keywords
        )
        if in_while or has_timeout:
            return
        self.mod.emit(
            "CX004",
            node,
            "`Condition.wait()` outside a while-predicate loop and without "
            "a timeout — a spurious wakeup or missed notify hangs here",
        )

    def _check_blocking(self, node: ast.Call, held: list) -> None:
        func = node.func
        path = _dotted(func, self.mod.imports)
        tail = _tail(path)
        lock = held[-1]
        root = path.split(".")[0] if path else ""
        if path == "time.sleep":
            self.mod.emit(
                "CX003",
                node,
                f"`time.sleep` while holding `self.{lock}` stalls every "
                "other thread waiting on the lock",
            )
        elif root == "subprocess" and tail in _SUBPROCESS_WAITS:
            self.mod.emit(
                "CX003",
                node,
                f"`subprocess.{tail}` while holding `self.{lock}` waits on "
                "another process under the lock",
            )
        elif tail == "block_until_ready" or path == "jax.device_get":
            self.mod.emit(
                "CX003",
                node,
                f"device sync `{tail}` while holding `self.{lock}` holds "
                "the lock for a full device round-trip",
            )
        elif isinstance(func, ast.Attribute) and func.attr == "result":
            self.mod.emit(
                "CX003",
                node,
                f"`.result()` while holding `self.{lock}` — if resolving "
                "the future needs the lock, this deadlocks",
            )
        elif isinstance(func, ast.Attribute) and (
            func.attr in _SOCKET_METHODS
            or (
                func.attr in _PIPE_METHODS
                and any(
                    isinstance(part, ast.Attribute) and part.attr in _PIPE_ATTRS
                    for part in ast.walk(func.value)
                )
            )
        ):
            self.mod.emit(
                "CX003",
                node,
                f"pipe/socket `{func.attr}` while holding `self.{lock}` can "
                "block on a slow/stalled peer with the lock held",
            )

    # -- CX001 ------------------------------------------------------------

    def report_unguarded(self) -> None:
        if not self.locks:
            return  # not a lock-owning class: no locking discipline to check
        reachable = self._entry_closure()
        written_by: dict[str, str] = {}
        for fn, attr, _unguarded, is_write, _node in self.accesses:
            if fn in reachable and fn != "__init__" and is_write:
                written_by.setdefault(attr, fn)
        if not written_by:
            return
        flagged: set[str] = set()
        for fn, attr, unguarded, _is_write, node in self.accesses:
            if (
                attr not in written_by
                or attr in flagged
                or not unguarded
                or fn in reachable
                or fn.startswith("_")
                or fn == written_by[attr]
                or attr in self.locks
                or self.attr_types.get(attr) in _SAFE_TYPES
                or attr in self.attr_class
                or attr in self.methods
            ):
                continue
            flagged.add(attr)
            self.mod.emit(
                "CX001",
                node,
                f"`self.{attr}` is written by thread-entry method "
                f"`{written_by[attr]}` but accessed in public `{fn}` "
                f"outside any `with self.<lock>` region of {self.name}",
            )

    def _entry_closure(self) -> set[str]:
        reach = set(self.entry_methods)
        frontier = list(reach)
        while frontier:
            m = frontier.pop()
            for call in self.method_calls.get(m, ()):
                if call[0] == "self" and call[1] in self.methods:
                    if call[1] not in reach:
                        reach.add(call[1])
                        frontier.append(call[1])
        return reach

    def summary(self) -> ClassSummary:
        return ClassSummary(
            name=self.name,
            path=self.mod.path,
            locks=dict(self.locks),
            attr_class=dict(self.attr_class),
            method_acquires={
                k: set(v) for k, v in self.method_acquires.items()
            },
            method_calls={k: set(v) for k, v in self.method_calls.items()},
            edge_events=list(self.edge_events),
        )


# ---------------------------------------------------------------------------
# the per-module scanner
# ---------------------------------------------------------------------------


class _ModuleScan:
    def __init__(self, tree: ast.Module, rel_path: str, lines: list[str]):
        self.tree = tree
        self.path = rel_path
        self.lines = lines
        self.imports = _collect_imports(tree)
        self.findings: list[Finding] = []
        self._flagged: set[tuple[str, int, int]] = set()

    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if (rule, line, col) in self._flagged:
            return
        self._flagged.add((rule, line, col))
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=line,
                col=col,
                message=message,
                snippet=self.line(line),
            )
        )

    def run(self) -> ModuleFragment:
        classes: dict[str, ClassSummary] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scan = _ClassScan(self, node)
            scan.collect_types()
            scan.scan_methods()
            scan.report_unguarded()
            classes.setdefault(node.name, scan.summary())
        self._check_fork(self.tree)
        return ModuleFragment(path=self.path, classes=classes)

    # -- CX005 ------------------------------------------------------------

    def _check_fork(self, tree: ast.Module) -> None:
        # scope -> (fork-request nodes, has guard_fork_safety call)
        self._fork_scope(tree)

    def _fork_scope(self, scope: ast.AST) -> None:
        forks: list[ast.Call] = []
        guarded = False
        for node in self._scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(_dotted(node.func, self.imports))
            if tail == "guard_fork_safety":
                guarded = True
            elif tail in {"get_context", "set_start_method"}:
                arg = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords if kw.arg == "method"),
                    None,
                )
                if isinstance(arg, ast.Constant) and arg.value == "fork":
                    forks.append(node)
        if not guarded:
            for node in forks:
                self.emit(
                    "CX005",
                    node,
                    "`fork` start-method requested without a "
                    "guard_fork_safety(...) call in the same scope — forked "
                    "children inherit locks held by live threads",
                )
        for node in ast.walk(scope):
            if node is not scope and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self._fork_scope(node)

    @staticmethod
    def _scope_nodes(scope: ast.AST) -> Iterable[ast.AST]:
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scopes get their own guard check
            yield cur
            stack.extend(ast.iter_child_nodes(cur))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def check_source(
    source: str, rel_path: str, tree: ast.Module | None = None
) -> tuple[list[Finding], ModuleFragment]:
    """Run the per-file CX rules (CX001/CX003/CX004/CX005) on one module;
    returns (findings with inline suppressions applied, the module's CX002
    fragment for :func:`finalize`). Unparseable files yield nothing —
    jaxlint's JX000 already reports those."""
    lines = source.splitlines()
    if tree is None:
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError:
            return [], ModuleFragment(path=rel_path, classes={})
    scan = _ModuleScan(tree, rel_path, lines)
    fragment = scan.run()
    findings = scan.findings
    jaxlint._apply_suppressions(findings, lines)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, fragment


def _lock_reach(
    classes: dict[str, ClassSummary]
) -> dict[tuple[str, str], set[str]]:
    """Fixpoint of (class, method) -> qualified locks it may acquire,
    through self-calls and cross-class self-attribute calls."""
    reach: dict[tuple[str, str], set[str]] = {}
    for cls in classes.values():
        for method, acquires in cls.method_acquires.items():
            reach[(cls.name, method)] = {
                f"{cls.name}.{a}" for a in acquires
            }
    for _ in range(len(reach) + 1):
        changed = False
        for cls in classes.values():
            for method, calls in cls.method_calls.items():
                mine = reach.setdefault((cls.name, method), set())
                before = len(mine)
                for call in calls:
                    if call[0] == "self":
                        mine |= reach.get((cls.name, call[1]), set())
                    else:
                        target = cls.attr_class.get(call[1])
                        if target in classes:
                            mine |= reach.get((target, call[2]), set())
                changed = changed or len(mine) != before
        if not changed:
            break
    return reach


def finalize(fragments: Iterable[ModuleFragment]) -> list[Finding]:
    """Join every module's fragments into the repo-wide acquisition graph
    and report CX002 cycles. A cycle finding anchors at its first edge
    (path, line order) and is suppressed when ANY edge line in the cycle
    carries a CX002 suppression (one documented annotation per cycle)."""
    classes: dict[str, ClassSummary] = {}
    for frag in fragments:
        for name, summary in frag.classes.items():
            classes.setdefault(name, summary)
    reach = _lock_reach(classes)
    lock_kind = {
        f"{c.name}.{attr}": kind
        for c in classes.values()
        for attr, kind in c.locks.items()
    }
    # (src, dst) -> representative EdgeEvent (first seen in path/line order)
    edges: dict[tuple[str, str], EdgeEvent] = {}
    events = sorted(
        (ev for c in classes.values() for ev in c.edge_events),
        key=lambda e: (e.path, e.line),
    )
    for ev in events:
        src = f"{ev.cls}.{ev.held}"
        if ev.kind == "lock":
            dsts = {f"{ev.cls}.{ev.target}"}
        elif ev.kind == "selfcall":
            dsts = reach.get((ev.cls, ev.target), set())
        else:
            target = classes.get(ev.cls)
            tcls = target.attr_class.get(ev.attr) if target else None
            dsts = reach.get((tcls, ev.target), set()) if tcls else set()
        for dst in dsts:
            if dst == src and lock_kind.get(src) == "rlock":
                continue  # reentrant re-acquire: not an edge
            edges.setdefault((src, dst), ev)

    adjacency: dict[str, set[str]] = {}
    for src, dst in edges:
        adjacency.setdefault(src, set()).add(dst)
        adjacency.setdefault(dst, set())
    findings: list[Finding] = []
    for component in _sccs(adjacency):
        cyclic = len(component) > 1 or any(
            (n, n) in edges for n in component
        )
        if not cyclic:
            continue
        member_edges = sorted(
            (
                ev
                for (src, dst), ev in edges.items()
                if src in component and dst in component
            ),
            key=lambda e: (e.path, e.line),
        )
        if not member_edges:  # pragma: no cover - SCC implies edges
            continue
        anchor = member_edges[0]
        order = " -> ".join(sorted(component) + [sorted(component)[0]])
        finding = Finding(
            rule="CX002",
            path=anchor.path,
            line=anchor.line,
            col=0,
            message=(
                f"lock acquisition cycle {order}: two code paths can take "
                "these locks in opposite orders (potential deadlock); "
                "edges at "
                + ", ".join(f"{e.path}:{e.line}" for e in member_edges[:6])
            ),
            snippet=anchor.snippet,
            suppressed=any(e.suppressed for e in member_edges),
        )
        findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _sccs(adjacency: dict[str, set[str]]) -> list[set[str]]:
    """Tarjan's strongly-connected components, iterative."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[set[str]] = []
    counter = [0]

    for root in adjacency:
        if root in index:
            continue
        work: list[tuple[str, Iterable]] = [(root, iter(adjacency[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adjacency[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                component: set[str] = set()
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    component.add(member)
                    if member == node:
                        break
                out.append(component)
    return out


def lint_concurrency(source: str, rel_path: str = "mod.py") -> list[Finding]:
    """Single-module convenience (fixture tests): per-file rules plus a
    one-module CX002 pass, suppressions applied, sorted."""
    findings, fragment = check_source(source, rel_path)
    findings = findings + finalize([fragment])
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
