"""``python -m code2vec_tpu.analysis`` — run jaxlint + the sharding checker.

Pure stdlib (no jax, no numpy): the whole pass costs parse time, so the
CI job runs it on a bare interpreter in seconds. Exit status is 1 iff
any NEW finding exists — one that is neither inline-suppressed
(``# jaxlint: disable=JXnnn``) nor recorded in the baseline file
(``analysis/baseline.json``; regenerate with ``--write-baseline``).

``--diff-only [REF]`` restricts the scan to ``.py`` files changed vs
``REF`` (default: the merge base with ``origin/main``, else ``HEAD~1``)
plus uncommitted/untracked files — the fast CI mode. An unresolvable ref
falls back to the full scan rather than silently passing.
"""

from __future__ import annotations

import argparse
import ast
import json
import subprocess
import sys
from pathlib import Path

from code2vec_tpu.analysis import concurrency, jaxlint, lifecycle
from code2vec_tpu.analysis.sharding_check import check_source, declared_axes

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_PATHS = ("code2vec_tpu", "tools", "bench.py", "main.py")
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
DEFAULT_MESH = "code2vec_tpu/parallel/mesh.py"
SYNC_MODULE = "code2vec_tpu/obs/sync.py"
HANDLES_MODULE = "code2vec_tpu/obs/handles.py"
# textual markers of a lock-factory call site / raw lock construction: a
# change to any such module can add or remove acquisition-graph edges whose
# cycles close through UNCHANGED files, so the diff-restricted scan widens
_LOCK_SITE_MARKERS = (
    "make_lock(",
    "make_rlock(",
    "make_condition(",
    "threading.Lock(",
    "threading.RLock(",
    "threading.Condition(",
)
# textual markers of resource construction: RS005's repo-wide finalize
# joins per-file class fragments, so a diff adding a resource ctor (or
# touching the ledger module) can change verdicts on UNCHANGED owner
# classes — same rationale as the CX002 widening above
_RESOURCE_SITE_MARKERS = (
    "subprocess.Popen(",
    "SharedMemory(",
    "np.memmap(",
    "open_memmap(",
    "mmap.mmap(",
    "mkdtemp(",
    "NamedTemporaryFile(",
    "threading.Thread(",
    "ThreadPoolExecutor(",
    "ProcessPoolExecutor(",
)


def _touches_lock_graph(root: Path, changed: list[Path]) -> Path | None:
    """The first changed file that can perturb the repo-wide lock
    acquisition graph (the sync module itself, or any module constructing
    locks / calling the lock factory); None when the diff is graph-inert."""
    for rel in changed:
        if rel.as_posix() == SYNC_MODULE:
            return rel
        path = root / rel
        if not path.exists():  # a deleted lock-site module also perturbs
            continue
        try:
            text = path.read_text()
        except OSError:  # pragma: no cover - unreadable working tree file
            continue
        if any(marker in text for marker in _LOCK_SITE_MARKERS):
            return rel
    return None


def _touches_resource_graph(root: Path, changed: list[Path]) -> Path | None:
    """The first changed file that can perturb the repo-wide resource
    ownership table (the handle-ledger module itself, or any module
    constructing tracked resources); None when the diff is inert."""
    for rel in changed:
        if rel.as_posix() == HANDLES_MODULE:
            return rel
        path = root / rel
        if not path.exists():  # a deleted owner module also perturbs
            continue
        try:
            text = path.read_text()
        except OSError:  # pragma: no cover - unreadable working tree file
            continue
        if any(marker in text for marker in _RESOURCE_SITE_MARKERS):
            return rel
    return None


def _git(root: Path, *args: str) -> str:
    return subprocess.run(
        ["git", "-C", str(root), *args],
        check=True,
        capture_output=True,
        text=True,
    ).stdout


def changed_py_files(root: Path, ref: str | None) -> list[Path] | None:
    """Repo-relative ``.py`` files changed vs ``ref`` + working-tree
    changes + untracked files; None when git state can't be read (the
    caller falls back to a full scan)."""
    try:
        if not ref:
            try:
                ref = _git(root, "merge-base", "origin/main", "HEAD").strip()
            except subprocess.CalledProcessError:
                ref = "HEAD~1"
        names = set(_git(root, "diff", "--name-only", ref).splitlines())
        names |= set(_git(root, "diff", "--name-only", "--cached").splitlines())
        names |= set(
            _git(
                root, "ls-files", "--others", "--exclude-standard"
            ).splitlines()
        )
    except (subprocess.CalledProcessError, OSError):
        return None
    return [Path(n) for n in sorted(names) if n.endswith(".py")]


def _severity_counts(findings: list[jaxlint.Finding]) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return out


def run(
    paths: list[Path],
    root: Path,
    baseline_path: Path,
    mesh_file: Path | None,
) -> list[jaxlint.Finding]:
    # one read + one ast.parse per file, shared by the lint and the
    # sharding checker — parse time is the whole cost of this tool
    axis_decls = (
        declared_axes(mesh_file.read_text()) if mesh_file is not None else None
    )
    findings: list[jaxlint.Finding] = []
    fragments: list[concurrency.ModuleFragment] = []
    rs_fragments: list[lifecycle.LifecycleFragment] = []
    for file in jaxlint.iter_py_files(paths):
        try:
            rel = file.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = file.as_posix()
        source = file.read_text()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError:
            tree = None  # lint_source reparses to emit JX000
        findings += jaxlint.lint_source(source, rel, tree=tree)
        if axis_decls is not None and tree is not None:
            findings += check_source(source, rel, axis_decls, tree=tree)
        if tree is not None:
            cx_findings, fragment = concurrency.check_source(
                source, rel, tree=tree
            )
            findings += cx_findings
            fragments.append(fragment)
            rs_findings, rs_fragment = lifecycle.check_source(
                source, rel, tree=tree
            )
            findings += rs_findings
            rs_fragments.append(rs_fragment)
    # CX002 is repo-wide: the acquisition graph joins every scanned file's
    # fragments, so cross-class cycles surface wherever their edges live
    findings += concurrency.finalize(fragments)
    # RS005 likewise: owned-class attributes resolve against every class
    # seen anywhere in the scan
    findings += lifecycle.finalize(rs_fragments)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    jaxlint.apply_baseline(findings, jaxlint.load_baseline(baseline_path))
    return findings


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m code2vec_tpu.analysis",
        description="JAX-footgun lint + sharding-contract check",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--root",
        type=Path,
        default=REPO_ROOT,
        help="repo root for relative finding paths (default: the package's)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help="baseline file of accepted pre-existing findings",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="record the current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--diff-only",
        nargs="?",
        const="",
        default=None,
        metavar="REF",
        help="scan only .py files changed vs REF (default: merge-base with "
        "origin/main, else HEAD~1) — the fast CI mode",
    )
    parser.add_argument(
        "--mesh-file",
        type=Path,
        default=None,
        help=f"mesh-axis declarations for SC rules (default: {DEFAULT_MESH})",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit findings as a JSON document"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.diff_only is not None and args.write_baseline:
        # a baseline written from a restricted scan would drop every
        # accepted fingerprint in the unscanned files
        parser.error("--write-baseline needs the full scan; drop --diff-only")

    if args.list_rules:
        for rule in jaxlint.RULES.values():
            print(f"{rule.id} [{rule.severity:7}] {rule.name}: {rule.summary}")
            print(f"       fix: {rule.hint}")
        return 0

    root = args.root.resolve()
    scan = [
        root / p for p in (args.paths or DEFAULT_PATHS) if (root / p).exists()
    ]
    mesh_file = args.mesh_file if args.mesh_file is not None else root / DEFAULT_MESH
    if not mesh_file.exists():
        mesh_file = None

    if args.diff_only is not None:
        changed = changed_py_files(root, args.diff_only or None)
        if changed is None:
            print(
                "jaxlint: --diff-only could not read git state; running the "
                "full scan",
                file=sys.stderr,
            )
        elif mesh_file is not None and any(
            (root / c).resolve() == mesh_file.resolve() for c in changed
        ):
            # a mesh-axis rename/removal invalidates PartitionSpecs in
            # UNCHANGED files; restricting to the diff would pass the PR
            # and break the full scan on main
            print(
                "jaxlint: mesh declarations changed; running the full scan",
                file=sys.stderr,
            )
        elif (lock_site := _touches_lock_graph(root, changed)) is not None:
            # same widening logic as the mesh rule, for CX002: the lock
            # acquisition graph is repo-wide, so an edge added in this
            # diff can close a cycle through unchanged files
            print(
                f"jaxlint: lock construction changed ({lock_site.as_posix()})"
                "; running the full scan",
                file=sys.stderr,
            )
        elif (res_site := _touches_resource_graph(root, changed)) is not None:
            # RS005's ownership table is repo-wide: a resource ctor added
            # in this diff can change verdicts on unchanged owner classes
            print(
                f"jaxlint: resource construction changed "
                f"({res_site.as_posix()}); running the full scan",
                file=sys.stderr,
            )
        else:
            scan_files = {
                f.resolve() for f in jaxlint.iter_py_files(scan)
            }
            scan = [
                root / c for c in changed if (root / c).resolve() in scan_files
            ]
            if not scan:
                print("jaxlint: no changed files in scope; nothing to do")
                return 0

    findings = run(scan, root, args.baseline, mesh_file)

    if args.write_baseline:
        jaxlint.write_baseline(
            [f for f in findings if not f.suppressed], args.baseline
        )
        print(f"jaxlint: baseline written to {args.baseline}")
        return 0

    new = [f for f in findings if not f.suppressed and not f.baselined]
    if args.json:
        print(
            json.dumps(
                {
                    "version": 1,
                    "tool": "jaxlint",
                    "findings": [f.to_json() for f in findings],
                    "summary": {
                        "total": len(findings),
                        "new": len(new),
                        "baselined": sum(1 for f in findings if f.baselined),
                        "suppressed": sum(1 for f in findings if f.suppressed),
                        "by_severity": _severity_counts(new),
                    },
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.text())
        print(
            f"jaxlint: {len(new)} new finding(s), "
            f"{sum(1 for f in findings if f.baselined)} baselined, "
            f"{sum(1 for f in findings if f.suppressed)} suppressed "
            f"({len(findings)} total)"
        )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
