"""Resource-lifecycle lint: the RS rule family (static half of the analyzer).

Rides the jaxlint engine (PR 5) exactly like the CX concurrency rules
(PR 18): same :class:`~code2vec_tpu.analysis.jaxlint.Finding` shape, same
``# jaxlint: disable=RSnnn`` inline suppressions, same fingerprint/baseline
semantics, shipped through ``python -m code2vec_tpu.analysis``. The runtime
twin is the handle ledger in :mod:`code2vec_tpu.obs.handles` — the rules
catch leak *shapes* at lint time, the ledger catches leaked *instances* at
run time, and both speak the same vocabulary of lifecycle owners.

Rules:

- **RS001 unclosed-resource** — a file / mmap / socket / SharedMemory bound
  to a local that is neither a ``with`` target nor closed anywhere in its
  scope. Escapes (returned, yielded, passed to a call, stored into a
  container/attribute) transfer ownership and silence the rule.
- **RS002 unjoined-thread** — a non-daemon ``threading.Thread`` stored on
  ``self`` and ``start()``-ed, where no ``join()`` on that attribute is
  reachable from any close-like method (``close``/``shutdown``/``stop``/
  ``__exit__``/...) via the class's own self-call graph.
- **RS003 unreaped-subprocess** — a ``subprocess.Popen`` (local or
  attribute) with no ``wait``/``communicate``/``terminate``/``kill`` on any
  path that can see it — a zombie on every exit path.
- **RS004 unremoved-tempfile** — ``tempfile.mkdtemp`` /
  ``NamedTemporaryFile(delete=False)`` whose result neither reaches a
  recorded cleanup (``shutil.rmtree``/``os.unlink``/``atexit.register``/
  fixture finalizers) nor leaves the scope as an owned value.
- **RS005 leaky-owner-class** — a class that acquires closeable resources
  in ``__init__``/``__post_init__`` but defines no close-like method at
  all, or whose close closure provably never touches a tracked attribute.
  Resolved in a repo-wide :func:`finalize` pass joining per-file class
  fragments (same shape as CX002), so owning an instance of another
  closeable class counts as a tracked resource.
- **RS006 unshutdown-executor** — a ``ThreadPoolExecutor`` /
  ``ProcessPoolExecutor`` / ``multiprocessing.Pool`` / ``mp.Queue``
  created without a shutdown call.

All rules over-approximate toward *silence*: anything that escapes its
scope, is managed by ``with``/``contextlib.closing``/``enter_context``, or
is daemonized is assumed intentional. The point is catching the
unambiguous shapes cheaply, not proving lifetimes.
"""

from __future__ import annotations

import ast
import dataclasses

from code2vec_tpu.analysis import jaxlint
from code2vec_tpu.analysis.jaxlint import (
    _SUPPRESS_RE,
    Finding,
    Rule,
    _collect_imports,
    _dotted,
    _tail,
)

RS_RULES: tuple[Rule, ...] = (
    Rule(
        "RS001",
        "unclosed-resource",
        "warning",
        "file/mmap/socket/SharedMemory opened outside `with` and never closed",
        "wrap in `with` (or contextlib.closing) or close in try/finally",
    ),
    Rule(
        "RS002",
        "unjoined-thread",
        "warning",
        "non-daemon thread started with no join reachable from close()",
        "join the thread from close()/shutdown(), or make it a daemon",
    ),
    Rule(
        "RS003",
        "unreaped-subprocess",
        "warning",
        "subprocess.Popen never waited/terminated — a zombie on exit paths",
        "call wait()/communicate() (or terminate()+wait()) on every path",
    ),
    Rule(
        "RS004",
        "unremoved-tempfile",
        "warning",
        "mkdtemp/NamedTemporaryFile(delete=False) without recorded cleanup",
        "register shutil.rmtree/os.unlink via try/finally, atexit, or a "
        "fixture finalizer",
    ),
    Rule(
        "RS005",
        "leaky-owner-class",
        "warning",
        "class acquires closeable resources in __init__ but close() is "
        "missing or provably incomplete",
        "define close()/__exit__ releasing every tracked attribute",
    ),
    Rule(
        "RS006",
        "unshutdown-executor",
        "warning",
        "executor/pool/mp.Queue created without a shutdown call",
        "use `with`, or call shutdown()/close()+join_thread() when done",
    ),
)

jaxlint.RULES.update({r.id: r for r in RS_RULES})


def _line_suppresses(line: str, rule: str) -> bool:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return False
    ids = m.group("ids")
    return ids is None or rule in {s.strip().upper() for s in ids.split(",")}


# ---------------------------------------------------------------------------
# resource classification
# ---------------------------------------------------------------------------

# explicit builtin/stdlib `open` spellings only — a bare tail match on
# "open" would hit every `x.open()` method in the repo
_OPEN_PATHS = {"open", "io.open", "gzip.open", "bz2.open", "lzma.open"}

_CLOSE_BY_KIND = {
    "file": {"close"},
    "mmap": {"close"},
    "socket": {"close", "shutdown", "detach"},
    "shm": {"close", "unlink"},
    "popen": {"wait", "communicate", "terminate", "kill"},
    "thread": {"join"},
    "executor": {"shutdown", "close", "terminate", "join"},
    "mpqueue": {"close", "join_thread", "shutdown"},
}

_RULE_BY_KIND = {
    "file": "RS001",
    "mmap": "RS001",
    "socket": "RS001",
    "shm": "RS001",
    "popen": "RS003",
    "thread": "RS002",
    "executor": "RS006",
    "mpqueue": "RS006",
}

# close-like entry points for the RS002/RS005 reachability closure
_CLOSE_ENTRY = {
    "close",
    "shutdown",
    "stop",
    "terminate",
    "join",
    "release",
    "kill",
    "aclose",
    "__exit__",
    "__del__",
}

# a call with one of these tails counts as "cleanup was recorded" for RS004
_CLEANUP_TAILS = {
    "rmtree",
    "rmdir",
    "remove",
    "unlink",
    "cleanup",
    "register",
    "addfinalizer",
    "addCleanup",
    "finalize",
}

# calls that adopt their Call arguments into managed lifetimes
_ADOPTING_TAILS = {"closing", "enter_context", "callback", "push"}


def _kw_const(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _resource_kind(call: ast.Call, imports: dict[str, str]) -> str | None:
    """Classify a Call as a resource acquisition, or None. Thread ctors
    with ``daemon=True`` and ``NamedTemporaryFile`` in its auto-delete
    default are deliberately NOT resources here (RS004 handles the
    ``delete=False`` form separately)."""
    path = _dotted(call.func, imports)
    if not path:
        return None
    tail = _tail(path)
    root = path.split(".", 1)[0]
    if path in _OPEN_PATHS:
        return "file"
    if tail == "open_memmap" or (tail == "memmap" and path != tail):
        return "mmap"
    if path == "mmap.mmap":
        return "mmap"
    if root == "socket" and tail in {
        "socket",
        "socketpair",
        "create_connection",
    }:
        return "socket"
    if tail == "SharedMemory":
        return "shm"
    if tail == "Popen":
        return "popen"
    if tail in {"ThreadPoolExecutor", "ProcessPoolExecutor"}:
        return "executor"
    if tail == "Pool" and path != tail:
        return "executor"
    if tail == "Queue" and root in {"multiprocessing", "mp"}:
        return "mpqueue"
    if tail in {"Thread", "Process"}:
        if _kw_const(call, "daemon") is True:
            return None
        return "thread"
    return None


def _is_tempdir_call(call: ast.Call, imports: dict[str, str]) -> str | None:
    """RS004 targets: 'tempdir' for mkdtemp, 'tempfile' for
    NamedTemporaryFile(delete=False); None otherwise."""
    tail = _tail(_dotted(call.func, imports))
    if tail == "mkdtemp":
        return "tempdir"
    if tail == "NamedTemporaryFile" and _kw_const(call, "delete") is False:
        return "tempfile"
    return None


def _iter_scope(body: list[ast.stmt]):
    """Walk a scope's nodes without descending into nested function/class
    bodies — those are scopes of their own."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# repo-wide fragments (RS005 finalize input)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResourceAttr:
    attr: str
    kind: str  # resource kind, or "closeable <ClassName>" for owned classes
    line: int
    col: int
    snippet: str
    suppressed: bool


@dataclasses.dataclass
class ClassSummary:
    name: str
    path: str
    line: int
    resources: list[ResourceAttr]
    # attr -> candidate owned-class ctor (resolved repo-wide in finalize)
    attr_class: dict[str, ResourceAttr]
    has_close: bool
    close_methods: list[str]
    closure_attrs: set[str]


@dataclasses.dataclass
class LifecycleFragment:
    path: str
    classes: dict[str, ClassSummary]


# ---------------------------------------------------------------------------
# per-file pass
# ---------------------------------------------------------------------------


class _ClassScan:
    """One class: collect __init__ resources + the close-reachability
    closure for RS005 fragments, and emit the class-local RS002/RS003/
    RS006 attribute findings."""

    def __init__(self, mod: "_ModuleScan", node: ast.ClassDef) -> None:
        self.mod = mod
        self.node = node
        self.methods: dict[str, ast.AST] = {}
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
        # attr -> (ctor call, resource kind, assign node) from ANY method
        self.attr_resources: dict[str, tuple[ast.Call, str, ast.AST]] = {}
        self.attr_class: dict[str, ResourceAttr] = {}
        self.init_attrs: set[str] = set()
        self.self_calls: dict[str, set[str]] = {}
        self.attr_mentions: dict[str, set[str]] = {}
        self.attr_calls: dict[str, set[tuple[str, str]]] = {}
        self.daemonized: set[str] = set()

    def _self_name(self, method: ast.AST) -> str:
        args = method.args.posonlyargs + method.args.args
        return args[0].arg if args else "self"

    def run(self) -> ClassSummary:
        for name, method in self.methods.items():
            self._scan_method(name, method)
        self._emit_attr_findings()
        return self._summary()

    def _scan_method(self, name: str, method: ast.AST) -> None:
        self_name = self._self_name(method)
        calls = self.self_calls.setdefault(name, set())
        mentions = self.attr_mentions.setdefault(name, set())
        receiver = self.attr_calls.setdefault(name, set())
        in_init = name in {"__init__", "__post_init__"}
        for node in _iter_scope(method.body):
            if isinstance(node, ast.Attribute) and (
                isinstance(node.value, ast.Name)
                and node.value.id == self_name
            ):
                mentions.add(node.attr)
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                base = node.func.value
                if isinstance(base, ast.Name) and base.id == self_name:
                    if node.func.attr in self.methods:
                        calls.add(node.func.attr)
                elif (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == self_name
                ):
                    receiver.add((base.attr, node.func.attr))
            if isinstance(node, ast.Assign):
                self._scan_assign(node, self_name, in_init)

    def _scan_assign(
        self, node: ast.Assign, self_name: str, in_init: bool
    ) -> None:
        if len(node.targets) != 1:
            return
        target = node.targets[0]
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == self_name
        ):
            return
        attr = target.attr
        value = node.value
        if not isinstance(value, ast.Call):
            return
        kind = _resource_kind(value, self.mod.imports)
        if kind is not None:
            self.attr_resources.setdefault(attr, (value, kind, node))
            if in_init:
                self.init_attrs.add(attr)
            return
        tail = _tail(_dotted(value.func, self.mod.imports))
        if in_init and tail and tail[0].isupper():
            self.attr_class.setdefault(
                attr,
                ResourceAttr(
                    attr=attr,
                    kind=f"closeable {tail}",
                    line=node.lineno,
                    col=node.col_offset,
                    snippet=self.mod.line(node.lineno),
                    suppressed=_line_suppresses(
                        self.mod.line(node.lineno), "RS005"
                    ),
                ),
            )

    def _daemonized_attrs(self) -> set[str]:
        """Attrs daemonized *after* construction: `self._t.daemon = True`."""
        out: set[str] = set()
        for method in self.methods.values():
            self_name = self._self_name(method)
            for node in _iter_scope(method.body):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and target.attr == "daemon"
                        and isinstance(target.value, ast.Attribute)
                        and isinstance(target.value.value, ast.Name)
                        and target.value.value.id == self_name
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True
                    ):
                        out.add(target.value.attr)
        return out

    def _closure(self) -> tuple[list[str], set[str], set[tuple[str, str]]]:
        entries = sorted(set(self.methods) & _CLOSE_ENTRY)
        seen: set[str] = set()
        frontier = list(entries)
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier.extend(self.self_calls.get(m, ()))
        attrs: set[str] = set()
        receiver: set[tuple[str, str]] = set()
        for m in seen:
            attrs |= self.attr_mentions.get(m, set())
            receiver |= self.attr_calls.get(m, set())
        return entries, attrs, receiver

    def _emit_attr_findings(self) -> None:
        entries, _closure_attrs, closure_recv = self._closure()
        all_recv: set[tuple[str, str]] = set()
        for recv in self.attr_calls.values():
            all_recv |= recv
        daemonized = self._daemonized_attrs()
        for attr, (call, kind, assign) in self.attr_resources.items():
            reaps = {m for (a, m) in all_recv if a == attr}
            if kind == "thread":
                if attr in daemonized or (attr, "start") not in all_recv:
                    continue
                if not entries:
                    continue  # no close path at all: that is RS005's call
                joined = {m for (a, m) in closure_recv if a == attr}
                if not joined & _CLOSE_BY_KIND["thread"]:
                    self.mod.emit(
                        "RS002",
                        assign,
                        f"non-daemon thread 'self.{attr}' of "
                        f"'{self.node.name}' is started but no join() is "
                        f"reachable from {'/'.join(entries)}",
                    )
            elif kind == "popen":
                if not reaps & _CLOSE_BY_KIND["popen"]:
                    self.mod.emit(
                        "RS003",
                        assign,
                        f"subprocess 'self.{attr}' of '{self.node.name}' "
                        "is never waited/terminated by any method",
                    )
            elif kind in {"executor", "mpqueue"}:
                if not reaps & _CLOSE_BY_KIND[kind]:
                    self.mod.emit(
                        "RS006",
                        assign,
                        f"executor 'self.{attr}' of '{self.node.name}' "
                        "is never shut down by any method",
                    )

    def _summary(self) -> ClassSummary:
        entries, closure_attrs, _ = self._closure()
        daemonized = self._daemonized_attrs()
        resources = []
        for attr in sorted(self.init_attrs):
            call, kind, assign = self.attr_resources[attr]
            if kind == "thread" and attr in daemonized:
                continue
            resources.append(
                ResourceAttr(
                    attr=attr,
                    kind=kind,
                    line=assign.lineno,
                    col=assign.col_offset,
                    snippet=self.mod.line(assign.lineno),
                    suppressed=_line_suppresses(
                        self.mod.line(assign.lineno), "RS005"
                    ),
                )
            )
        return ClassSummary(
            name=self.node.name,
            path=self.mod.rel_path,
            line=self.node.lineno,
            resources=resources,
            attr_class=dict(self.attr_class),
            has_close=bool(entries),
            close_methods=entries,
            closure_attrs=closure_attrs,
        )


class _ModuleScan:
    def __init__(
        self, tree: ast.Module, rel_path: str, lines: list[str]
    ) -> None:
        self.tree = tree
        self.rel_path = rel_path
        self.lines = lines
        self.imports = _collect_imports(tree)
        self.findings: list[Finding] = []
        self._emitted: set[tuple[str, int, int]] = set()

    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        key = (rule, node.lineno, node.col_offset)
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.findings.append(
            Finding(
                rule=rule,
                path=self.rel_path,
                line=node.lineno,
                col=node.col_offset,
                message=message,
                snippet=self.line(node.lineno),
            )
        )

    def run(self) -> LifecycleFragment:
        classes: dict[str, ClassSummary] = {}
        self._scan_scope(self.tree.body)
        for node in ast.walk(self.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self._scan_scope(node.body)
            elif isinstance(node, ast.ClassDef):
                summary = _ClassScan(self, node).run()
                classes.setdefault(summary.name, summary)
        return LifecycleFragment(path=self.rel_path, classes=classes)

    # -- one local scope (module body or a function body) ------------------

    def _scan_scope(self, body: list[ast.stmt]) -> None:
        managed: set[int] = set()
        candidates: list[tuple[str, str, ast.Call, ast.AST]] = []
        temp_candidates: list[tuple[str, str, ast.AST]] = []
        attr_root_ids: set[int] = set()
        bare_names: set[str] = set()
        method_calls: dict[str, set[str]] = {}
        owned_escapes: set[str] = set()
        cleanup_seen = False
        store_targets: set[int] = set()

        nodes = list(_iter_scope(body))
        for node in nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        managed.add(id(expr))
                        for arg in expr.args:
                            if isinstance(arg, ast.Call):
                                managed.add(id(arg))
            elif isinstance(node, ast.Call):
                tail = _tail(_dotted(node.func, self.imports))
                if tail in _ADOPTING_TAILS:
                    for arg in node.args:
                        if isinstance(arg, ast.Call):
                            managed.add(id(arg))
                if tail in _CLEANUP_TAILS:
                    cleanup_seen = True

        for node in nodes:
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ):
                attr_root_ids.add(id(node.value))
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                base = node.func.value
                if isinstance(base, ast.Name):
                    method_calls.setdefault(base.id, set()).add(
                        node.func.attr
                    )
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                value = getattr(node, "value", None)
                if value is not None:
                    for sub in ast.walk(value):
                        if isinstance(sub, ast.Name):
                            owned_escapes.add(sub.id)
            if isinstance(node, ast.Assign):
                has_container_target = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets
                )
                if has_container_target:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name):
                            owned_escapes.add(sub.id)
                target = node.targets[0]
                if (
                    len(node.targets) == 1
                    and isinstance(target, ast.Name)
                    and isinstance(node.value, ast.Call)
                    and id(node.value) not in managed
                ):
                    store_targets.add(id(target))
                    kind = _resource_kind(node.value, self.imports)
                    if kind is not None and kind != "thread":
                        candidates.append(
                            (target.id, kind, node.value, node)
                        )
                    temp = _is_tempdir_call(node.value, self.imports)
                    if temp is not None:
                        temp_candidates.append((target.id, temp, node))

        for node in nodes:
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and id(node) not in attr_root_ids
            ):
                bare_names.add(node.id)

        for var, kind, call, assign in candidates:
            if var in bare_names:
                continue  # escapes: passed/returned/stored — ownership moved
            if method_calls.get(var, set()) & _CLOSE_BY_KIND[kind]:
                continue
            rule = _RULE_BY_KIND[kind]
            noun = {
                "popen": "subprocess",
                "executor": "executor",
                "mpqueue": "mp.Queue",
            }.get(kind, kind)
            if rule == "RS001":
                message = (
                    f"'{var}' holds an open {noun} but is neither a "
                    "`with` target nor closed on any path in this scope"
                )
            elif rule == "RS003":
                message = (
                    f"subprocess '{var}' is never waited/terminated in "
                    "this scope — a zombie on every exit path"
                )
            else:
                message = (
                    f"{noun} '{var}' is never shut down in this scope"
                )
            self.emit(rule, assign, message)

        for var, temp, assign in temp_candidates:
            if cleanup_seen or var in owned_escapes:
                continue
            if temp == "tempfile" and var in bare_names:
                # the NamedTemporaryFile object was handed off; its
                # delete=False file may be someone else's to remove
                continue
            what = (
                "temp dir from mkdtemp()"
                if temp == "tempdir"
                else "NamedTemporaryFile(delete=False)"
            )
            self.emit(
                "RS004",
                assign,
                f"'{var}' names a {what} with no recorded cleanup "
                "(rmtree/unlink/atexit/finalizer) in this scope",
            )


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_source(
    source: str, rel_path: str, tree: ast.Module | None = None
) -> tuple[list[Finding], LifecycleFragment]:
    """Per-file RS pass. Returns (findings, fragment); the fragment feeds
    the repo-wide :func:`finalize` join for RS005. Unparseable files
    contribute nothing (jaxlint's JX000 already reports the SyntaxError).
    """
    lines = source.splitlines()
    if tree is None:
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError:
            return [], LifecycleFragment(path=rel_path, classes={})
    scan = _ModuleScan(tree, rel_path, lines)
    fragment = scan.run()
    findings = scan.findings
    jaxlint._apply_suppressions(findings, lines)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, fragment


def finalize(fragments: list[LifecycleFragment]) -> list[Finding]:
    """Repo-wide RS005: join per-file class fragments, resolve owned-class
    attributes against every class seen anywhere (first definition wins on
    name collisions), then flag owners with tracked resources whose close
    path is missing or provably incomplete. Suppression state was captured
    at scan time from the resource's own source line."""
    has_close: dict[str, bool] = {}
    for fragment in fragments:
        for name, summary in fragment.classes.items():
            has_close.setdefault(name, summary.has_close)

    findings: list[Finding] = []
    for fragment in fragments:
        for summary in fragment.classes.values():
            tracked = list(summary.resources)
            for attr, res in sorted(summary.attr_class.items()):
                owned = res.kind.split(" ", 1)[1]
                if has_close.get(owned):
                    tracked.append(res)
            if not tracked:
                continue
            if not summary.has_close:
                anchor = min(tracked, key=lambda r: r.line)
                attrs = ", ".join(f"self.{r.attr}" for r in tracked)
                findings.append(
                    Finding(
                        rule="RS005",
                        path=summary.path,
                        line=anchor.line,
                        col=anchor.col,
                        message=(
                            f"class '{summary.name}' acquires "
                            f"{len(tracked)} closeable resource(s) in "
                            f"__init__ ({attrs}) but defines no "
                            "close()/__exit__"
                        ),
                        snippet=anchor.snippet,
                        suppressed=anchor.suppressed,
                    )
                )
                continue
            for res in tracked:
                if res.attr in summary.closure_attrs:
                    continue
                if res.kind in {"thread", "popen", "executor", "mpqueue"}:
                    # RS002/RS003/RS006 already judge these attrs against
                    # the close path; re-reporting them here double-counts
                    continue
                findings.append(
                    Finding(
                        rule="RS005",
                        path=summary.path,
                        line=res.line,
                        col=res.col,
                        message=(
                            f"'self.{res.attr}' ({res.kind}) of "
                            f"'{summary.name}' is acquired in __init__ "
                            "but never touched by "
                            f"{'/'.join(summary.close_methods)} — the "
                            "close path provably misses it"
                        ),
                        snippet=res.snippet,
                        suppressed=res.suppressed,
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_lifecycle(source: str, rel_path: str = "mod.py") -> list[Finding]:
    """Single-file convenience for tests/fixtures: per-file pass plus a
    finalize over just this file's fragment."""
    findings, fragment = check_source(source, rel_path)
    findings = findings + finalize([fragment])
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
