"""Sharding rules: which parameter/batch dimension lives on which mesh axis.

Parameter layout (SURVEY.md §7.5; vocab sizes from top11 params.txt make the
embedding tables the only big tensors — 360k x d and 342k x d):

- ``terminal_embedding`` / ``path_embedding`` tables: row-sharded over
  ``model`` (vocab dim). XLA turns the gathers into local gathers + psum.
- output head: column-sharded over ``model`` (label dim) — the label vocab
  also scales with corpus size; the margin-head weight is row-sharded since
  its layout is [label, encode].
- encoder Dense/LayerNorm/attention vector: replicated (tiny at any scale).

Batch layout: batch dim over ``data``, bag dim L over ``ctx``; labels and
masks over ``data`` only. Gradients reduce over ``data`` via the psum XLA
inserts automatically under jit.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from code2vec_tpu.parallel.mesh import AXIS_CTX, AXIS_DATA, AXIS_MODEL


def _spec_for_param(path: tuple[str, ...], mesh: Mesh, shape=None) -> P:
    """Sharding spec for one parameter (or adam-moment) leaf.

    A dim is only sharded if its size divides evenly by the axis; otherwise
    it silently replicates. For the big tables, pad the vocab up front
    (``pad_to_multiple``) so the shard actually happens — a few dummy rows
    on a 360k-row table cost nothing.
    """
    names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    joined = "/".join(names)
    model_axis = AXIS_MODEL if mesh.shape[AXIS_MODEL] > 1 else None

    def axis_if_divisible(axis, dim):
        if axis is None or shape is None:
            return axis
        if dim >= len(shape):
            return None
        return axis if shape[dim] % mesh.shape[axis] == 0 else None

    if "terminal_embedding" in joined or "path_embedding" in joined:
        return P(axis_if_divisible(model_axis, 0), None)  # row-shard vocab
    if "output_dense" in joined:
        if joined.endswith("kernel"):
            return P(None, axis_if_divisible(model_axis, 1))  # [E, label]
        return P(axis_if_divisible(model_axis, 0))  # bias [label]
    if "output_margin_weight" in joined:
        return P(axis_if_divisible(model_axis, 0), None)  # [label, E]
    return P()  # replicate the small encoder params


def pad_to_multiple(count: int, multiple: int) -> int:
    """Round a vocab/label count up so the table shards evenly."""
    return -(-count // multiple) * multiple


def param_shardings(mesh: Mesh, params: Any) -> Any:
    """NamedSharding pytree matching ``params`` (concrete or abstract)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, _spec_for_param(path, mesh, getattr(leaf, "shape", None))
        ),
        params,
    )


def batch_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    data_axis = AXIS_DATA if mesh.shape[AXIS_DATA] > 1 else None
    ctx_axis = AXIS_CTX if mesh.shape[AXIS_CTX] > 1 else None
    row = NamedSharding(mesh, P(data_axis))
    bag = NamedSharding(mesh, P(data_axis, ctx_axis))
    return {
        "ids": row,
        "starts": bag,
        "paths": bag,
        "ends": bag,
        "labels": row,
        "example_mask": row,
    }


@functools.lru_cache(maxsize=16)
def cached_batch_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """The batch-layout NamedShardings, cached per mesh — the canonical
    accessor for every per-batch placement site (train loop ``to_device``,
    the prefetch producer, device-epoch constraints). NamedShardings are
    shape-free, so ALL bag widths of a bucketed run (every ``[B, L_b]`` in
    the ladder) reuse the same cached dict: switching bucket widths
    mid-epoch costs no sharding reconstruction. Callers must treat the
    returned dict as immutable."""
    return batch_shardings(mesh)


def shard_batch(mesh: Mesh, batch: dict[str, np.ndarray]) -> dict[str, jax.Array]:
    """Place a host batch onto the mesh with the batch layout above."""
    shardings = cached_batch_shardings(mesh)
    return {k: jax.device_put(v, shardings[k]) for k, v in batch.items()}


def state_shardings(mesh: Mesh, state):
    """A TrainState-shaped pytree of NamedShardings: params and the adam
    moments (which mirror the param tree, so the same path rules apply) by
    the parameter rules; RNG, step counter, and other scalars replicated."""
    replicated = NamedSharding(mesh, P())
    by_rules = lambda tree: jax.tree_util.tree_map_with_path(  # noqa: E731
        lambda path, leaf: NamedSharding(
            mesh, _spec_for_param(path, mesh, getattr(leaf, "shape", None))
        ),
        tree,
    )
    return state.replace(
        params=by_rules(state.params),
        opt_state=by_rules(state.opt_state),
        dropout_rng=replicated,
        step=replicated,
    )


def shard_state(mesh: Mesh, state):
    """Place a TrainState onto the mesh per ``state_shardings``."""
    sharding = state_shardings(mesh, state)
    return state.replace(
        params=jax.device_put(state.params, sharding.params),
        opt_state=jax.device_put(state.opt_state, sharding.opt_state),
        dropout_rng=jax.device_put(state.dropout_rng, sharding.dropout_rng),
    )


def retrieval_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Placement for the serving top-k retrieval matmul (serve/retrieval.py).

    The exported code-vector matrix is ``[n_methods, E]`` — the same
    tall-skinny layout as the embedding tables, so it takes the same rule:
    row-sharded over ``model`` (the corpus scales with method count the
    way the tables scale with vocab). The query block ``[Q, E]`` and each
    query's result are tiny and replicate. ``sims = rows @ q.T`` is then a
    fully local matmul per shard ([rows/n, E] x [E, Q]); the top-k over
    the sharded rows axis is the only cross-shard step and GSPMD inserts
    the gather for it. Like ``_spec_for_param``, an indivisible row count
    silently replicates — pad rows at load if the shard must happen."""
    model_axis = AXIS_MODEL if mesh.shape[AXIS_MODEL] > 1 else None
    return {
        "rows": NamedSharding(mesh, P(model_axis, None)),
        "query": NamedSharding(mesh, P()),
        "out": NamedSharding(mesh, P()),
    }


def ann_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Placement for the ANN index's search arrays (ann/index.py).

    The cell-major stores — codes ``[n_list, C, M]``, per-row scales/bias
    ``[n_list, C]``, row ids ``[n_list, C]`` — are tall-skinny in the
    *cell* dimension, so cells take the embedding tables' rule: row-shard
    over ``model``. The coarse centroids, PQ codebooks, per-query LUT, and
    the query/shortlist blocks are tiny at any corpus scale and replicate.
    Like ``_spec_for_param``, an indivisible cell count silently
    replicates — the searcher pads ``n_list`` (with ``-inf`` coarse bias
    so pad cells are never probed) so the shard actually happens."""
    model_axis = AXIS_MODEL if mesh.shape[AXIS_MODEL] > 1 else None
    return {
        "codes": NamedSharding(mesh, P(model_axis, None, None)),
        "scales": NamedSharding(mesh, P(model_axis, None)),
        "bias": NamedSharding(mesh, P(model_axis, None)),
        "ids": NamedSharding(mesh, P(model_axis, None)),
        "centroids": NamedSharding(mesh, P()),
        "cell_bias": NamedSharding(mesh, P()),
        "codebooks": NamedSharding(mesh, P()),
        "query": NamedSharding(mesh, P()),
    }


# ---------------------------------------------------------------------------
# PartitionSpec serialization — the mesh-reshape restore primitive
#
# A checkpoint that only stores arrays is bound to the topology it was saved
# on; storing the *specs* alongside lets restore re-bind them to whatever
# mesh the resumed run declares (checkpoint.py writes the doc as a
# `shardings.json` sidecar, restore rebuilds NamedShardings from it). Specs
# are mesh-shape-free — `P('model', None)` means the same thing on a 2- or
# 4-way model axis — which is exactly why they, and not device layouts, are
# the right thing to persist.
# ---------------------------------------------------------------------------


def _spec_entries(spec: P) -> list:
    """JSON form of a PartitionSpec: one entry per dim — None, an axis
    name, or a list of axis names (a dim sharded over several axes)."""
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _entries_spec(entries: list) -> P:
    return P(*(tuple(e) if isinstance(e, list) else e for e in entries))


def pytree_spec_doc(tree: Any) -> dict:
    """Serializable sharding doc for a (possibly host-side) pytree.

    ``{"mesh_shape": {axis: size} | None, "specs": {keypath: entries|null}}``
    — mesh_shape comes from the first NamedSharding-carrying leaf (one mesh
    per state by construction); leaves without a NamedSharding (host numpy,
    single-device arrays) record null and restore with the template's
    placement.
    """
    specs: dict[str, list | None] = {}
    mesh_shape: dict[str, int] | None = None

    def record(path, leaf):
        nonlocal mesh_shape
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            if mesh_shape is None:
                mesh_shape = dict(sharding.mesh.shape)
            specs[jax.tree_util.keystr(path)] = _spec_entries(sharding.spec)
        else:
            specs[jax.tree_util.keystr(path)] = None
        return leaf

    jax.tree_util.tree_map_with_path(record, tree)
    return {"mesh_shape": mesh_shape, "specs": specs}


def rebind_abstract_shardings(mesh: Mesh, abstract_tree: Any, doc: dict) -> Any:
    """Re-bind a saved sharding doc onto ``mesh``: the restore target tree.

    For each leaf of ``abstract_tree`` (ShapeDtypeStructs from the restore
    template) with a recorded spec, returns a ShapeDtypeStruct whose
    sharding is ``NamedSharding(mesh, spec)`` — the checkpointed layout
    re-expressed on the *new* topology. Validation (axis names the new mesh
    does not declare) is the caller's job via
    ``analysis.sharding_check.validate_runtime_spec``; this function only
    applies the divisibility rule: a dim whose size no longer divides the
    (resized) axis falls back to replicated for that dim, mirroring
    ``_spec_for_param``.
    """
    specs: dict[str, list | None] = doc.get("specs", {})

    def rebind(path, leaf):
        entries = specs.get(jax.tree_util.keystr(path))
        if entries is None:
            return leaf
        shape = getattr(leaf, "shape", ())
        fitted: list = []
        for dim, entry in enumerate(entries):
            axes = entry if isinstance(entry, list) else (
                [] if entry is None else [entry]
            )
            span = 1
            for axis in axes:
                span *= mesh.shape[axis]
            if axes and (dim >= len(shape) or shape[dim] % span):
                fitted.append(None)  # indivisible on the new mesh: replicate
            else:
                fitted.append(entry)
        return jax.ShapeDtypeStruct(
            shape,
            leaf.dtype,
            sharding=NamedSharding(mesh, _entries_spec(fitted)),
        )

    return jax.tree_util.tree_map_with_path(rebind, abstract_tree)
