"""Sharded train/eval steps: the single-chip step math compiled over a mesh.

Under jit with NamedSharding-annotated inputs, XLA's SPMD partitioner
inserts every collective (SURVEY.md §5.8): gradient all-reduce over
``data``, embedding-gather combines and label-head logit all-gather over
``model``, softmax-statistic reductions over ``ctx``. The step functions
are byte-identical to the single-chip ones (train.step.build_*_step_fn) —
only the in/out shardings differ, which is the point of designing
mesh-first.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from code2vec_tpu.models.code2vec import Code2VecConfig
from code2vec_tpu.parallel.mesh import AXIS_DATA
from code2vec_tpu.parallel.shardings import batch_shardings, state_shardings
from code2vec_tpu.train.step import (
    TrainState,
    build_eval_step_fn,
    build_train_step_fn,
    contract_step,
)


def make_parallel_train_step(
    model_config: Code2VecConfig, class_weights, mesh: Mesh, state: TrainState,
    table_update: str = "dense",
):
    """jit the train step with explicit mesh shardings; ``state`` supplies
    the pytree structure for the annotations. The same trace-time contract
    as the single-chip step applies (tracing sees GLOBAL shapes, so the
    [B, L] patterns hold unchanged under any mesh)."""
    state_sh = state_shardings(mesh, state)
    return jax.jit(
        contract_step(
            build_train_step_fn(model_config, class_weights, table_update)
        ),
        in_shardings=(state_sh, batch_shardings(mesh)),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def make_parallel_eval_step(
    model_config: Code2VecConfig, class_weights, mesh: Mesh, state: TrainState
):
    data_axis = AXIS_DATA if mesh.shape[AXIS_DATA] > 1 else None
    row = NamedSharding(mesh, P(data_axis))
    out_sh = {
        "loss": NamedSharding(mesh, P()),
        "preds": row,
        "max_logit": row,
        "code_vector": row,
        "attention": row,
    }
    return jax.jit(
        contract_step(build_eval_step_fn(model_config, class_weights)),
        in_shardings=(state_shardings(mesh, state), batch_shardings(mesh)),
        out_shardings=out_sh,
    )
