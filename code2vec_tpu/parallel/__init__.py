"""Parallelism over a jax.sharding.Mesh — dp / tp / sp, multi-host init.

The reference is strictly single-device (SURVEY.md §2: no DP/TP/PP/SP, no
NCCL/MPI). This package is the TPU-native replacement: shardings over a
(data, model, ctx) mesh, XLA collectives over ICI/DCN, multi-host process
groups via jax.distributed.
"""

from code2vec_tpu.parallel.mesh import (
    AXIS_CTX,
    AXIS_DATA,
    AXIS_MODEL,
    make_mesh,
)
from code2vec_tpu.parallel.shardings import (
    batch_shardings,
    param_shardings,
    shard_batch,
    shard_state,
    state_shardings,
)
