"""Multi-host (pod / multi-slice) process setup and host-local data feeding.

Replaces the NCCL/MPI role of conventional frameworks (the reference has no
distributed backend at all — SURVEY.md §5.8): jax.distributed forms the
process group, XLA compiles the collectives, ICI carries intra-slice traffic
and DCN carries inter-slice.

Host-local batches become global arrays via
``jax.make_array_from_process_local_data`` — each host loads only its
round-robin share of the corpus (``load_corpus(shard=(index, count))``;
record i is local iff ``i % count == index``, see
``data.reader.CorpusData.local_rows_of_global``).
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from code2vec_tpu.parallel.shardings import batch_shardings

logger = logging.getLogger(__name__)


def initialize_from_env() -> bool:
    """Initialize jax.distributed from standard env vars when present
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, or the TPU pod
    metadata that jax autodetects). No-op for single-process runs."""
    coordinator = os.environ.get("COORDINATOR_ADDRESS")
    num_processes = os.environ.get("NUM_PROCESSES")
    process_id = os.environ.get("PROCESS_ID")
    if coordinator and num_processes and process_id:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id),
        )
        logger.info(
            "jax.distributed up: process %s/%s via %s",
            process_id,
            num_processes,
            coordinator,
        )
        return True
    if os.environ.get("JAX_AUTO_DISTRIBUTED", ""):
        jax.distributed.initialize()  # TPU pod autodetection
        return True
    return False


def global_batch(mesh: Mesh, full_batch: dict[str, np.ndarray]) -> dict[str, jax.Array]:
    """Assemble a global device batch when every host holds the FULL batch
    (the loop's epochs are seeded identically on all processes).

    ``make_array_from_callback`` lets each host serve exactly the slices its
    addressable devices need, for *any* batch sharding — data-sharded,
    replicated, or mixed — with no per-process divisibility constraint.
    """
    shardings = batch_shardings(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, shardings[k]) for k, v in full_batch.items()}
    return {
        k: jax.make_array_from_callback(
            v.shape, shardings[k], lambda idx, v=v: v[idx]
        )
        for k, v in full_batch.items()
    }


def local_to_global_batch(
    mesh: Mesh, local_batch: dict[str, np.ndarray]
) -> dict[str, jax.Array]:
    """Assemble a global device batch from HOST-LOCAL sub-batches (the
    host-sharded corpus path, SURVEY §7.4): each process supplies its
    ``batch/n_hosts`` rows and ``make_array_from_process_local_data``
    stitches them along the data-sharded dimension. Rows land in process
    order (a host's devices are contiguous in jax device order), so process
    p owns global rows [p*feed, (p+1)*feed).
    """
    shardings = batch_shardings(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, shardings[k]) for k, v in local_batch.items()}
    return {
        k: jax.make_array_from_process_local_data(shardings[k], v)
        for k, v in local_batch.items()
    }


def allgather_to_host(x: jax.Array) -> np.ndarray:
    """Fetch a possibly cross-process-sharded array to host numpy.

    np.asarray on an array that spans non-addressable devices raises; the
    multihost allgather replicates it first. Single-process arrays take the
    direct path.
    """
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
