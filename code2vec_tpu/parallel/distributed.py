"""Multi-host (pod / multi-slice) process setup and host-local data feeding.

Replaces the NCCL/MPI role of conventional frameworks (the reference has no
distributed backend at all — SURVEY.md §5.8): jax.distributed forms the
process group, XLA compiles the collectives, ICI carries intra-slice traffic
and DCN carries inter-slice.

Host-local batches become global arrays via
``jax.make_array_from_process_local_data`` — each FEED GROUP (the
processes whose devices cover the same data-axis coords — see
``feed_groups``) loads only its round-robin share of the corpus
(``load_corpus(shard=feed_groups(mesh))``; record i is local iff
``i % n_groups == group``, see
``data.reader.CorpusData.local_rows_of_global``). For pure-DP meshes a
group is just one process; a model/ctx axis spanning processes makes the
group's members replicas that load identical shards.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from code2vec_tpu.parallel.shardings import cached_batch_shardings

logger = logging.getLogger(__name__)

# the batch assemblers below run once per train/eval STEP (and, with
# --prefetch_batches, on the input-pipeline producer thread) — rebuilding
# the six NamedShardings per call is pure per-step host overhead, and the
# layout is a function of the mesh alone (shape-free: every bucket width
# of a bucketed run shares it). The cache now lives in parallel.shardings
# so every placement site shares ONE memo; this alias keeps the
# historical local name.
_cached_batch_shardings = cached_batch_shardings


def initialize_from_env() -> bool:
    """Initialize jax.distributed from standard env vars when present
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, or the TPU pod
    metadata that jax autodetects). No-op for single-process runs."""
    coordinator = os.environ.get("COORDINATOR_ADDRESS")
    num_processes = os.environ.get("NUM_PROCESSES")
    process_id = os.environ.get("PROCESS_ID")
    if coordinator and num_processes and process_id:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id),
        )
        logger.info(
            "jax.distributed up: process %s/%s via %s",
            process_id,
            num_processes,
            coordinator,
        )
        return True
    if os.environ.get("JAX_AUTO_DISTRIBUTED", ""):
        jax.distributed.initialize()  # TPU pod autodetection
        return True
    return False


def process_info() -> dict:
    """This process's identity block for telemetry manifests
    (obs.events.run_manifest): who am I in the pod, on what hardware.
    Initializes the backend if nothing has yet."""
    devices = jax.local_devices()
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else None,
        "local_device_count": len(devices),
        "global_device_count": jax.device_count(),
    }


def global_batch(mesh: Mesh, full_batch: dict[str, np.ndarray]) -> dict[str, jax.Array]:
    """Assemble a global device batch when every host holds the FULL batch
    (the loop's epochs are seeded identically on all processes).

    ``make_array_from_callback`` lets each host serve exactly the slices its
    addressable devices need, for *any* batch sharding — data-sharded,
    replicated, or mixed — with no per-process divisibility constraint.

    Process-local (no collective), so the prefetch producer thread
    (train/prefetch.py) may call it off the main thread.
    """
    shardings = _cached_batch_shardings(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, shardings[k]) for k, v in full_batch.items()}
    return {
        k: jax.make_array_from_callback(
            v.shape, shardings[k], lambda idx, v=v: v[idx]
        )
        for k, v in full_batch.items()
    }


def local_to_global_batch(
    mesh: Mesh, local_batch: dict[str, np.ndarray]
) -> dict[str, jax.Array]:
    """Assemble a global device batch from HOST-LOCAL sub-batches (the
    host-sharded corpus path, SURVEY §7.4): each process supplies its
    ``batch/n_groups`` rows and ``make_array_from_process_local_data``
    stitches them along the data-sharded dimension. Rows land by data-axis
    coord, and ``feed_groups`` orders groups by their coords, so group g
    owns global rows [g*feed, (g+1)*feed); the processes replicating a
    group (model/ctx axes spanning processes) supply identical sub-batches
    for the same rows.

    Process-local (``make_array_from_process_local_data`` assembles from
    local blocks without a collective), so the prefetch producer thread
    (train/prefetch.py) may call it off the main thread.
    """
    shardings = _cached_batch_shardings(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, shardings[k]) for k, v in local_batch.items()}
    return {
        k: jax.make_array_from_process_local_data(shardings[k], v)
        for k, v in local_batch.items()
    }


def feed_groups(mesh: Mesh) -> tuple[int, int]:
    """Host-sharded feeding groups for this mesh: (my_group, n_groups).

    A feed group is the set of processes whose devices cover the SAME
    data-axis coordinates — with a model/ctx axis spanning processes, those
    processes are replicas of the same batch rows and must load the SAME
    corpus shard and supply identical sub-batches (a per-process round-robin
    shard would hand replicas different rows, which cannot assemble into
    one global array). Pure-DP meshes degenerate to group == process.

    Shard a corpus for this layout with ``load_corpus(shard=feed_groups(mesh))``.
    """
    coords: dict[int, set[int]] = {}
    for pos, dev in np.ndenumerate(mesh.devices):
        coords.setdefault(dev.process_index, set()).add(int(pos[0]))
    canon = {p: tuple(sorted(c)) for p, c in coords.items()}
    if jax.process_index() not in canon:
        raise ValueError(
            f"process {jax.process_index()} has no devices in the mesh "
            f"(mesh covers processes {sorted(canon)}); the mesh axes must "
            "span every participating host's devices for host-sharded "
            "feeding"
        )
    groups = sorted(set(canon.values()))
    covered = [c for g in groups for c in g]
    if sorted(covered) != list(range(mesh.devices.shape[0])):
        raise ValueError(
            "processes' data-axis coverage overlaps partially "
            f"({canon}); host-sharded feeding needs processes to partition "
            "the data axis into clean groups"
        )
    for g in groups:
        if list(g) != list(range(g[0], g[-1] + 1)):
            raise ValueError(
                f"feed group {g} covers non-contiguous data coords; the "
                "host-sharded feed lays group rows out contiguously"
            )
    if len({len(g) for g in groups}) != 1:
        raise ValueError(
            f"feed groups cover unequal data-axis shares ({groups}); "
            "equal per-group sub-batches need a uniform partition"
        )
    return groups.index(canon[jax.process_index()]), len(groups)


def allgather_to_host(x: jax.Array) -> np.ndarray:
    """Fetch a possibly cross-process-sharded array to host numpy.

    np.asarray on an array that spans non-addressable devices raises; the
    multihost allgather replicates it first. Single-process arrays take the
    direct path.
    """
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
