"""Device mesh construction.

Three logical axes (SURVEY.md §7.5 + §5.7-5.8):

- ``data``  — batch (pure data parallelism; gradient psum over ICI)
- ``model`` — tensor parallelism: the two embedding tables row-sharded over
  vocab (360k+ rows at top11 scale) and the label head column-sharded
- ``ctx``   — context/sequence parallelism: the bag axis L of each batch is
  sharded, for the large-bag regime (whole-file context bags)

Pipeline (pp) and expert (ep) axes deliberately do not exist: the model is a
two-layer bag encoder with no sequential layer stack to pipeline and no MoE
routing — dp/tp/sp are the parallelism axes this architecture admits
(documented for parity auditing against SURVEY.md §2).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_CTX = "ctx"
AXES = (AXIS_DATA, AXIS_MODEL, AXIS_CTX)


def make_mesh(
    data: int | None = None,
    model: int = 1,
    ctx: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (data, model, ctx) mesh. ``data=None`` absorbs all remaining
    devices. On real TPU slices mesh_utils picks an ICI-friendly layout."""
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        data = len(devices) // (model * ctx)
    n = data * model * ctx
    if n > len(devices):
        raise ValueError(
            f"mesh ({data}x{model}x{ctx}={n}) exceeds {len(devices)} devices"
        )
    if n == len(devices):
        try:
            arr = mesh_utils.create_device_mesh((data, model, ctx), devices=devices)
        except (ValueError, AssertionError):
            arr = np.asarray(devices).reshape(data, model, ctx)
    else:
        arr = np.asarray(devices[:n]).reshape(data, model, ctx)
    return Mesh(arr, AXES)


def single_device_mesh(device=None) -> Mesh:
    """Degenerate 1x1x1 mesh: the single-chip path uses the same code."""
    device = device if device is not None else jax.devices()[0]
    return make_mesh(data=1, model=1, ctx=1, devices=[device])
