"""Context (sequence) parallelism for the attention pooling.

Long-bag regime: a method's path-context bag can far exceed HBM-friendly
sizes when extraction caps are lifted (whole-file bags). The bag axis L is
sharded over the ``ctx`` mesh axis and the masked softmax + weighted sum is
computed with the streaming-softmax decomposition:

    m   = pmax(max(local_scores))            one scalar per row
    e   = exp(local_scores - m)
    s   = psum(sum(e))
    out = psum(e @ local_contexts) / s

This is the exact counterpart of ring attention specialized to a rank-1
query: because code2vec attention has a single learned query vector (not
L x L), no K/V rotation is needed — one pmax + two psums over ICI are
information-optimal, touching each context shard exactly once. (Ring
attention's O(L^2) tiling degenerates to this when the query count is 1;
see PAPERS.md ring-attention lineage.)

Used under ``shard_map``; the GSPMD path in ops.attention reaches the same
collectives automatically, this module is the explicit/inspectable variant
the Pallas kernel plugs into.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.ops.attention import streaming_attention_pool
from code2vec_tpu.parallel.mesh import AXIS_CTX


def context_parallel_attention_pool(
    mesh: Mesh,
    contexts: jnp.ndarray,  # [B, L, E], L sharded over ctx
    mask: jnp.ndarray,  # [B, L]
    attn_param: jnp.ndarray,  # [E] replicated
):
    """shard_map-wrapped pooling; returns (code_vector [B, E] replicated
    over ctx, attention [B, L] sharded like the input). The per-shard math
    (and the single-device ``attn_impl="streaming"`` model variant) lives
    in ops.attention.streaming_attention_pool."""
    return jax.shard_map(
        partial(streaming_attention_pool, axis_name=AXIS_CTX),
        mesh=mesh,
        in_specs=(P(None, AXIS_CTX, None), P(None, AXIS_CTX), P()),
        out_specs=(P(), P(None, AXIS_CTX)),
    )(contexts, mask, attn_param)
