"""Context (sequence) parallelism for the attention pooling.

Long-bag regime: a method's path-context bag can far exceed HBM-friendly
sizes when extraction caps are lifted (whole-file bags). The bag axis L is
sharded over the ``ctx`` mesh axis and the masked softmax + weighted sum is
computed with the streaming-softmax decomposition:

    m   = pmax(max(local_scores))            one scalar per row
    e   = exp(local_scores - m)
    s   = psum(sum(e))
    out = psum(e @ local_contexts) / s

This is the exact counterpart of ring attention specialized to a rank-1
query: because code2vec attention has a single learned query vector (not
L x L), no K/V rotation is needed — one pmax + two psums over ICI are
information-optimal, touching each context shard exactly once. (Ring
attention's O(L^2) tiling degenerates to this when the query count is 1;
see PAPERS.md ring-attention lineage.)

Used under ``shard_map``; the GSPMD path in ops.attention reaches the same
collectives automatically, this module is the explicit/inspectable variant
the Pallas kernel plugs into.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.ops.attention import NINF
from code2vec_tpu.parallel.mesh import AXIS_CTX


def _local_pool(contexts, mask, attn_param, axis_name):
    scores = jnp.einsum("ble,e->bl", contexts, attn_param).astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    masked = scores * mask + (1.0 - mask) * NINF
    local_max = jnp.max(masked, axis=-1)
    # stop_gradient INSIDE the pmax: pmax has no AD rule, and none is
    # needed — the softmax max-shift is gradient-free (the -dm terms cancel
    # exactly in the normalization). Stopping the operand zeroes its tangent
    # symbolically, so AD never differentiates the collective, keeping
    # backward through the pool exact AND trainable.
    global_max = jax.lax.pmax(jax.lax.stop_gradient(local_max), axis_name)
    e = jnp.exp(masked - global_max[:, None])
    local_sum = jnp.sum(e, axis=-1)
    global_sum = jax.lax.psum(local_sum, axis_name)
    weights = e / jnp.maximum(global_sum[:, None], 1e-38)
    local_cv = jnp.einsum("bl,ble->be", weights.astype(contexts.dtype), contexts)
    code_vector = jax.lax.psum(local_cv, axis_name)
    return code_vector, weights


def context_parallel_attention_pool(
    mesh: Mesh,
    contexts: jnp.ndarray,  # [B, L, E], L sharded over ctx
    mask: jnp.ndarray,  # [B, L]
    attn_param: jnp.ndarray,  # [E] replicated
):
    """shard_map-wrapped pooling; returns (code_vector [B, E] replicated
    over ctx, attention [B, L] sharded like the input)."""
    return jax.shard_map(
        partial(_local_pool, axis_name=AXIS_CTX),
        mesh=mesh,
        in_specs=(P(None, AXIS_CTX, None), P(None, AXIS_CTX), P()),
        out_specs=(P(), P(None, AXIS_CTX)),
    )(contexts, mask, attn_param)
