"""Python interface to the native C++ path-context extractor.

The extractor (``extractor/`` — lexer, Java parser, normalizer, path
enumerator; the TPU-framework equivalent of the reference's Scala/JVM
notebook pipeline, SURVEY.md §2.3) is exposed two ways:

- ``extract_source``: in-process via ctypes against ``libc2v.so`` — parse a
  Java source string, get records + vocabs back without touching disk;
- ``extract_dataset``: the ``c2v-extract`` CLI over a methods.txt, writing
  the five corpus artifacts (the createDataset equivalent, ipynb cell11).

``build_extractor`` compiles both with cmake+ninja on first use.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import sys
from dataclasses import dataclass, field

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(_PKG_DIR)


import functools


def _source_digest(src_dir: str) -> str:
    import hashlib

    h = hashlib.sha256()
    candidates = [os.path.join(src_dir, "CMakeLists.txt")]
    src_sub = os.path.join(src_dir, "src")
    if os.path.isdir(src_sub):
        candidates += [
            os.path.join(src_sub, n) for n in sorted(os.listdir(src_sub))
        ]
    for path in candidates:
        if os.path.isfile(path):
            h.update(os.path.basename(path).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:16]


@functools.lru_cache(maxsize=1)
def _locate_sources() -> tuple[str, str]:
    """(cmake source dir, build dir) for the current install layout.

    A repo checkout builds in-tree (extractor/build). An installed wheel
    carries the C++ sources as package data (code2vec_tpu/_native, copied by
    setup.py's build_py) and builds once into the user cache dir, keyed by a
    digest of the shipped sources so a package upgrade rebuilds instead of
    reusing the previous version's binary. Computed lazily (first build/load),
    not at import — the digest reads every shipped C++ source.
    """
    repo_src = os.path.join(REPO_ROOT, "extractor")
    if os.path.exists(os.path.join(repo_src, "CMakeLists.txt")):
        return repo_src, os.path.join(repo_src, "build")
    pkg_src = os.path.join(_PKG_DIR, "_native")
    cache_root = os.environ.get(
        "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
    )
    return pkg_src, os.path.join(
        cache_root, "code2vec-tpu", f"extractor-build-{_source_digest(pkg_src)}"
    )


def __getattr__(name: str) -> str:
    # lazy module attributes: EXTRACTOR_DIR/BUILD_DIR/BINARY/LIBRARY resolve
    # the install layout on first access instead of at import time
    if name in ("EXTRACTOR_DIR", "BUILD_DIR", "BINARY", "LIBRARY"):
        src, build = _locate_sources()
        return {
            "EXTRACTOR_DIR": src,
            "BUILD_DIR": build,
            "BINARY": os.path.join(build, "c2v-extract"),
            "LIBRARY": os.path.join(build, "libc2v.so"),
        }[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def build_extractor(force: bool = False) -> str:
    """Compile the extractor if needed; returns the binary path."""
    src_dir, build_dir = _locate_sources()
    binary = os.path.join(build_dir, "c2v-extract")
    library = os.path.join(build_dir, "libc2v.so")
    if not force and os.path.exists(binary) and os.path.exists(library):
        return binary
    if not os.path.exists(os.path.join(src_dir, "CMakeLists.txt")):
        raise RuntimeError(
            "extractor sources not found (looked in "
            f"{os.path.join(REPO_ROOT, 'extractor')} and "
            f"{os.path.join(_PKG_DIR, '_native')}); reinstall the package "
            "from a wheel built with setup.py, or run from a repo checkout"
        )
    from code2vec_tpu.obs.trace import get_tracer

    with get_tracer().span("extractor_build", category="extract"):
        os.makedirs(build_dir, exist_ok=True)
        generator = ["-G", "Ninja"] if shutil.which("ninja") else []
        subprocess.run(
            ["cmake", "-S", src_dir, "-B", build_dir, *generator],
            check=True,
            capture_output=True,
        )
        subprocess.run(
            ["cmake", "--build", build_dir], check=True, capture_output=True
        )
    return binary


@dataclass
class ExtractedMethod:
    label: str
    path_contexts: list[tuple[int, int, int]] = field(default_factory=list)
    aliases: list[tuple[str, str]] = field(default_factory=list)


@dataclass
class ExtractResult:
    methods: list[ExtractedMethod]
    terminal_vocab: dict[int, str]  # 1-based raw indices (no PAD row)
    path_vocab: dict[int, str]


_lib = None


def _load_library():
    global _lib
    if _lib is None:
        binary = build_extractor()
        _lib = ctypes.CDLL(os.path.join(os.path.dirname(binary), "libc2v.so"))
        _lib.c2v_extract_source.restype = ctypes.c_void_p
        _lib.c2v_extract_source.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        _lib.c2v_free.argtypes = [ctypes.c_void_p]
        _lib.c2v_last_error.restype = ctypes.c_char_p
    return _lib


def extract_source(
    source: str,
    method_name: str = "*",
    max_length: int = 8,
    max_width: int = 3,
    normalize_string: bool = True,
    normalize_char: bool = True,
    normalize_int: bool = False,
    normalize_double: bool = True,
) -> ExtractResult:
    """Extract path-contexts from a Java source string, in process."""
    lib = _load_library()
    raw = lib.c2v_extract_source(
        source.encode("utf-8"),
        method_name.encode("utf-8"),
        max_length,
        max_width,
        int(normalize_string),
        int(normalize_char),
        int(normalize_int),
        int(normalize_double),
    )
    if not raw:
        raise ValueError(
            "extraction failed: " + lib.c2v_last_error().decode("utf-8")
        )
    try:
        text = ctypes.string_at(raw).decode("utf-8")
    finally:
        lib.c2v_free(raw)
    return _parse_blob(text)


def _parse_blob(text: str) -> ExtractResult:
    body, _, tail = text.partition("===TERMINALS===\n")
    terminal_part, _, path_part = tail.partition("===PATHS===\n")

    def parse_vocab(chunk: str) -> dict[int, str]:
        out = {}
        for line in chunk.splitlines():
            if "\t" in line:
                index, name = line.split("\t", 1)
                out[int(index)] = name
        return out

    methods: list[ExtractedMethod] = []
    current: ExtractedMethod | None = None
    mode = 0
    for line in body.splitlines():
        if not line:
            current = None
            continue
        if line.startswith("#"):
            current = ExtractedMethod(label="")
            methods.append(current)
            mode = 0
        elif line.startswith("label:"):
            current.label = line[6:]
        elif line == "paths:":
            mode = 1
        elif line == "vars:":
            mode = 2
        elif mode == 1:
            start, path, end = line.split("\t")
            current.path_contexts.append((int(start), int(path), int(end)))
        elif mode == 2:
            original, alias = line.split("\t")
            current.aliases.append((original, alias))
    return ExtractResult(
        methods=methods,
        terminal_vocab=parse_vocab(terminal_part),
        path_vocab=parse_vocab(path_part),
    )


def extract_dataset(
    dataset_dir: str,
    source_dir: str,
    max_length: int = 8,
    max_width: int = 3,
    method_declarations: str | None = None,
    extra_args: list[str] = (),
) -> subprocess.CompletedProcess:
    """Run the CLI over <dataset_dir>/methods.txt (createDataset parity)."""
    from code2vec_tpu.obs.trace import get_tracer

    cmd = [
        build_extractor(),
        dataset_dir,
        source_dir,
        "--max-length",
        str(max_length),
        "--max-width",
        str(max_width),
    ]
    if method_declarations:
        cmd += ["--method-declarations", method_declarations]
    cmd += list(extra_args)
    with get_tracer().span(
        "extract_dataset", category="extract", dataset_dir=dataset_dir
    ):
        return subprocess.run(cmd, check=True, capture_output=True, text=True)


class _C2vCorpus(ctypes.Structure):
    _fields_ = [
        ("n_records", ctypes.c_int64),
        ("n_contexts", ctypes.c_int64),
        ("starts", ctypes.POINTER(ctypes.c_int32)),
        ("paths", ctypes.POINTER(ctypes.c_int32)),
        ("ends", ctypes.POINTER(ctypes.c_int32)),
        ("row_splits", ctypes.POINTER(ctypes.c_int64)),
        ("ids", ctypes.POINTER(ctypes.c_int64)),
        ("headers", ctypes.POINTER(ctypes.c_char)),
        ("headers_len", ctypes.c_int64),
        ("vars", ctypes.POINTER(ctypes.c_char)),
        ("vars_len", ctypes.c_int64),
    ]


def parse_corpus_native(path: str):
    """Parse a corpus.txt with the native C++ parser (~20x the Python
    state machine; the path-triple lines are ~98% of corpus bytes).

    Returns ``(starts, paths, ends, row_splits, ids, headers, vars)``:
    numpy copies of the arrays (raw indices, no @question shift) plus the
    per-record ``(label, source | None)`` list and the per-record
    ``[(original, alias), ...]`` lists. Raises RuntimeError on parse/IO
    failure (caller falls back to the Python parser).
    """
    import numpy as np

    from code2vec_tpu.obs.trace import get_tracer

    lib = _load_library()
    if not hasattr(lib.c2v_parse_corpus, "_configured"):
        lib.c2v_parse_corpus.restype = ctypes.POINTER(_C2vCorpus)
        lib.c2v_parse_corpus.argtypes = [ctypes.c_char_p]
        lib.c2v_free_corpus.argtypes = [ctypes.POINTER(_C2vCorpus)]
        lib.c2v_parse_corpus._configured = True
    with get_tracer().span("parse_corpus_native", category="extract"):
        ptr = lib.c2v_parse_corpus(os.fspath(path).encode())
    if not ptr:
        raise RuntimeError(
            "native corpus parse failed: "
            + lib.c2v_last_error().decode("utf-8")
        )
    try:
        c = ptr.contents
        n, total = int(c.n_records), int(c.n_contexts)

        def arr(p, count, dtype):
            if count == 0:
                return np.zeros(0, dtype)
            return np.ctypeslib.as_array(p, shape=(count,)).astype(dtype, copy=True)

        starts = arr(c.starts, total, np.int32)
        paths = arr(c.paths, total, np.int32)
        ends = arr(c.ends, total, np.int32)
        row_splits = arr(c.row_splits, n + 1, np.int64)
        ids = arr(c.ids, n, np.int64)
        headers_blob = ctypes.string_at(c.headers, c.headers_len).decode("utf-8")
        vars_blob = ctypes.string_at(c.vars, c.vars_len).decode("utf-8")
    finally:
        lib.c2v_free_corpus(ptr)

    headers = []
    for rec in headers_blob.split("\x1e")[:n]:
        label, _, flagged_source = rec.partition("\x1f")
        source = flagged_source[1:] if flagged_source[:1] == "1" else None
        headers.append((label, source))
    var_lists = []
    for rec in vars_blob.split("\x1e")[:n]:
        pairs = []
        for item in rec.split("\x1d"):
            if item:
                original, _, alias = item.partition("\x1f")
                pairs.append((original, alias))
        var_lists.append(pairs)
    return starts, paths, ends, row_splits, ids, headers, var_lists


def _read_method_rows(dataset_dir: str) -> list[tuple[str, str]]:
    # surrogateescape keeps non-UTF-8 path bytes lossless through the
    # Python detour (the C++ leg reads methods.txt as raw bytes itself)
    rows = []
    path = os.path.join(dataset_dir, "methods.txt")
    with open(path, encoding="utf-8", errors="surrogateescape") as f:
        for line in f:
            line = line.strip()
            if not line or "\t" not in line:
                continue
            src, method = line.split("\t", 1)
            rows.append((src, method))
    return rows


def _py_config_from_flags(args, extra):
    """The Java leg's passthrough normalization flags, applied to the
    Python leg too — both legs intern literals into ONE vocab, so they
    must agree on what a literal normalizes to."""
    from code2vec_tpu.pyextract import PyExtractConfig

    config = PyExtractConfig(
        max_length=args.max_length, max_width=args.max_width
    )
    for flag in extra:
        if flag == "--no-normalize-string":
            config.normalize_string_literal = False
        elif flag == "--no-normalize-char":
            config.normalize_char_literal = False
        elif flag == "--normalize-int":
            config.normalize_int_literal = True
        elif flag == "--normalize-double":
            config.normalize_double_literal = True
        elif flag == "--no-normalize-double":
            config.normalize_double_literal = False
    return config


def _extract_mixed(args, extra, rows) -> None:
    """Multi-language dataset (BASELINE config 5): .java rows go through
    the native CLI, .py rows through code2vec_tpu.pyextract in merge mode,
    both interning into ONE shared vocab space (the Python leg preloads the
    Java leg's terminal/path vocab files and appends records)."""
    import tempfile

    from code2vec_tpu.formats.params_io import read_params
    from code2vec_tpu.pyextract import extract_python_dataset

    java_rows = [r for r in rows if not r[0].endswith(".py")]
    py_rows = [r for r in rows if r[0].endswith(".py")]

    start_id = 0
    merge = False
    if java_rows:
        with tempfile.TemporaryDirectory() as tmp:
            with open(
                os.path.join(tmp, "methods.txt"), "w", encoding="utf-8",
                errors="surrogateescape",
            ) as f:
                for src, method in java_rows:
                    f.write(f"{src}\t{method}\n")
            result = extract_dataset(
                tmp,
                args.source_dir,
                max_length=args.max_length,
                max_width=args.max_width,
                method_declarations=args.method_declarations,
                extra_args=extra,
            )
            sys.stderr.write(result.stderr)
            copy_names = [
                "corpus.txt", "actual_methods.txt", "terminal_idxs.txt",
                "path_idxs.txt", "params.txt",
            ]
            if args.method_declarations and os.path.exists(
                os.path.join(tmp, args.method_declarations)
            ):
                copy_names.append(args.method_declarations)
            for name in copy_names:
                shutil.copy2(
                    os.path.join(tmp, name),
                    os.path.join(args.dataset_dir, name),
                )
            start_id = int(
                read_params(os.path.join(tmp, "params.txt"))["method_count"]
            )
        merge = True

    n, vocabs = extract_python_dataset(
        args.dataset_dir, args.source_dir, py_rows,
        config=_py_config_from_flags(args, extra),
        merge=merge, start_id=start_id,
        method_declarations=args.method_declarations,
    )
    print(
        f"extracted {n} methods ({start_id} java + {n - start_id} python), "
        f"{len(vocabs.terminals)} terminals, {len(vocabs.paths)} paths",
        file=sys.stderr,
    )


def main(argv: list[str] | None = None) -> None:
    """``python -m code2vec_tpu.extractor <dataset_dir> <source_dir> …`` —
    builds the native extractor on first use and forwards to ``c2v-extract``
    (createDataset parity, ipynb cell11). methods.txt rows naming .py files
    route through the Python-language extractor (pyextract), merging into
    the same vocab space as the Java rows."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="code2vec_tpu.extractor",
        description="Java and/or Python sources -> path-context corpus "
        "artifacts (reads <dataset_dir>/methods.txt, writes corpus.txt, "
        "terminal_idxs.txt, path_idxs.txt, params.txt, actual_methods.txt)",
    )
    parser.add_argument("dataset_dir")
    parser.add_argument("source_dir")
    parser.add_argument("--max-length", type=int, default=8)
    parser.add_argument("--max-width", type=int, default=3)
    parser.add_argument("--method-declarations", default=None)
    args, extra = parser.parse_known_args(argv)

    try:
        rows = _read_method_rows(args.dataset_dir)
    except OSError as e:
        print(f"ERROR: cannot open methods.txt: {e}", file=sys.stderr)
        raise SystemExit(1)
    try:
        if any(src.endswith(".py") for src, _ in rows):
            _extract_mixed(args, extra, rows)
            return
        result = extract_dataset(
            args.dataset_dir,
            args.source_dir,
            max_length=args.max_length,
            max_width=args.max_width,
            method_declarations=args.method_declarations,
            extra_args=extra,
        )
    except subprocess.CalledProcessError as e:
        if e.stdout:
            sys.stdout.write(e.stdout)
        if e.stderr:
            sys.stderr.write(e.stderr)
        raise SystemExit(e.returncode)
    sys.stdout.write(result.stdout)
    sys.stderr.write(result.stderr)


if __name__ == "__main__":
    main()
