"""``code.vec`` and test-result TSV formats.

``code.vec`` (SURVEY.md §2.4): line 1 is ``<count>\\t<dim>``, then one
``label\\t<space-separated floats>`` row per example (reference:
main.py:226-230,414-416; read back by visualize_code_vec.py:8-23).

Test-result TSV: ``id\\tcorrect?\\texpected\\tpredicted\\tprob``
(reference: main.py:418-420).
"""

from __future__ import annotations

import os
from typing import IO, Iterable, Sequence

import numpy as np


def write_code_vectors_header(path: str | os.PathLike, count: int, dim: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{count}\t{dim}\n")


def append_code_vectors(
    path: str | os.PathLike,
    labels: Sequence[str],
    vectors: np.ndarray,
) -> None:
    """Append label+vector rows (reference row format: main.py:416)."""
    with open(path, "a", encoding="utf-8") as f:
        for label, vec in zip(labels, vectors):
            f.write(label + "\t" + " ".join(str(float(e)) for e in vec) + "\n")


def read_code_vectors(path: str | os.PathLike) -> tuple[list[str], np.ndarray]:
    """Parse code.vec back into (labels, [n, dim] float array)
    (reference reader: visualize_code_vec.py:8-21)."""
    labels: list[str] = []
    rows: list[list[float]] = []
    with open(path, encoding="utf-8") as f:
        header = f.readline().strip().split("\t")
        count, dim = int(header[0]), int(header[1])
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            label, values = line.split("\t")
            labels.append(label)
            rows.append([float(v) for v in values.split(" ")])
    # The header count can disagree with the row count (the reference
    # re-appends rows per best epoch); tolerate it like the reference
    # visualizer, which never checks.
    del count
    arr = np.asarray(rows, dtype=np.float32) if rows else np.zeros((0, dim), np.float32)
    return labels, arr


def write_test_results(
    f: IO[str],
    ids: Iterable[int],
    expected: Iterable[str],
    predicted: Iterable[str],
    probs: Iterable[float],
) -> None:
    for i, exp, pred, prob in zip(ids, expected, predicted, probs):
        f.write(f"{i}\t{exp == pred}\t{exp}\t{pred}\t{prob}\n")
