"""``corpus.txt`` streaming parser and writer.

Record format (SURVEY.md §2.4; written by the reference extractor at
create_path_contexts.ipynb cell11, parsed at model/dataset_reader.py:72-128)::

    #<int id>
    label:<original method name>
    class:<source file path>
    paths:
    <startIdx>\\t<pathIdx>\\t<endIdx>      (one per path-context)
    vars:
    <originalName>\\t<aliasName>           (e.g. counter\\t@var_0)

Records are separated by blank lines. A ``doc:`` line is recognized and its
value discarded, matching the reference's behavior
(model/dataset_reader.py:109-110).

This layer is *raw*: terminal indices are emitted exactly as they appear in
the file. The ``@question`` +1 shift is applied by the dataset reader
(code2vec_tpu.data.reader), keeping file round-trips byte-faithful.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import IO, Iterator


@dataclass
class CorpusRecord:
    """One method's worth of corpus data, indices raw as-on-disk."""

    id: int | None = None
    label: str | None = None
    source: str | None = None
    doc: str | None = None
    path_contexts: list[tuple[int, int, int]] = field(default_factory=list)
    aliases: list[tuple[str, str]] = field(default_factory=list)  # (original, alias)


_MODE_HEADER, _MODE_PATHS, _MODE_VARS = 0, 1, 2


def iter_corpus_records(path: str | os.PathLike) -> Iterator[CorpusRecord]:
    """Stream records from a corpus file with a small line state machine
    (same three parse modes as the reference, model/dataset_reader.py:72-128)."""
    record: CorpusRecord | None = None
    mode = _MODE_HEADER
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip(" \r\n\t")
            if line == "":
                if record is not None:
                    yield record
                    record = None
                continue
            if record is None:
                record = CorpusRecord()
                mode = _MODE_HEADER
            if line.startswith("#"):
                record.id = int(line[1:])
            elif line.startswith("label:"):
                record.label = line[6:]
            elif line.startswith("class:"):
                record.source = line[6:]
            elif line.startswith("doc:"):
                record.doc = line[4:]
            elif line.startswith("paths:"):
                mode = _MODE_PATHS
            elif line.startswith("vars:"):
                mode = _MODE_VARS
            elif mode == _MODE_PATHS:
                # Index the first three fields, tolerating extra trailing
                # columns like the reference parser does
                # (model/dataset_reader.py:112-115).
                fields = line.split("\t")
                record.path_contexts.append(
                    (int(fields[0]), int(fields[1]), int(fields[2]))
                )
            elif mode == _MODE_VARS:
                fields = line.split("\t")
                record.aliases.append((fields[0], fields[1]))
    if record is not None:
        yield record


def read_corpus(path: str | os.PathLike) -> list[CorpusRecord]:
    return list(iter_corpus_records(path))


def write_corpus_record(f: IO[str], record: CorpusRecord) -> None:
    """Write one record followed by the blank separator line."""
    f.write(f"#{record.id}\n")
    f.write(f"label:{record.label}\n")
    if record.source is not None:
        f.write(f"class:{record.source}\n")
    if record.doc is not None:
        f.write(f"doc:{record.doc}\n")
    f.write("paths:\n")
    for start, p, end in record.path_contexts:
        f.write(f"{start}\t{p}\t{end}\n")
    f.write("vars:\n")
    for original, alias in record.aliases:
        f.write(f"{original}\t{alias}\n")
    f.write("\n")


def write_corpus(path: str | os.PathLike, records: list[CorpusRecord]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            write_corpus_record(f, record)
