"""``corpus.txt`` streaming parser and writer.

Record format (SURVEY.md §2.4; written by the reference extractor at
create_path_contexts.ipynb cell11, parsed at model/dataset_reader.py:72-128)::

    #<int id>
    label:<original method name>
    class:<source file path>
    paths:
    <startIdx>\\t<pathIdx>\\t<endIdx>      (one per path-context)
    vars:
    <originalName>\\t<aliasName>           (e.g. counter\\t@var_0)

Records are separated by blank lines. A ``doc:`` line is recognized and its
value discarded, matching the reference's behavior
(model/dataset_reader.py:109-110).

This layer is *raw*: terminal indices are emitted exactly as they appear in
the file. The ``@question`` +1 shift is applied by the dataset reader
(code2vec_tpu.data.reader), keeping file round-trips byte-faithful.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import IO, Iterator

import numpy as np

from code2vec_tpu.obs import handles


@dataclass
class CorpusRecord:
    """One method's worth of corpus data, indices raw as-on-disk."""

    id: int | None = None
    label: str | None = None
    source: str | None = None
    doc: str | None = None
    path_contexts: list[tuple[int, int, int]] = field(default_factory=list)
    aliases: list[tuple[str, str]] = field(default_factory=list)  # (original, alias)


_MODE_HEADER, _MODE_PATHS, _MODE_VARS = 0, 1, 2


def iter_corpus_records(path: str | os.PathLike) -> Iterator[CorpusRecord]:
    """Stream records from a corpus file with a small line state machine
    (same three parse modes as the reference, model/dataset_reader.py:72-128)."""
    record: CorpusRecord | None = None
    mode = _MODE_HEADER
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip(" \r\n\t")
            if line == "":
                if record is not None:
                    yield record
                    record = None
                continue
            if record is None:
                record = CorpusRecord()
                mode = _MODE_HEADER
            if line.startswith("#"):
                record.id = int(line[1:])
            elif line.startswith("label:"):
                record.label = line[6:]
            elif line.startswith("class:"):
                record.source = line[6:]
            elif line.startswith("doc:"):
                record.doc = line[4:]
            elif line.startswith("paths:"):
                mode = _MODE_PATHS
            elif line.startswith("vars:"):
                mode = _MODE_VARS
            elif mode == _MODE_PATHS:
                # Index the first three fields, tolerating extra trailing
                # columns like the reference parser does
                # (model/dataset_reader.py:112-115).
                fields = line.split("\t")
                record.path_contexts.append(
                    (int(fields[0]), int(fields[1]), int(fields[2]))
                )
            elif mode == _MODE_VARS:
                fields = line.split("\t")
                record.aliases.append((fields[0], fields[1]))
    if record is not None:
        yield record


def read_corpus(path: str | os.PathLike) -> list[CorpusRecord]:
    return list(iter_corpus_records(path))


def write_corpus_record(f: IO[str], record: CorpusRecord) -> None:
    """Write one record followed by the blank separator line."""
    f.write(f"#{record.id}\n")
    f.write(f"label:{record.label}\n")
    if record.source is not None:
        f.write(f"class:{record.source}\n")
    if record.doc is not None:
        f.write(f"doc:{record.doc}\n")
    f.write("paths:\n")
    for start, p, end in record.path_contexts:
        f.write(f"{start}\t{p}\t{end}\n")
    f.write("vars:\n")
    for original, alias in record.aliases:
        f.write(f"{original}\t{alias}\n")
    f.write("\n")


def write_corpus(path: str | os.PathLike, records: list[CorpusRecord]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            write_corpus_record(f, record)


# ---------------------------------------------------------------------------
# Binary memory-mapped CSR corpus container (the out-of-core corpus format)
#
# The text format above re-parses the whole corpus on every run and the
# parsed CSR arrays must fit host RAM. This container stores the SAME record
# stream as flat on-disk arrays so a corpus larger than host RAM feeds
# training through mmap views (data/pipeline.py:MmapCorpusSource): batches
# gather only the rows they touch and the kernel pages the file lazily.
#
# Layout (all little-endian, sections 16-byte aligned)::
#
#     [0:8)   magic  b"C2VCSR1\n"
#     [8:16)  uint64 header length H
#     [16:16+H) JSON header {version, n_items, n_contexts, terminal_shift,
#                            sections: {name: [offset, dtype, n_elems]}}
#     ...sections...
#     footer: hist_lengths/hist_counts — the ``row_splits`` histogram, so
#     ``derive_bucket_ladder`` and tools/corpus_stats.py read the bucket
#     ladder WITHOUT scanning the context arrays.
#
# Sections: ``row_splits`` (int64 [n+1]), ``starts``/``paths``/``ends``
# (int32 [total]), ``ids`` (int64 [n]), ``flags`` (uint8 [n]: bit0 source
# present, bit1 doc present, bit2 label present, bit3 id present), four
# (offsets, blob) string-table pairs (labels/sources/docs/vars), and the
# histogram footer.
#
# ``terminal_shift``: start/end terminal ids are stored pre-shifted by this
# amount (the ``@question`` +1 the dataset reader would otherwise apply per
# run) so mmap feeding is zero-copy; the CSR->text converter subtracts it
# back — shifting is a bijection on int32, so text -> CSR -> text is
# byte-faithful for canonically-written corpora (``write_corpus``).
# ---------------------------------------------------------------------------

CSR_MAGIC = b"C2VCSR1\n"
_CSR_ALIGN = 16


def _aligned(offset: int) -> int:
    return (offset + _CSR_ALIGN - 1) // _CSR_ALIGN * _CSR_ALIGN


# public: readers outside this module (data/reader.py) test these bits
FLAG_SOURCE, FLAG_DOC, FLAG_LABEL, FLAG_ID = 1, 2, 4, 8


class _StringTable:
    """Append-only UTF-8 string section: (offsets int64 [n+1], blob)."""

    def __init__(self):
        self._parts: list[bytes] = []
        self._offsets: list[int] = [0]

    def add(self, text: str) -> None:
        raw = text.encode("utf-8")
        self._parts.append(raw)
        self._offsets.append(self._offsets[-1] + len(raw))

    def arrays(self) -> tuple[np.ndarray, np.ndarray]:
        blob = b"".join(self._parts)
        return (
            np.asarray(self._offsets, np.int64),
            np.frombuffer(blob, np.uint8).copy()
            if blob
            else np.zeros(0, np.uint8),
        )


class CsrCorpusWriter:
    """Streaming text-record -> CSR-container writer.

    Context rows append to spill files as records arrive, so peak writer RSS
    is O(n_items + strings) — independent of the context count, which is the
    term that outgrows RAM. ``close()`` assembles the final container.
    """

    def __init__(self, path: str | os.PathLike, terminal_shift: int = 0):
        self.path = os.fspath(path)
        self.terminal_shift = int(terminal_shift)
        self._tmp = [self.path + f".tmp{os.getpid()}.{k}" for k in "spe"]
        self._spill = [open(p, "wb") for p in self._tmp]
        self._counts: list[int] = []
        self._ids: list[int] = []
        self._flags: list[int] = []
        self._labels = _StringTable()
        self._sources = _StringTable()
        self._docs = _StringTable()
        self._vars = _StringTable()
        self._closed = False

    def add(self, record: CorpusRecord) -> None:
        contexts = np.asarray(record.path_contexts, np.int32).reshape(-1, 3)
        if self.terminal_shift:
            contexts = contexts + np.asarray(
                [self.terminal_shift, 0, self.terminal_shift], np.int32
            )
        for col, f in enumerate(self._spill):
            f.write(np.ascontiguousarray(contexts[:, col]).tobytes())
        self._counts.append(len(contexts))
        flags = 0
        if record.source is not None:
            flags |= FLAG_SOURCE
        if record.doc is not None:
            flags |= FLAG_DOC
        if record.label is not None:
            flags |= FLAG_LABEL
        if record.id is not None:
            flags |= FLAG_ID
        self._flags.append(flags)
        self._ids.append(record.id if record.id is not None else -1)
        self._labels.add(record.label or "")
        self._sources.add(record.source or "")
        self._docs.add(record.doc or "")
        self._vars.add(
            "".join(f"{orig}\t{alias}\n" for orig, alias in record.aliases)
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for f in self._spill:
            f.close()
        try:
            self._assemble()
        finally:
            for p in self._tmp:
                if os.path.exists(p):
                    os.remove(p)

    def _assemble(self) -> None:
        row_splits = np.zeros(len(self._counts) + 1, np.int64)
        np.cumsum(self._counts, out=row_splits[1:])
        total = int(row_splits[-1])
        lengths, weights = np.unique(
            np.asarray(self._counts, np.int64), return_counts=True
        )
        sections: dict[str, tuple[np.ndarray | str, str, int]] = {}

        def section(name, arr_or_tmp, dtype, n):
            sections[name] = (arr_or_tmp, dtype, int(n))

        section("row_splits", row_splits, "int64", len(row_splits))
        for name, tmp in zip(("starts", "paths", "ends"), self._tmp):
            section(name, tmp, "int32", total)
        section("ids", np.asarray(self._ids, np.int64), "int64", len(self._ids))
        section(
            "flags", np.asarray(self._flags, np.uint8), "uint8", len(self._flags)
        )
        for prefix, table in (
            ("label", self._labels),
            ("source", self._sources),
            ("doc", self._docs),
            ("var", self._vars),
        ):
            offsets, blob = table.arrays()
            section(f"{prefix}_offsets", offsets, "int64", len(offsets))
            section(f"{prefix}_blob", blob, "uint8", len(blob))
        # the histogram footer: ladder derivation without a context scan
        section("hist_lengths", lengths.astype(np.int64), "int64", len(lengths))
        section("hist_counts", weights.astype(np.int64), "int64", len(weights))

        # lay out offsets; the header length feeds back into the first
        # offset, so fix-point over the (stable) JSON serialization
        def render(table: dict) -> bytes:
            return json.dumps(
                {
                    "version": 1,
                    "n_items": len(self._counts),
                    "n_contexts": total,
                    "terminal_shift": self.terminal_shift,
                    "sections": table,
                },
                sort_keys=True,
            ).encode("utf-8")

        itemsize = {"int64": 8, "int32": 4, "uint8": 1}
        header_len = len(render({n: [0, d, c] for n, (_, d, c) in sections.items()}))
        for _ in range(4):  # offsets widen digits; re-layout until stable
            offset = _aligned(16 + header_len)
            table = {}
            for name, (_, dtype, n) in sections.items():
                table[name] = [offset, dtype, n]
                offset = _aligned(offset + n * itemsize[dtype])
            header = render(table)
            if len(header) == header_len:
                break
            header_len = len(header)
        else:
            raise RuntimeError("csr header layout did not converge")

        tmp_out = self.path + f".tmp{os.getpid()}.out"
        with open(tmp_out, "wb") as out:
            out.write(CSR_MAGIC)
            out.write(np.uint64(header_len).tobytes())
            out.write(header)
            for name, (src, dtype, n) in sections.items():
                off = table[name][0]
                out.write(b"\0" * (off - out.tell()))
                if isinstance(src, str):  # context spill file: chunked copy
                    with open(src, "rb") as f:
                        while True:
                            chunk = f.read(1 << 22)
                            if not chunk:
                                break
                            out.write(chunk)
                else:
                    out.write(np.ascontiguousarray(src).tobytes())
        os.replace(tmp_out, self.path)

    def __enter__(self) -> "CsrCorpusWriter":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


def write_corpus_csr(
    path: str | os.PathLike,
    records,
    terminal_shift: int = 0,
) -> None:
    """Write an iterable of :class:`CorpusRecord` as a CSR container."""
    with CsrCorpusWriter(path, terminal_shift=terminal_shift) as writer:
        for record in records:
            writer.add(record)


def is_csr_corpus(path: str | os.PathLike) -> bool:
    """Whether ``path`` is a CSR container (magic sniff)."""
    try:
        with open(path, "rb") as f:
            return f.read(len(CSR_MAGIC)) == CSR_MAGIC
    except OSError:
        return False


@dataclass
class CsrCorpus:
    """An open CSR container: mmap-backed array views + string tables.

    ``starts``/``paths``/``ends`` are read-only views into one shared
    ``np.memmap`` — fancy indexing gathers only the touched rows and the OS
    pages the file on demand, so holding a CsrCorpus costs ~zero host RSS
    regardless of corpus size. ``row_splits``/``ids``/``flags`` are small
    in-RAM copies (O(n_items)).
    """

    path: str
    n_items: int
    n_contexts: int
    terminal_shift: int
    row_splits: np.ndarray  # int64 [n+1], in RAM
    starts: np.ndarray  # int32 [total], mmap view
    paths: np.ndarray  # int32 [total], mmap view
    ends: np.ndarray  # int32 [total], mmap view
    ids: np.ndarray  # int64 [n], in RAM
    flags: np.ndarray  # uint8 [n], in RAM
    hist_lengths: np.ndarray  # int64 [k], in RAM
    hist_counts: np.ndarray  # int64 [k], in RAM
    _mm: np.memmap = field(repr=False)
    _strings: dict = field(repr=False)

    def _string(self, prefix: str, i: int) -> str:
        offsets, blob = self._strings[prefix]
        return bytes(blob[offsets[i] : offsets[i + 1]]).decode("utf-8")

    def label(self, i: int) -> str | None:
        return (
            self._string("label", i)
            if self.flags[i] & FLAG_LABEL
            else None
        )

    def source(self, i: int) -> str | None:
        return (
            self._string("source", i)
            if self.flags[i] & FLAG_SOURCE
            else None
        )

    def doc(self, i: int) -> str | None:
        return self._string("doc", i) if self.flags[i] & FLAG_DOC else None

    def close(self) -> None:
        """Retire this reader from the handle ledger (idempotent). The OS
        releases the mapping when the last array view dies; views already
        handed out stay valid — they hold their own reference to the
        underlying mmap buffer."""
        handles.untrack(self)

    def __enter__(self) -> "CsrCorpus":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    def aliases(self, i: int) -> list[tuple[str, str]]:
        out = []
        for line in self._string("var", i).splitlines():
            orig, alias = line.split("\t", 1)
            out.append((orig, alias))
        return out

    def record(self, i: int) -> CorpusRecord:
        """Decode record ``i`` back to the text layer's representation
        (terminal shift removed)."""
        lo, hi = int(self.row_splits[i]), int(self.row_splits[i + 1])
        shift = self.terminal_shift
        return CorpusRecord(
            id=int(self.ids[i]) if self.flags[i] & FLAG_ID else None,
            label=self.label(i),
            source=self.source(i),
            doc=self.doc(i),
            path_contexts=[
                (int(s) - shift, int(p), int(e) - shift)
                for s, p, e in zip(
                    self.starts[lo:hi], self.paths[lo:hi], self.ends[lo:hi]
                )
            ],
            aliases=self.aliases(i),
        )

    def iter_records(self) -> Iterator[CorpusRecord]:
        for i in range(self.n_items):
            yield self.record(i)


def open_corpus_csr(path: str | os.PathLike) -> CsrCorpus:
    """Open a CSR container with mmap-backed context arrays."""
    path = os.fspath(path)
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    if bytes(mm[: len(CSR_MAGIC)]) != CSR_MAGIC:
        raise ValueError(f"{path!r} is not a CSR corpus container")
    header_len = int(mm[8:16].view(np.uint64)[0])
    header = json.loads(bytes(mm[16 : 16 + header_len]).decode("utf-8"))
    if header.get("version") != 1:
        raise ValueError(
            f"unsupported CSR container version {header.get('version')!r}"
        )
    itemsize = {"int64": 8, "int32": 4, "uint8": 1}

    def view(name: str) -> np.ndarray:
        offset, dtype, n = header["sections"][name]
        return mm[offset : offset + n * itemsize[dtype]].view(dtype)

    strings = {
        prefix: (np.array(view(f"{prefix}_offsets")), view(f"{prefix}_blob"))
        for prefix in ("label", "source", "doc", "var")
    }
    return handles.track(CsrCorpus(
        path=path,
        n_items=int(header["n_items"]),
        n_contexts=int(header["n_contexts"]),
        terminal_shift=int(header["terminal_shift"]),
        row_splits=np.array(view("row_splits")),
        starts=view("starts"),
        paths=view("paths"),
        ends=view("ends"),
        ids=np.array(view("ids")),
        flags=np.array(view("flags")),
        hist_lengths=np.array(view("hist_lengths")),
        hist_counts=np.array(view("hist_counts")),
        _mm=mm,
        _strings=strings,
    ), "mmap_corpus", name=path)


def read_csr_histogram(
    path: str | os.PathLike,
) -> tuple[np.ndarray, np.ndarray]:
    """(lengths, counts) context-count histogram from the container footer —
    no context scan; the O(1) input to ``derive_bucket_ladder_hist``."""
    with open_corpus_csr(path) as corpus:
        # in-RAM copies (O(k)); the mmap itself is released with the reader
        return corpus.hist_lengths, corpus.hist_counts
