"""Vocab file (``terminal_idxs.txt`` / ``path_idxs.txt``) reader/writer.

Format: ``<index>\\t<name>`` per line; index 0 is the ``<PAD/>`` sentinel and
blank names are tolerated (SURVEY.md §2.4).

The reader supports *extra-token injection*: extras occupy indices 1..k and
every file index > 0 is shifted up by k. The terminal vocab is always read
with ``extra_tokens=["@question"]`` so ``@question`` sits at index 1 —
which is also why raw corpus start/end terminal indices must be shifted by
+1 when parsed (reference: model/dataset_reader.py:18-41,113-115).
"""

from __future__ import annotations

import os
from typing import Iterable, Sequence

from code2vec_tpu.data.vocab import Vocab


def read_vocab(path: str | os.PathLike, extra_tokens: Sequence[str] = ()) -> Vocab:
    """Read a vocab file, injecting ``extra_tokens`` at indices 1..k and
    shifting file indices > 0 up by k (reference: model/dataset_reader.py:22-41)."""
    vocab = Vocab()
    extra_size = len(extra_tokens)
    for offset, name in enumerate(extra_tokens):
        vocab.add(name, index=1 + offset)
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip(" \r\n")
            if not line:
                continue
            fields = line.split("\t")
            index = int(fields[0])
            if index > 0:
                index += extra_size
            name = fields[1] if len(fields) > 1 else ""
            vocab.add(name, index=index)
    return vocab


def write_vocab(path: str | os.PathLike, entries: Iterable[tuple[int, str]]) -> None:
    """Write ``index\\tname`` lines. Callers are responsible for emitting the
    ``0\\t<PAD/>`` sentinel first (the extractor does,
    reference: create_path_contexts.ipynb cell11)."""
    with open(path, "w", encoding="utf-8") as f:
        for index, name in entries:
            f.write(f"{index}\t{name}\n")


def write_vocab_from_names(
    path: str | os.PathLike, names: Iterable[str], pad_name: str = "<PAD/>"
) -> None:
    """Write a vocab file with the PAD sentinel at 0 and names at 1..n."""
    def rows():
        yield 0, pad_name
        for i, name in enumerate(names, start=1):
            yield i, name

    write_vocab(path, rows())
