"""Binary memory-mapped ANN index container (IVF-PQ).

Same conventions as the CSR corpus container (``corpus_io.py``): magic +
uint64 header length + JSON section-table header + 16-byte-aligned raw
little-endian sections, written atomically (tmp + ``os.replace``), loaded
tolerantly (magic/version mismatch is a loud error, not a crash elsewhere).
Sections here are N-dimensional, so the table stores a *shape* per section
(``{name: [offset, dtype, shape]}``) instead of a flat element count.

The reader returns **views into one shared ``np.memmap``** for every
section: the exact-rerank ``rows`` matrix and the cell-major code arrays —
the two terms that scale with corpus size — cost ~zero host RSS until
touched, and a query pages in only the cells it probes plus the shortlist
rows it re-ranks. Callers copy the small sections they want in RAM.

Header ``meta`` carries the index geometry (n, dim, n_list, m, capacity,
defaults) — everything a loader needs before touching a section.
"""

from __future__ import annotations

import json
import os

import numpy as np

ANN_MAGIC = b"C2VANN1\n"
_ALIGN = 16
_VERSION = 1

_DTYPES = {"float32": 4, "int64": 8, "int32": 4, "uint8": 1}


def _aligned(offset: int) -> int:
    return (offset + _ALIGN - 1) // _ALIGN * _ALIGN


def is_ann_index(path: str | os.PathLike) -> bool:
    """Magic sniff."""
    try:
        with open(path, "rb") as f:
            return f.read(len(ANN_MAGIC)) == ANN_MAGIC
    except OSError:
        return False


def write_ann_container(
    path: str | os.PathLike,
    arrays: dict[str, np.ndarray],
    meta: dict,
) -> None:
    """Write ``arrays`` + ``meta`` as one container. Section order follows
    the dict order, so put the hot small sections first and the big
    mmap-heavy ones (rows) last if locality matters."""
    path = os.fspath(path)
    sections: dict[str, tuple[np.ndarray, str, tuple[int, ...]]] = {}
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        dtype = arr.dtype.name
        if dtype not in _DTYPES:
            raise ValueError(
                f"section {name!r}: unsupported dtype {dtype!r} "
                f"(supported: {sorted(_DTYPES)})"
            )
        sections[name] = (arr, dtype, tuple(int(d) for d in arr.shape))

    def render(table: dict) -> bytes:
        return json.dumps(
            {"version": _VERSION, "meta": meta, "sections": table},
            sort_keys=True,
        ).encode("utf-8")

    # fix-point over the header length (corpus_io's layout discipline:
    # offsets widen digits; re-layout until the serialization is stable)
    header_len = len(
        render({n: [0, d, list(s)] for n, (_, d, s) in sections.items()})
    )
    for _ in range(4):
        offset = _aligned(16 + header_len)
        table = {}
        for name, (arr, dtype, shape) in sections.items():
            table[name] = [offset, dtype, list(shape)]
            offset = _aligned(offset + arr.size * _DTYPES[dtype])
        header = render(table)
        if len(header) == header_len:
            break
        header_len = len(header)
    else:
        raise RuntimeError("ann container header layout did not converge")

    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as out:
        out.write(ANN_MAGIC)
        out.write(np.uint64(header_len).tobytes())
        out.write(header)
        for name, (arr, dtype, _) in sections.items():
            off = table[name][0]
            out.write(b"\0" * (off - out.tell()))
            out.write(arr.tobytes())
    os.replace(tmp, path)


def read_ann_container(
    path: str | os.PathLike,
) -> tuple[dict[str, np.ndarray], dict]:
    """Open a container: ``(arrays, meta)``. Every array is a read-only
    view into one shared ``np.memmap`` — copy what you want resident."""
    path = os.fspath(path)
    with open(path, "rb") as f:
        magic = f.read(len(ANN_MAGIC))
        if magic != ANN_MAGIC:
            raise ValueError(f"{path}: not an ANN index container")
        header_len = int(np.frombuffer(f.read(8), np.uint64)[0])
        payload = json.loads(f.read(header_len).decode("utf-8"))
    if payload.get("version") != _VERSION:
        raise ValueError(
            f"{path}: ann container version {payload.get('version')!r} "
            f"(this build reads {_VERSION})"
        )
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    arrays: dict[str, np.ndarray] = {}
    for name, (offset, dtype, shape) in payload["sections"].items():
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * _DTYPES[dtype]
        view = mm[offset : offset + nbytes].view(dtype)
        arrays[name] = view.reshape(tuple(shape))
    return arrays, payload["meta"]
