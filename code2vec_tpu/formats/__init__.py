"""Text artifact formats — the L1 interchange contract (SURVEY.md §2.4).

These five formats are the only coupling between the extraction half and the
training half, in the reference and here:

- ``corpus.txt``        blank-line-separated method records
- ``*_idxs.txt``        vocab files, index 0 = ``<PAD/>``
- ``params.txt``        extraction stats, ``key:value`` lines
- ``code.vec``          exported code vectors
- test-result TSV       per-example prediction dump
"""

from code2vec_tpu.formats.vocab_io import (
    read_vocab,
    write_vocab,
    write_vocab_from_names,
)
from code2vec_tpu.formats.corpus_io import (
    CorpusRecord,
    iter_corpus_records,
    read_corpus,
    write_corpus,
    write_corpus_record,
)
from code2vec_tpu.formats.params_io import read_params, write_params
from code2vec_tpu.formats.vectors_io import (
    read_code_vectors,
    write_code_vectors_header,
    append_code_vectors,
    write_test_results,
)
