"""``params.txt`` — extraction stats as ``key:value`` lines.

SURVEY.md §2.4; written by the reference extractor
(create_path_contexts.ipynb cell11), e.g.::

    max_length:8
    max_width:3
    terminal_vocab_count:360631
    path_vocab_count:342845
    method_count:605945
"""

from __future__ import annotations

import os


def read_params(path: str | os.PathLike) -> dict[str, str]:
    params: dict[str, str] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or ":" not in line:
                continue
            key, value = line.split(":", 1)
            params[key] = value
    return params


def write_params(path: str | os.PathLike, params: dict[str, object]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for key, value in params.items():
            f.write(f"{key}:{value}\n")
