"""Code-vector visualization — TensorBoard embedding-projector export
(reference: visualize_code_vec.py:1-23).

The reference feeds ``output/code.vec`` to tensorboardX
``SummaryWriter.add_embedding``. This module does the same when
tensorboardX is importable, and ALWAYS writes the projector's standalone
TSV interchange (``vectors.tsv`` + ``metadata.tsv`` +
``projector_config.pbtxt``) so the vectors remain inspectable with the
hosted projector (projector.tensorflow.org) or any tool, with no
TensorFlow dependency.

CLI: ``python -m code2vec_tpu.visualize [code.vec] [--log_dir DIR]``.
"""

from __future__ import annotations

import argparse
import logging
import os

import numpy as np

from code2vec_tpu.formats.vectors_io import read_code_vectors

logger = logging.getLogger(__name__)


def write_projector_tsv(
    log_dir: str | os.PathLike,
    labels: list[str],
    vectors: np.ndarray,
) -> dict[str, str]:
    """Write the standalone projector TSV triple; returns the paths."""
    os.makedirs(log_dir, exist_ok=True)
    paths = {
        "vectors": os.path.join(log_dir, "vectors.tsv"),
        "metadata": os.path.join(log_dir, "metadata.tsv"),
        "config": os.path.join(log_dir, "projector_config.pbtxt"),
    }
    with open(paths["vectors"], "w", encoding="utf-8") as f:
        for vec in vectors:
            f.write("\t".join(str(float(e)) for e in vec) + "\n")
    with open(paths["metadata"], "w", encoding="utf-8") as f:
        for label in labels:
            # single-column metadata has no header row (projector rule)
            f.write(label.replace("\t", " ").replace("\n", " ") + "\n")
    with open(paths["config"], "w", encoding="utf-8") as f:
        f.write(
            "embeddings {\n"
            '  tensor_name: "code_vectors"\n'
            '  tensor_path: "vectors.tsv"\n'
            '  metadata_path: "metadata.tsv"\n'
            "}\n"
        )
    return paths


def visualize_code_vectors(
    vectors_path: str | os.PathLike,
    log_dir: str | os.PathLike = "runs",
) -> dict[str, str]:
    """Load code.vec and export for the projector; add_embedding when
    tensorboardX is present (reference behavior, visualize_code_vec.py:23)."""
    labels, vectors = read_code_vectors(vectors_path)
    logger.info("loaded %d vectors (dim %d) from %s", len(labels),
                vectors.shape[1] if vectors.size else 0, vectors_path)
    paths = write_projector_tsv(log_dir, labels, vectors)
    try:
        from tensorboardX import SummaryWriter
    except ImportError:
        logger.info("tensorboardX not available; wrote projector TSVs only")
        return paths
    writer = SummaryWriter(str(log_dir))
    writer.add_embedding(vectors, metadata=labels, tag="code_vectors")
    writer.close()
    return paths


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        description="Export code.vec for the TensorBoard embedding projector"
    )
    parser.add_argument("vectors_path", nargs="?", default="./output/code.vec")
    parser.add_argument("--log_dir", type=str, default="runs")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(asctime)s: %(message)s")
    visualize_code_vectors(args.vectors_path, args.log_dir)


if __name__ == "__main__":
    main()
