"""Headline benchmark: training throughput in path-contexts/sec/chip at
top11 scale (BASELINE.md: the reference publishes no numbers; this run
establishes/extends the baseline).

Setup mirrors the reference's top11 recipe (README.md:34 — batch 1024,
embed 100/100, encode 100) at the top11 corpus scale (605,945 methods,
360,631 terminals, 342,845 paths — top11_dataset/params.txt), with the
TPU-ablation-winning recipe (f32 compute, unsafe_rbg dropout bits, dense
embedding backward — tools/run_tpu_ablation.py, docs/ARCHITECTURE.md;
override via BENCH_DTYPE / BENCH_RNG_IMPL / BENCH_EMBED_GRAD). The measured path is the flagship one: the corpus staged to
device memory once (CSR), per-epoch context subsampling on device, and
scanned chunks of [1024, 200] train steps per dispatch
(train/device_epoch.py). Accounting matches the reference's work per step:
B x L context slots.

Output contract: a detail JSON line goes to stderr first, then the headline
metric JSON {"metric", "value", "unit", "vs_baseline", "backend"} is the
LAST line printed to stdout — the driver parses the final JSON line of the
merged stream. On failure, a metric line with value=null and an "error"
field is still emitted. vs_baseline compares against the newest successful
BENCH_r*.json in the repo (1.0 on the first ever run).

Process shape: the top-level invocation is a thin SUPERVISOR that runs the
actual measurement in a killable child under BENCH_DEADLINE seconds
(default 1200) and retries once on CPU if the child hangs or dies — the
axon tunnel can wedge *after* init succeeds, which no in-process guard can
escape. Set BENCH_SUPERVISED=1 to run the measurement directly.

Modes: the default measures the device-epoch flagship path and emits a
per-step host/H2D/compute attribution dict in the detail JSON
(BENCH_ATTR_CHUNKS fenced chunks after the measured window).
``--prefetch-ab`` instead A/Bs the HOST input pipeline — synchronous feed
vs the double-buffered prefetcher (train/prefetch.py) on one spec — and
reports both steps/sec plus the attribution split (see _prefetch_ab).
``--bucket-ab`` A/Bs length-aware bucketed batching (data/pipeline.py)
against the fixed-L feed on an identical skewed synth corpus, same ABBA
best-of protocol, reporting the wall-clock speedup at equal real-context
throughput accounting (see _bucket_ab).
``--ooc-ab`` A/Bs the in-RAM epoch feed against the out-of-core mmap-CSR
feed (formats/corpus_io.py container + MmapCorpusSource) at equal
real-context work, with host-RSS snapshots in both arms (see _ooc_ab).
``--ann-ab`` A/Bs IVF-PQ ANN retrieval (code2vec_tpu/ann/) against the
exact RetrievalIndex on one synthetic clustered index: recall@{1,10,100}
-vs-QPS across an ``n_probe`` sweep, probed-row-fraction accounting, and
the serve arm's zero-post-warmup-recompile verdict on the query path
(see _ann_ab).

Metric honesty: the headline counts REAL path contexts (summed batch
masks / staged row counts), not padded slots — bag lengths are heavy-
tailed, so at fixed L the majority of B x L slots can be PAD, and
crediting them inflated the metric by exactly the padding waste. Detail
blocks carry ``pad_efficiency`` (real/padded) and ``padded_slots_per_sec``
(the pre-change accounting) so rounds across the change stay comparable.
"""

from __future__ import annotations

import atexit
import glob
import json
import os
import re
import shutil
import sys
import time

import numpy as np


def _compile_cache_dir() -> str:
    """The persistent XLA compile-cache dir, keyed by a host CPU-feature
    fingerprint: cached executables embed the compiling host's ISA
    features, and reusing a dir written on a different host logs XLA's
    "machine features mismatch ... could lead to SIGILL" warning (seen in
    BENCH_r05) — so each CPU population gets its own dir. BENCH_COMPILE_CACHE
    pins an explicit path."""
    pinned = os.environ.get("BENCH_COMPILE_CACHE", "").strip()
    if pinned:
        return pinned
    from code2vec_tpu.obs.runtime import host_cpu_fingerprint

    return f"/tmp/jaxcache_{host_cpu_fingerprint()}"


def _metric_id() -> tuple[str, str]:
    """(metric, unit) for this invocation's mode — failure records must be
    keyed to the benchmark that actually ran, or a crashed --prefetch-ab
    run gets logged against the device-epoch headline metric."""
    if "--prefetch-ab" in sys.argv[1:]:
        return "host_pipeline_steps_per_sec", "steps/sec"
    if "--bucket-ab" in sys.argv[1:]:
        return "bucketed_real_contexts_per_sec", "contexts/sec"
    if "--kernel-ab" in sys.argv[1:]:
        return "fused_kernel_real_contexts_per_sec", "contexts/sec"
    if "--serve" in sys.argv[1:]:
        return "serve_requests_per_sec", "req/sec"
    if "--ooc-ab" in sys.argv[1:]:
        return "mmap_csr_real_contexts_per_sec", "contexts/sec"
    if "--feed-ab" in sys.argv[1:]:
        return "feed_real_contexts_per_sec", "contexts/sec"
    if "--ann-ab" in sys.argv[1:]:
        return "ann_queries_per_sec", "queries/sec"
    if "--longbag-ab" in sys.argv[1:]:
        return "longbag_real_contexts_per_sec", "contexts/sec"
    return "path_contexts_per_sec_per_chip", "contexts/sec"


def _failure_record(error: str) -> str:
    metric, unit = _metric_id()
    return json.dumps(
        {
            "metric": metric,
            "value": None,
            "unit": unit,
            "vs_baseline": None,
            "error": error,
        }
    )


def _extract_metric(payload: dict) -> tuple[float, str | None] | None:
    """Pull (value, backend) out of one BENCH_r*.json.

    The driver writes {n, cmd, rc, tail, parsed}: `parsed` is whichever JSON
    line it captured from the merged stdout/stderr stream, and `tail` holds
    the raw last lines. Value is accepted, in order, from: a bare
    {"value": ...} payload (the schema this file documented before round 2's
    verdict corrected it), parsed.value, and finally a scan of `tail` for
    the metric line. Backend comes from the metric line when present, else
    from any {"detail": {"backend": ...}} line (older rounds put it only
    there); None means the round predates the label (assume device).
    """
    value: float | None = None
    backend: str | None = None

    def consider(obj) -> None:
        nonlocal value, backend
        if not isinstance(obj, dict):
            return
        if value is None and "value" in obj:
            try:
                v = float(obj["value"])
            except (TypeError, ValueError):
                pass
            else:
                value = v
                if isinstance(obj.get("backend"), str):
                    backend = obj["backend"]
        detail = obj.get("detail")
        if (
            backend is None
            and isinstance(detail, dict)
            and isinstance(detail.get("backend"), str)
        ):
            backend = detail["backend"]

    consider(payload)
    consider(payload.get("parsed") or {})
    tail = payload.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            consider(obj)
    return None if value is None else (value, backend)


def _extract_metric_name(payload: dict) -> str | None:
    """The metric NAME a BENCH_r*.json recorded, scanning the same places
    _extract_metric takes the value from; None when the record predates
    metric labels (those are device-epoch headline rounds)."""
    candidates = [payload, payload.get("parsed") or {}]
    tail = payload.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                candidates.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    for obj in candidates:
        if isinstance(obj, dict) and isinstance(obj.get("metric"), str):
            return obj["metric"]
    return None


def _previous_benchmark(current_backend: str) -> tuple[float, bool] | None:
    """Newest successful prior round measured on the SAME kind of backend
    AND the same metric: (value, padded_accounting).

    A fell-back CPU round must not become the baseline for a healthy device
    run (a ~2000x vs_baseline is no signal at all), and vice versa — so
    rounds are compared like-for-like: cpu against cpu, device against
    device. Rounds without a backend label predate the CPU fallback and are
    device numbers. A --prefetch-ab round records steps/sec under its own
    metric name — comparing that against contexts/sec would be a
    meaningless cross-unit ratio, so mismatched-metric rounds are skipped
    (unlabeled legacy rounds count as the headline metric).

    ``padded_accounting``: the headline changed semantics from padded slots
    to real contexts; a round that predates the change (no pad_efficiency
    anywhere in its record) stored a padded-slot number, and vs_baseline
    must divide the SAME quantity into it or the accounting change reads
    as a phantom ~pad_efficiency× perf regression.
    """
    want_cpu = current_backend == "cpu"
    want_metric = _metric_id()[0]
    best = None
    best_round = -1
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if not isinstance(payload, dict) or payload.get("rc", 0) != 0:
            continue
        recorded = _extract_metric_name(payload) or "path_contexts_per_sec_per_chip"
        if recorded != want_metric:
            continue
        metric = _extract_metric(payload)
        if metric is None:
            continue
        value, backend = metric
        if (backend == "cpu") != want_cpu:
            continue
        if int(m.group(1)) > best_round:
            best_round = int(m.group(1))
            padded = "pad_efficiency" not in json.dumps(payload)
            best = (value, padded)
    return best


def _mu_dtype_from_env() -> str:
    """BENCH_ADAM_MU_DTYPE → TrainConfig.adam_mu_dtype, strictly: the two
    arms have distinct measurement meaning (bf16 = measured bench winner,
    f32 = torch parity), so an unrecognized alias raises instead of
    silently picking one."""
    raw = os.environ.get("BENCH_ADAM_MU_DTYPE", "bfloat16").strip().lower()
    if raw in ("float32", "f32", "fp32"):
        return "float32"
    if raw in ("bfloat16", "bf16"):
        return "bfloat16"
    raise ValueError(
        f"BENCH_ADAM_MU_DTYPE={raw!r}: expected float32/f32/fp32 or "
        "bfloat16/bf16"
    )


def _recipe_knob(
    name: str, device_default: int, cpu_default: int,
    fell_back: bool, backend: str,
) -> int:
    """An int recipe knob: env override, else a backend-sized default —
    the CPU fallback shrinks the recipe so a fallback run still finishes
    inside the bench deadline. Shared by every A/B mode so the
    CPU-fallback default logic cannot diverge between them."""
    if name in os.environ:
        return int(os.environ[name])
    return cpu_default if fell_back or backend == "cpu" else device_default


def _recipe_flag(
    name: str, device_default: bool, cpu_default: bool,
    fell_back: bool, backend: str,
) -> bool:
    """Bool sibling of ``_recipe_knob``: env override (1/true/yes/on), else
    the backend-sized default. First-class recipe knobs, not ad-hoc env
    reads — so every mode parses and defaults them identically."""
    if name in os.environ:
        return os.environ[name].strip().lower() in ("1", "true", "yes", "on")
    return bool(cpu_default if fell_back or backend == "cpu" else device_default)


def _env_float(name: str, default: float) -> float:
    """A malformed knob must degrade to its default, not crash the run —
    a crash here yields rc=1 with zero perf data (or silently converts a
    healthy device run into a CPU-fallback measurement)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        print(
            f"bench: malformed {name}={raw!r}; using {default:g}",
            file=sys.stderr,
            flush=True,
        )
        return default


def _purge_jax_modules() -> None:
    import importlib

    for mod in [m for m in list(sys.modules) if m == "jax" or m.startswith("jax.")]:
        sys.modules.pop(mod, None)
    importlib.invalidate_caches()


def _probe_default_backend(timeout_s: float) -> bool:
    """Can the default backend actually run compute within the deadline?
    Probed in a THROWAWAY subprocess: a wedged TPU tunnel makes init — or
    the first dispatch — HANG rather than raise, so the probe must be
    killable, and it must compile + execute (a live-looking `jax.devices()`
    has been observed on a tunnel whose first real dispatch then hangs)."""
    import subprocess

    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                # share main()'s persistent compile cache so a healthy
                # probe costs ~1s instead of a fresh 20-40s tunnel compile
                "import jax;"
                f"jax.config.update('jax_compilation_cache_dir', '{_compile_cache_dir()}');"
                "jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0);"
                "import jax.numpy as jnp;"
                "jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64)))"
                ".block_until_ready()",
            ],
            timeout=timeout_s,
            capture_output=True,
        )
        if proc.returncode != 0:
            # a non-tunnel failure (broken install, bad XLA flag) must not
            # masquerade as a wedge — surface the child's actual error
            tail = proc.stderr.decode(errors="replace").strip().splitlines()[-8:]
            print(
                "bench: probe exited rc=%d; stderr tail:\n%s"
                % (proc.returncode, "\n".join(tail)),
                file=sys.stderr,
                flush=True,
            )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _kill_tree(proc) -> None:
    """SIGKILL the child's whole process group (it was started in its own
    session), then reap it. Falls back to plain kill if the group is gone."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait()
    # the child inherited stdout/stderr and may have died mid-write:
    # terminate any partial line so the NEXT attempt's final metric JSON
    # still starts at column 0 (the driver parses the last stream line)
    sys.stdout.write("\n")
    sys.stdout.flush()
    sys.stderr.write("\n")
    sys.stderr.flush()


def _supervise() -> int:
    """Run the measurement in a CHILD process under a hard deadline.

    The axon tunnel has three observed failure modes: backend init that
    RAISES (BENCH_r01), init that HANGS, and — nastiest — a probe/init
    that SUCCEEDS followed by a first compile or dispatch that hangs
    forever. Only a killable child defends against the last one: the
    parent never imports jax, waits out `BENCH_DEADLINE` seconds, kills
    the child on overrun, and retries ONCE with `JAX_PLATFORMS=cpu` (plus
    the reduced emergency recipe via BENCH_FELL_BACK) so the driver gets
    a labeled CPU number instead of a timeout with zero data. The child
    inherits stdout/stderr, so the metric line is still the last JSON
    printed; if both attempts die, the parent prints the error line
    itself to honor the output contract.
    """
    import subprocess

    deadline = _env_float("BENCH_DEADLINE", 1200.0)
    # BENCH_FELL_BACK is an internal supervisor→child contract var: a stale
    # export (e.g. left over from reproducing a fallback run) must not put
    # a healthy device attempt on the reduced emergency recipe
    base_env = {k: v for k, v in os.environ.items() if k != "BENCH_FELL_BACK"}
    attempts = [dict(base_env, BENCH_SUPERVISED="1")]
    # CPU retry policy: this harness environment exports JAX_PLATFORMS=axon
    # ambiently, so a set platform is NOT evidence of operator intent — an
    # unattended driver run under a wedged tunnel must still land a (cpu-
    # labeled, reduced-recipe) number. Only an explicit cpu platform makes
    # the retry pointless; BENCH_NO_FALLBACK=1 is the opt-out for anyone
    # who would rather fail than measure the wrong backend.
    platform = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if platform != "cpu" and os.environ.get("BENCH_NO_FALLBACK", "").strip() != "1":
        attempts.append(
            dict(
                base_env,
                BENCH_SUPERVISED="1",
                JAX_PLATFORMS="cpu",
                BENCH_FELL_BACK="1",
            )
        )
    # if an OUTER timeout SIGTERMs this supervisor, take the child's whole
    # process tree down too — a leaked hung child is a stray tunnel client
    # that keeps the wedge alive for the next run
    import signal

    live: dict = {"proc": None}

    def _on_term(signum, frame):  # pragma: no cover - exercised e2e only
        # signal context: must not touch Popen.wait()'s non-reentrant
        # _waitpid_lock (the interrupted frame may hold it) — raw killpg,
        # print the contract line, and leave via os._exit; init reaps
        proc = live["proc"]
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
        # still honor the output contract: leave a parseable failure record
        # (leading newline: the killed child may have left a partial line)
        sys.stdout.write("\n")
        print(
            _failure_record(f"supervisor terminated by signal {signum}"),
            flush=True,
        )
        sys.stdout.flush()
        os._exit(128 + signum)

    prev_term = signal.signal(signal.SIGTERM, _on_term)

    last_rc = 1
    started = time.monotonic()
    try:
        for i, env in enumerate(attempts):
            # the deadline is a TOTAL budget across attempts — the CPU retry
            # only gets what the first attempt left, so the driver's window
            # (sized to BENCH_DEADLINE) is honored even when attempt 1 burns
            # its share hanging. The emergency recipe needs only minutes.
            remaining = deadline - (time.monotonic() - started)
            # the first attempt always runs (an operator-set tiny budget is
            # their call); only a RETRY with too little left to produce a
            # number is pointless
            if i > 0 and remaining < 30.0:
                print(
                    f"bench: {remaining:.0f}s left of the {deadline:.0f}s budget; "
                    f"skipping attempt {i + 1}",
                    file=sys.stderr,
                    flush=True,
                )
                break
            # a non-final attempt may not starve the retry: hold back a slice
            # big enough for the reduced CPU recipe (compile + a few steps)
            is_last = i == len(attempts) - 1
            attempt_timeout = remaining if is_last else remaining - min(420.0, remaining / 2.0)
            # own session/process-group: a hung child may be deep in a probe
            # grandchild holding the tunnel — killing only the direct child
            # would orphan it as a stray concurrent tunnel client
            proc = subprocess.Popen(
                # forward argv: mode flags (--prefetch-ab) select the
                # measurement inside the supervised child
                [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
                env=env,
                start_new_session=True,
            )
            live["proc"] = proc
            try:
                last_rc = proc.wait(timeout=attempt_timeout)
                live["proc"] = None
            except subprocess.TimeoutExpired:
                _kill_tree(proc)
                live["proc"] = None
                print(
                    f"bench: attempt {i + 1} exceeded its {attempt_timeout:.0f}s "
                    f"share of the {deadline:.0f}s budget; killed",
                    file=sys.stderr,
                    flush=True,
                )
                last_rc = -9
                continue
            if last_rc == 0:
                return 0
            print(f"bench: attempt {i + 1} exited rc={last_rc}", file=sys.stderr, flush=True)
        print(
            _failure_record(f"all bench attempts failed (last rc={last_rc})"),
            flush=True,
        )
        return 1
    except KeyboardInterrupt:
        # Ctrl-C: still honor the output contract (the killed child may
        # have left a partial line — hence the leading newline)
        sys.stdout.write("\n")
        print(
            _failure_record("supervisor interrupted (SIGINT)"),
            flush=True,
        )
        return 130
    finally:
        # Ctrl-C (KeyboardInterrupt) and any other exit path: the child is
        # in its own session, so the terminal's SIGINT never reaches it —
        # reap it here or it lingers as a stray tunnel client
        signal.signal(signal.SIGTERM, prev_term)
        if live["proc"] is not None:
            _kill_tree(live["proc"])
            live["proc"] = None


def _init_backend():
    """Import jax and force backend init, guarding both wedged-tunnel
    failure modes (the BENCH_r01 postmortem: rc=1 with zero perf data):
    init that RAISES (retry once, then CPU) and init that HANGS (killable
    subprocess probe first, then CPU). A post-init hang (probe passes,
    first real compile wedges) is the supervisor's job — see _supervise().
    Returns (jax_module, backend_name, fell_back)."""
    fell_back = os.environ.get("BENCH_FELL_BACK", "").strip() == "1"
    no_fallback = os.environ.get("BENCH_NO_FALLBACK", "").strip() == "1"
    platform = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    # probe whenever the target is a DEVICE backend (unset, or the ambient
    # JAX_PLATFORMS=axon this environment exports) — the probe subprocess
    # inherits the env, so it exercises exactly the backend main() will use
    if platform != "cpu" and not no_fallback:
        timeout_s = _env_float("BENCH_INIT_TIMEOUT", 240.0)
        for attempt in range(2):
            if _probe_default_backend(timeout_s):
                break
            print(
                f"bench: default backend unreachable within {timeout_s:.0f}s "
                f"(attempt {attempt + 1})",
                file=sys.stderr,
            )
            if attempt == 0:
                time.sleep(30.0)
        else:
            os.environ["JAX_PLATFORMS"] = "cpu"
            fell_back = True
    for attempt in range(2):
        try:
            import jax

            # the experimental axon device plugin can pre-empt the
            # JAX_PLATFORMS env var; the config API route is reliable
            if os.environ.get("JAX_PLATFORMS", "").strip():
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            return jax, jax.default_backend(), fell_back
        except Exception as exc:  # noqa: BLE001 - backend init raises RuntimeError subclasses
            print(f"bench: backend init failed (attempt {attempt + 1}): {exc}", file=sys.stderr)
            _purge_jax_modules()
            if attempt == 0:
                time.sleep(2.0)
    if no_fallback:
        # the operator opted out of fallback: fail so the error line is
        # emitted instead of silently measuring the wrong backend
        raise RuntimeError(
            f"backend init failed for JAX_PLATFORMS="
            f"{os.environ.get('JAX_PLATFORMS', '')!r} and BENCH_NO_FALLBACK=1"
        )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax, jax.default_backend(), True


def _bench_tracer(jax):
    """BENCH_TRACE_DIR=<dir>: record the measurement as Chrome-trace spans
    (obs/trace.py) — the staged/warmup/measure phases plus whatever the
    instrumented layers (prefetch producer, epoch builds) emit. The whole
    effect is the process-wide set_tracer install plus an atexit export —
    so the trace survives a FAILING measurement too (the run most worth
    inspecting). No-op when unset."""
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "").strip()
    if not trace_dir:
        return
    import atexit

    from code2vec_tpu.obs.trace import Tracer, set_tracer

    tracer = Tracer(process_index=jax.process_index())
    set_tracer(tracer)

    def _export():
        try:
            tracer.export_dir(trace_dir)
        except Exception:
            pass  # never replace the bench's own exit path

    atexit.register(_export)


def _prefetch_ab() -> None:
    """``--prefetch-ab``: sync-vs-prefetch A/B over the HOST input pipeline.

    The headline bench measures the device-epoch path (corpus staged to
    HBM); this mode measures the other feed — the host-epoch path that
    multi-host runs and unstaged corpora use — where every step gathers a
    ``[B, L]`` batch on host and transfers it. Three passes over identical
    batches (same epoch, same per-arm shuffle seed): an ATTRIBUTED pass
    (block_until_ready-fenced steps → host-build / H2D / compute split),
    then a timed SYNCHRONOUS pass, then a timed PREFETCH pass
    (train/prefetch.py, depth ``BENCH_PREFETCH``). The win lands as a
    recorded A/B on one spec, not a claim: detail JSON carries both
    steps/sec numbers and the attribution dict, and the metric line's
    ``vs_baseline`` field is the prefetch/sync speedup.
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import (
        build_epoch,
        build_method_epoch,
        iter_batches,
        iter_streaming_batches,
    )
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.prefetch import StepProfiler, device_batches
    from code2vec_tpu.train.step import create_train_state, make_train_step

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # recipe: top11 shape on a device backend; the CPU fallback shrinks the
    # MODEL (not the host work) so the host-build/compute ratio stays
    # representative of a device run — on CPU the full-size step is seconds
    # of compute and any feed-side win would drown in run-to-run noise
    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    batch_size = knob("BENCH_BATCH", 1024, 256)
    bag = knob("BENCH_BAG", 200, 64)
    steps = knob("BENCH_AB_STEPS", 30, 24)
    embed_size = knob("BENCH_EMBED", 100, 8)
    encode_size = knob("BENCH_ENCODE", 100, 16)
    depth = int(os.environ.get("BENCH_PREFETCH", 2))
    attr_steps = int(os.environ.get("BENCH_PROFILE_STEPS", min(8, steps)))

    # enough methods for `steps` full batches per arm. Vocab scale follows
    # the backend: top11 on device; shrunk on CPU, where the dense Adam RMW
    # over a 360k-row table is seconds of compute that the feed-side A/B is
    # not about (host gather/pad cost is independent of vocab size)
    spec = SynthSpec(
        n_methods=max(batch_size * steps, 2048),
        n_terminals=knob("BENCH_AB_TERMINALS", 360_631, 20_000),
        n_paths=knob("BENCH_AB_PATHS", 342_845, 20_000),
        n_labels=knob("BENCH_AB_LABELS", 8_000, 800),
        mean_contexts=120.0,
        max_contexts=400,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.25,
        dtype=jnp.float32,
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )

    class_weights = jnp.ones(model_config.label_count, jnp.float32)
    example = next(
        iter_batches(
            build_method_epoch(
                data, np.arange(batch_size), bag, np.random.default_rng(0)
            ),
            batch_size,
            rng=None,
            pad_final=False,
        )
    )
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    train_step = make_train_step(model_config, class_weights)
    item_idx = np.arange(data.n_items)

    def make_batches():
        # the streaming feed (loop.py's java-large configuration): per-batch
        # host work includes the chunked epoch CONSTRUCTION, i.e. exactly
        # the gather/pad the prefetcher exists to overlap. Fresh iterator
        # with a fixed seed per arm -> identical batches in identical order.
        rng = np.random.default_rng(1)
        return iter_streaming_batches(
            lambda idx: build_epoch(data, idx, bag, rng, False),
            item_idx,
            batch_size,
            rng,
            chunk_items=batch_size * 2,
        )

    def to_device(batch):
        # explicit placement so the transfer runs on the producer thread
        # in the prefetch arm (jit would otherwise copy at dispatch)
        return jax.device_put(batch)

    def one_pass(prefetch: int, profiler=None, arm_steps: int = steps):
        nonlocal state
        done = 0
        t0 = time.perf_counter()
        with device_batches(
            make_batches(), to_device, prefetch, profiler
        ) as stream:
            for _, device_batch in stream:
                s0 = time.perf_counter()
                new_state, loss = train_step(state, device_batch)
                state = new_state
                float(loss)  # deliberate per-step sync: bounds step latency and keeps timings comparable across rounds  # jaxlint: disable=JX007
                if profiler is not None and profiler.sampled(done):
                    profiler.record_compute(
                        done, (time.perf_counter() - s0) * 1e3
                    )
                done += 1
                if done >= arm_steps:
                    break
        return done, time.perf_counter() - t0

    # compile + cache warm (not timed)
    one_pass(prefetch=0, arm_steps=2)

    # real-context accounting: both arms feed IDENTICAL batches, so one
    # untimed pass over the same stream counts the non-PAD slots the
    # timed passes actually process (PAD paths are index 0)
    real_slots = 0
    accounting = make_batches()
    for done, b in enumerate(accounting):
        if done >= steps:
            break
        valid_rows = b["example_mask"].astype(bool)
        real_slots += int((b["paths"][valid_rows] != 0).sum())
    close = getattr(accounting, "close", None)
    if close is not None:
        close()

    profiler = StepProfiler(attr_steps)
    one_pass(prefetch=0, profiler=profiler, arm_steps=max(attr_steps, 1))
    attribution = profiler.summary()

    # ABBA-ordered repeats with a best-of (min-time) estimate per arm:
    # ABBA cancels monotonic drift (frequency/cache warm-up makes later
    # arms faster), and the min is robust to the slow outliers a shared
    # host injects — both arms run identical batches, so min time is the
    # cleanest view of each pipeline's attainable rate
    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3)), 1)
    sync_times: list[float] = []
    pref_times: list[float] = []
    sync_steps = steps
    for _ in range(repeats):
        sync_steps, t = one_pass(prefetch=0)
        sync_times.append(t)
        _, t = one_pass(prefetch=depth)
        pref_times.append(t)
        _, t = one_pass(prefetch=depth)
        pref_times.append(t)
        _, t = one_pass(prefetch=0)
        sync_times.append(t)
    sync_sps = sync_steps / min(sync_times)
    pref_sps = sync_steps / min(pref_times)
    speedup = pref_sps / sync_sps

    from code2vec_tpu.obs.runtime import memory_snapshot

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "prefetch_ab",
                    "batch": batch_size,
                    "bag": bag,
                    "steps": sync_steps,
                    "prefetch_depth": depth,
                    "sync_steps_per_sec": round(sync_sps, 3),
                    "prefetch_steps_per_sec": round(pref_sps, 3),
                    "pad_efficiency": round(
                        real_slots / (sync_steps * batch_size * bag), 4
                    ) if sync_steps else None,
                    "sync_real_contexts_per_sec": round(
                        real_slots / min(sync_times), 1
                    ),
                    "prefetch_real_contexts_per_sec": round(
                        real_slots / min(pref_times), 1
                    ),
                    "padded_slots_per_sec": round(
                        sync_steps * batch_size * bag / min(pref_times), 1
                    ),
                    "speedup": round(speedup, 4),
                    "attribution": attribution,
                    "memory": memory_snapshot(),
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "host_pipeline_steps_per_sec",
                "value": round(pref_sps, 3),
                "unit": "steps/sec",
                # in AB mode the baseline IS the same-spec synchronous arm
                "vs_baseline": round(speedup, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


def _bucket_ab() -> None:
    """``--bucket-ab``: fixed-L vs length-aware bucketed batching A/B.

    Same host-pipeline harness as ``--prefetch-ab`` and the same ABBA
    best-of protocol, on an identically skewed synth corpus (lognormal
    bag lengths, ``BENCH_LENGTH_SIGMA``): both arms train on the SAME
    epoch arrays (one context subsample, shared), the fixed arm through
    ``iter_batches`` at bag ``L`` and the bucketed arm through
    ``iter_bucketed_batches`` over the histogram-derived ladder. Each arm
    processes every example exactly once per pass, so equal real-context
    work — the wall-clock ratio IS the padding waste recovered. The
    metric line reports the bucketed arm's real-context throughput with
    ``vs_baseline`` = the bucketed/fixed speedup; detail carries both
    arms' real-context and padded-slot rates plus ``pad_efficiency``, and
    the recompile detector (budgeted to the ladder) confirms the bucket
    shapes cost exactly their expected compiles.
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import (
        build_method_epoch,
        derive_bucket_ladder,
        epoch_context_counts,
        iter_batches,
        iter_bucketed_batches,
        pad_stats,
    )
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.obs.runtime import RecompileDetector, memory_snapshot
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state, make_train_step

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    batch_size = knob("BENCH_BATCH", 1024, 128)
    bag = knob("BENCH_BAG", 200, 48)
    steps = knob("BENCH_AB_STEPS", 30, 10)  # full fixed-L batches per pass
    embed_size = knob("BENCH_EMBED", 100, 8)
    encode_size = knob("BENCH_ENCODE", 100, 16)
    mean_ctx = knob("BENCH_AB_MEAN_CTX", 60, 16)
    sigma = _env_float("BENCH_LENGTH_SIGMA", 1.0)

    # the skew IS the experiment: lognormal lengths (sigma >= 0.6 per the
    # acceptance protocol) with a mean well under the bag, so fixed-L pads
    # most slots; max_contexts 2x bag exercises the top bucket's subsample
    spec = SynthSpec(
        n_methods=max(batch_size * steps, 2048),
        n_terminals=knob("BENCH_AB_TERMINALS", 360_631, 20_000),
        n_paths=knob("BENCH_AB_PATHS", 342_845, 20_000),
        n_labels=knob("BENCH_AB_LABELS", 8_000, 800),
        mean_contexts=float(mean_ctx),
        length_sigma=sigma,
        max_contexts=2 * bag,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))
    ladder = derive_bucket_ladder(np.diff(data.row_splits), bag)

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.25,
        dtype=jnp.float32,
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )
    class_weights = jnp.ones(model_config.label_count, jnp.float32)

    # ONE shared context subsample: both arms see identical per-example
    # rows; the bucketed arm just stops padding them to the full bag
    rng = np.random.default_rng(0)
    epoch = build_method_epoch(data, np.arange(data.n_items), bag, rng)
    counts = epoch_context_counts(epoch)
    real_total = int(counts.sum())
    _, fixed_slots = pad_stats(counts, (bag,), batch_size)
    _, bucket_slots = pad_stats(counts, ladder, batch_size)

    example = next(iter_batches(epoch, batch_size, rng=None, pad_final=False))
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    train_step = make_train_step(model_config, class_weights)
    detector = RecompileDetector()
    # the ladder's top width IS the fixed width, so the two arms share
    # len(ladder) step shapes total — the whole expected compile budget
    detector.track("train_step", train_step, expected_compiles=len(ladder))

    def one_pass(batches) -> tuple[int, float]:
        nonlocal state
        n = 0
        t0 = time.perf_counter()
        for b in batches:
            state, loss = train_step(state, jax.device_put(b))
            float(loss)  # deliberate per-step sync: bounds step latency and keeps timings comparable across rounds  # jaxlint: disable=JX007
            n += 1
        return n, time.perf_counter() - t0

    def fixed_batches():
        return iter_batches(epoch, batch_size, rng=None, pad_final=True)

    def bucketed_batches():
        # fresh seeded rng per pass -> identical batches every pass
        return iter_bucketed_batches(
            epoch, ladder, batch_size, rng=np.random.default_rng(2),
            pad_final=True,
        )

    # warmup: compile every ladder width + the fixed width (not timed)
    one_pass(fixed_batches())
    one_pass(bucketed_batches())
    detector.check()  # within budget: counts nothing

    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3)), 1)
    fixed_times: list[float] = []
    bucket_times: list[float] = []
    fixed_steps = bucket_steps = 0
    for _ in range(repeats):
        fixed_steps, t = one_pass(fixed_batches())
        fixed_times.append(t)
        bucket_steps, t = one_pass(bucketed_batches())
        bucket_times.append(t)
        bucket_steps, t = one_pass(bucketed_batches())
        bucket_times.append(t)
        fixed_steps, t = one_pass(fixed_batches())
        fixed_times.append(t)
    recompiles = detector.check()  # post-warmup churn would show here
    speedup = min(fixed_times) / min(bucket_times)
    bucket_rps = real_total / min(bucket_times)

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "bucket_ab",
                    "batch": batch_size,
                    "bag": bag,
                    "ladder": list(ladder),
                    "length_sigma": sigma,
                    "mean_contexts": mean_ctx,
                    "n_methods": spec.n_methods,
                    "fixed_steps": fixed_steps,
                    "bucketed_steps": bucket_steps,
                    "pad_efficiency_fixed": round(real_total / fixed_slots, 4),
                    "pad_efficiency_bucketed": round(
                        real_total / bucket_slots, 4
                    ),
                    "fixed_real_contexts_per_sec": round(
                        real_total / min(fixed_times), 1
                    ),
                    "bucketed_real_contexts_per_sec": round(bucket_rps, 1),
                    "fixed_padded_slots_per_sec": round(
                        fixed_slots / min(fixed_times), 1
                    ),
                    "bucketed_padded_slots_per_sec": round(
                        bucket_slots / min(bucket_times), 1
                    ),
                    "speedup": round(speedup, 4),
                    "post_warmup_recompiles": recompiles,
                    "memory": memory_snapshot(),
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "bucketed_real_contexts_per_sec",
                "value": round(bucket_rps, 1),
                "unit": "contexts/sec",
                # in AB mode the baseline IS the same-spec fixed-L arm
                "vs_baseline": round(speedup, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


def _longbag_ab() -> None:
    """``--longbag-ab``: truncated-at-top-rung vs chunked (longbag) A/B.

    Heavy-tailed synthetic corpus (lognormal bag lengths); the truncated
    arm is today's default — every bag subsampled down to ``BENCH_BAG``
    and batched over the base bucket ladder — while the chunked arm feeds
    the SAME corpus with ``--max_contexts 0`` semantics: the ladder grows
    longbag rungs above the base top (multiples of the kernel chunk) and
    those widths stream through the fused kernel's flash-style chunked
    softmax (interpret mode on CPU; the same code path the TPU compiles).
    One model config (longbag dispatch) and ONE step function serve both
    arms — base widths run identically in both — so the recompile
    detector's budget is exactly the full ladder. ABBA best-of like the
    other arms.

    Reported: per-arm REAL-context accounting (the chunked arm does
    strictly more real work — ``truncated_context_fraction`` goes to 0
    there, and that is the headline honesty number), per-arm wall clock
    and real-context throughput, the eval-F1 of each arm's trained state
    on UN-truncated test bags (the delta is what truncation costs), and
    the zero-post-warmup-recompiles verdict (the run FAILS on churn).
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import (
        build_method_epoch,
        derive_bucket_ladder,
        derive_longbag_ladder,
        epoch_context_counts,
        iter_batches,
        iter_bucketed_batches,
        truncated_fraction_of_counts,
    )
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.metrics import evaluate
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.obs.runtime import RecompileDetector, memory_snapshot
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import (
        create_train_state,
        make_eval_step,
        make_train_step,
    )

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    batch_size = knob("BENCH_BATCH", 256, 8)
    bag = knob("BENCH_BAG", 200, 16)
    steps = knob("BENCH_AB_STEPS", 20, 2)
    embed_size = knob("BENCH_EMBED", 100, 4)
    encode_size = knob("BENCH_ENCODE", 100, 8)
    mean_ctx = knob("BENCH_AB_MEAN_CTX", 60, 10)
    chunk_l = knob("BENCH_PALLAS_CHUNK_L", 128, 128)
    sigma = _env_float("BENCH_LENGTH_SIGMA", 1.2)

    # heavy tail past the bag cap IS the experiment: a lognormal with
    # sigma >= 1 puts a real fraction of contexts beyond BENCH_BAG, which
    # the truncated arm silently drops and the chunked arm streams
    spec = SynthSpec(
        n_methods=max(batch_size * steps * 2, 64),
        n_terminals=knob("BENCH_AB_TERMINALS", 100_000, 200),
        n_paths=knob("BENCH_AB_PATHS", 100_000, 150),
        n_labels=knob("BENCH_AB_LABELS", 2_000, 20),
        mean_contexts=float(mean_ctx),
        length_sigma=sigma,
        max_contexts=16 * bag,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))
    counts = np.diff(data.row_splits)
    base_ladder = derive_bucket_ladder(counts, bag)
    lengths, weights = np.unique(counts, return_counts=True)
    longbag_rungs = derive_longbag_ladder(
        lengths, weights, bag, chunk_l=chunk_l
    )
    full_ladder = tuple(base_ladder) + longbag_rungs
    top_width = full_ladder[-1]

    # ONE model config drives both arms: base widths dispatch exactly as
    # the truncated arm would alone, widths above `bag` force the fused
    # kernel's online chunked softmax (the longbag_width dispatch)
    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.0,
        dtype=jnp.float32,
        use_pallas=True,
        pallas_impl="pool_only",
        pallas_block_b=min(8, batch_size),
        pallas_chunk_l=chunk_l,
        longbag_width=bag,
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )
    class_weights = jnp.ones(model_config.label_count, jnp.float32)

    split = max(int(spec.n_methods * 0.8), 1)
    train_items = np.arange(split)
    test_items = np.arange(split, spec.n_methods)

    # one epoch build per arm: truncated subsamples down to `bag`, the
    # chunked build keeps every context up to the top longbag rung
    epoch_truncated = build_method_epoch(
        data, train_items, bag, np.random.default_rng(1)
    )
    epoch_full = build_method_epoch(
        data, train_items, top_width, np.random.default_rng(1)
    )
    real_truncated = int(epoch_context_counts(epoch_truncated).sum())
    real_full = int(epoch_context_counts(epoch_full).sum())
    trunc_fraction = truncated_fraction_of_counts(counts[train_items], bag)

    example = next(
        iter_batches(epoch_truncated, batch_size, rng=None, pad_final=True)
    )
    # two states from the SAME key (identical init values, separate
    # buffers): the step donates its state, so the arms cannot share one
    state_truncated = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    state_chunked = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    train_step = make_train_step(model_config, class_weights)
    detector = RecompileDetector()
    detector.track(
        "train_step", train_step, expected_compiles=len(full_ladder)
    )

    def one_pass(state, batches) -> tuple[object, float]:
        t0 = time.perf_counter()
        for b in batches:
            state, loss = train_step(state, jax.device_put(b))
        jax.block_until_ready(loss)
        return state, time.perf_counter() - t0

    def truncated_batches():
        return iter_bucketed_batches(
            epoch_truncated, base_ladder, batch_size,
            rng=np.random.default_rng(2), pad_final=True,
        )

    def chunked_batches():
        return iter_bucketed_batches(
            epoch_full, full_ladder, batch_size,
            rng=np.random.default_rng(2), pad_final=True,
        )

    # warmup compiles every width of both arms (untimed), then the ABBA
    # passes must add zero compiles
    state_truncated, _ = one_pass(state_truncated, truncated_batches())
    state_chunked, _ = one_pass(state_chunked, chunked_batches())
    detector.check()

    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 2)), 1)
    t_times: list[float] = []
    c_times: list[float] = []
    for _ in range(repeats):
        state_truncated, t = one_pass(state_truncated, truncated_batches())
        t_times.append(t)
        state_chunked, t = one_pass(state_chunked, chunked_batches())
        c_times.append(t)
        state_chunked, t = one_pass(state_chunked, chunked_batches())
        c_times.append(t)
        state_truncated, t = one_pass(state_truncated, truncated_batches())
        t_times.append(t)
    recompiles = detector.check()
    if recompiles:
        raise RuntimeError(
            f"longbag-ab verdict FAILED: {recompiles} post-warmup "
            "recompile(s) — a shape escaped the ladder"
        )

    # eval both trained states on UN-truncated test bags through ONE eval
    # step (identical param trees across impls): the f1 delta is what the
    # truncated arm's dropped contexts cost at evaluation time
    eval_step = make_eval_step(model_config, class_weights)
    test_epoch = build_method_epoch(
        data, test_items, top_width, np.random.default_rng(3)
    )

    def eval_f1(state) -> float:
        preds = []
        labels = []
        for b in iter_bucketed_batches(
            test_epoch, full_ladder, batch_size, rng=None, pad_final=True
        ):
            out = eval_step(state, jax.device_put(b))
            valid = b["example_mask"].astype(bool)
            preds.append(np.asarray(out["preds"])[valid])
            labels.append(b["labels"][valid])
        if not preds:
            return 0.0
        _, _, _, f1 = evaluate(
            "subtoken", np.concatenate(labels), np.concatenate(preds),
            data.label_vocab,
        )
        return float(f1)

    f1_truncated = eval_f1(state_truncated)
    f1_chunked = eval_f1(state_chunked)

    chunked_rps = real_full / min(c_times)
    truncated_rps = real_truncated / min(t_times)

    from code2vec_tpu.ops.backend import resolve as resolve_backend

    kernel_backend = resolve_backend()

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "longbag_ab",
                    "strategy": kernel_backend.label,
                    "interpret": kernel_backend.interpret,
                    "batch": batch_size,
                    "bag": bag,
                    "base_ladder": list(base_ladder),
                    "longbag_rungs": list(longbag_rungs),
                    "length_sigma": sigma,
                    "n_methods": spec.n_methods,
                    # real-context accounting: what each arm actually fed
                    "real_contexts_truncated": real_truncated,
                    "real_contexts_chunked": real_full,
                    "truncated_context_fraction_truncated": round(
                        trunc_fraction, 6
                    ),
                    "truncated_context_fraction_chunked": 0.0,
                    "truncated_real_contexts_per_sec": round(
                        truncated_rps, 1
                    ),
                    "chunked_real_contexts_per_sec": round(chunked_rps, 1),
                    "eval_f1_truncated": round(f1_truncated, 4),
                    "eval_f1_chunked": round(f1_chunked, 4),
                    "eval_f1_delta": round(f1_chunked - f1_truncated, 4),
                    "post_warmup_recompiles": recompiles,
                    "verdict_ok": recompiles == 0,
                    "memory": memory_snapshot(),
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "longbag_real_contexts_per_sec",
                "value": round(chunked_rps, 1),
                "unit": "contexts/sec",
                # the baseline is the truncated arm's REAL-context rate;
                # note the chunked arm is doing strictly more real work
                # per example (the whole point), so <1 on CPU interpret
                # is expected and honest
                "vs_baseline": round(chunked_rps / truncated_rps, 4)
                if truncated_rps else None,
                "backend": backend,
            }
        ),
        flush=True,
    )


def _ooc_ab() -> None:
    """``--ooc-ab``: in-RAM vs mmap-CSR feed A/B at equal real-context work.

    The out-of-core acceptance instrument (ISSUE 10): one skewed synth
    corpus is written as TEXT, converted to the binary CSR container
    (tools/corpus_convert.py), and the same bucketed epoch is trained from
    both backings — arm A feeds from the in-RAM ``EpochSource`` (the
    materialized [N, L] path), arm B from ``MmapCorpusSource`` (per-bucket
    batches gathered straight from the mmap views; no epoch tensor ever
    exists). Both arms cover every example exactly once per pass over the
    SAME ladder, so equal real-context work — the wall-clock ratio is the
    out-of-core feed's cost (or win), not a workload difference. ABBA
    best-of like the other AB arms. Detail carries both arms' real-context
    rates, ``pad_efficiency``, the on-disk container size, and two memory
    records from the obs sampler: ``memory_mmap_feed`` — the host-RSS
    delta of a full mmap-fed pass measured BEFORE the in-RAM corpus is
    even loaded (the bounded-memory claim, isolated: nothing
    in-RAM-arm-sized is live in the process yet) — and per-arm
    whole-process snapshots taken during the A/B (those necessarily
    include the other arm's live corpus; context, not the claim).
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import (
        EpochSource,
        MmapCorpusSource,
        derive_bucket_ladder,
        iter_batches,
    )
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.obs.runtime import memory_snapshot
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state, make_train_step

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    batch_size = knob("BENCH_BATCH", 1024, 128)
    bag = knob("BENCH_BAG", 200, 48)
    steps = knob("BENCH_AB_STEPS", 30, 10)  # full top-width batches per pass
    embed_size = knob("BENCH_EMBED", 100, 8)
    encode_size = knob("BENCH_ENCODE", 100, 16)
    mean_ctx = knob("BENCH_AB_MEAN_CTX", 60, 16)
    sigma = _env_float("BENCH_LENGTH_SIGMA", 1.0)

    import tempfile

    spec = SynthSpec(
        n_methods=max(batch_size * steps, 2048),
        n_terminals=knob("BENCH_AB_TERMINALS", 360_631, 20_000),
        n_paths=knob("BENCH_AB_PATHS", 342_845, 20_000),
        n_labels=knob("BENCH_AB_LABELS", 8_000, 800),
        mean_contexts=float(mean_ctx),
        length_sigma=sigma,
        max_contexts=2 * bag,
        seed=0,
    )
    tmp = tempfile.mkdtemp(prefix="c2v_ooc_ab_")
    # the CSR mmap stays open for the whole arm, so the synthetic corpus
    # (GBs at the default spec) is reclaimed at exit, not inline
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)
    paths = generate_corpus_files(tmp, spec)
    csr_path = os.path.join(tmp, "corpus.csr")
    from tools.corpus_convert import text_to_csr

    t0 = time.perf_counter()
    text_to_csr(paths["corpus"], csr_path)
    convert_seconds = time.perf_counter() - t0
    corpus_bytes = os.path.getsize(csr_path)

    # the MMAP side first — and alone: the isolated-feed memory record
    # below must run while nothing in-RAM-arm-sized is live
    data_mmap = load_corpus(csr_path, paths["path_idx"], paths["terminal_idx"])
    assert data_mmap.mmap_backed
    ladder = derive_bucket_ladder(np.diff(data_mmap.row_splits), bag)
    counts = np.minimum(np.diff(data_mmap.row_splits), bag)
    real_total = int(counts.sum())

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data_mmap.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.25,
        dtype=jnp.float32,
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )
    class_weights = jnp.ones(model_config.label_count, jnp.float32)
    item_idx = np.arange(data_mmap.n_items)

    mmap_source = MmapCorpusSource(
        data_mmap, item_idx, batch_size, bag, ladder=ladder
    )

    example_stream = mmap_source.batches(np.random.default_rng(0))
    example = next(example_stream)
    example_stream.close()
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )
    train_step = make_train_step(model_config, class_weights)

    def one_pass(source) -> tuple[int, float]:
        nonlocal state
        n = 0
        t0 = time.perf_counter()
        # fresh seeded rng per pass -> identical batch plans every pass
        for b in source.batches(np.random.default_rng(2)):
            state, loss = train_step(state, jax.device_put(b))
            float(loss)  # deliberate per-step sync: bounds step latency and keeps timings comparable across rounds  # jaxlint: disable=JX007
            n += 1
        return n, time.perf_counter() - t0

    # warmup: compile every ladder width (not timed)
    one_pass(mmap_source)
    # THE memory claim, isolated: RSS delta of one full mmap-fed pass with
    # compiles warm and the in-RAM corpus NOT YET LOADED — nothing
    # corpus-sized exists in the process except the kernel's page cache
    rss_before_feed = memory_snapshot().get("host_rss_bytes")
    one_pass(mmap_source)
    rss_after_feed = memory_snapshot().get("host_rss_bytes")
    memory_mmap_feed = {
        "rss_before_bytes": rss_before_feed,
        "rss_after_bytes": rss_after_feed,
        "rss_delta_bytes": (
            rss_after_feed - rss_before_feed
            if None not in (rss_before_feed, rss_after_feed)
            else None
        ),
        "corpus_bytes_on_disk": corpus_bytes,
    }

    # only now bring up the in-RAM arm
    data_ram = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"],
        cache=False, native=False,
    )
    ram_source = EpochSource(data_ram, item_idx, batch_size, bag, ladder=ladder)
    one_pass(ram_source)

    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3)), 1)
    ram_times: list[float] = []
    mmap_times: list[float] = []
    ram_steps = mmap_steps = 0
    memory_ram = memory_mmap = None
    for _ in range(repeats):
        ram_steps, t = one_pass(ram_source)
        ram_times.append(t)
        memory_ram = memory_snapshot()
        mmap_steps, t = one_pass(mmap_source)
        mmap_times.append(t)
        mmap_steps, t = one_pass(mmap_source)
        mmap_times.append(t)
        memory_mmap = memory_snapshot()
        ram_steps, t = one_pass(ram_source)
        ram_times.append(t)
    speedup = min(ram_times) / min(mmap_times)
    mmap_rps = real_total / min(mmap_times)
    real, slots = mmap_source.pad_stats()

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "ooc_ab",
                    "batch": batch_size,
                    "bag": bag,
                    "ladder": list(ladder),
                    "length_sigma": sigma,
                    "n_methods": spec.n_methods,
                    "corpus_bytes_on_disk": corpus_bytes,
                    "convert_seconds": round(convert_seconds, 2),
                    "in_ram_steps": ram_steps,
                    "mmap_steps": mmap_steps,
                    "pad_efficiency": round(real / slots, 4) if slots else None,
                    "in_ram_real_contexts_per_sec": round(
                        real_total / min(ram_times), 1
                    ),
                    "mmap_real_contexts_per_sec": round(mmap_rps, 1),
                    "mmap_vs_in_ram": round(speedup, 4),
                    "memory_mmap_feed": memory_mmap_feed,
                    "memory_process_after_in_ram_arm": memory_ram,
                    "memory_process_after_mmap_arm": memory_mmap,
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "mmap_csr_real_contexts_per_sec",
                "value": round(mmap_rps, 1),
                "unit": "contexts/sec",
                # in AB mode the baseline IS the same-spec in-RAM arm
                "vs_baseline": round(speedup, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


def _feed_ab() -> None:
    """``--feed-ab``: coordinator-build vs parallel host ingest at equal
    real-context work (ISSUE 14 acceptance instrument).

    One skewed synth corpus converted to the mmap-CSR container feeds a
    deliberately HOST-HEAVY bucketed recipe (large bags, tiny model: the
    classic feed-starved accelerator shape) twice through the prefetched
    host pipeline — arm A with ``--feed_workers 0`` (single-threaded
    coordinator builds, the historical path), arm B with ``--feed_workers
    N`` (``data/parallel_feed.py``: plans on the coordinator, builds on N
    forked workers through the shared-memory arena). Same seeds → the two
    arms dispatch IDENTICAL batches in identical order, so the wall-clock
    ratio is pure feed cost. The run FAILS its verdict unless the fresh-
    state loss trajectories match bitwise, the recompile detector saw
    exactly the ladder's compiles, and the workers arm's measured
    ``feed_wait_ms`` undercuts the sync arm's ``host_build_ms``
    attribution (input-boundness must measurably shrink, not vibes).
    ABBA best-of like the other AB arms.
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.parallel_feed import FeedPool, ParallelFeed
    from code2vec_tpu.data.pipeline import MmapCorpusSource, derive_bucket_ladder
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.obs.runtime import RecompileDetector
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.prefetch import StepProfiler, device_batches
    from code2vec_tpu.train.step import create_train_state, make_train_step

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    batch_size = knob("BENCH_BATCH", 512, 256)
    bag = knob("BENCH_BAG", 200, 64)
    steps = knob("BENCH_AB_STEPS", 40, 14)  # top-width batches per pass
    embed_size = knob("BENCH_EMBED", 64, 8)
    encode_size = knob("BENCH_ENCODE", 64, 16)
    # host-heavy by construction: long raw bags mean every batch pays a
    # large subsample sort + CSR gather while the model stays tiny
    mean_ctx = knob("BENCH_FEED_MEAN_CTX", 300, 220)
    feed_workers = knob("BENCH_FEED_WORKERS", 4, 4)
    prefetch = knob("BENCH_PREFETCH", 2, 2)
    sigma = _env_float("BENCH_LENGTH_SIGMA", 0.8)

    import tempfile

    spec = SynthSpec(
        n_methods=max(batch_size * steps, 2048),
        n_terminals=knob("BENCH_AB_TERMINALS", 80_000, 20_000),
        n_paths=knob("BENCH_AB_PATHS", 80_000, 20_000),
        n_labels=knob("BENCH_AB_LABELS", 2_000, 800),
        mean_contexts=float(mean_ctx),
        length_sigma=sigma,
        max_contexts=3 * bag,
        seed=0,
    )
    tmp = tempfile.mkdtemp(prefix="c2v_feed_ab_")
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)
    paths = generate_corpus_files(tmp, spec)
    csr_path = os.path.join(tmp, "corpus.csr")
    from tools.corpus_convert import text_to_csr

    text_to_csr(paths["corpus"], csr_path)
    data = load_corpus(csr_path, paths["path_idx"], paths["terminal_idx"])
    assert data.mmap_backed

    ladder = derive_bucket_ladder(np.diff(data.row_splits), bag)
    counts = np.minimum(np.diff(data.row_splits), bag)
    real_total = int(counts.sum())
    item_idx = np.arange(data.n_items)

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.25,
        dtype=jnp.float32,
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )
    class_weights = jnp.ones(model_config.label_count, jnp.float32)

    sync_source = MmapCorpusSource(
        data, item_idx, batch_size, bag, ladder=ladder
    )
    pool = FeedPool(
        data, feed_workers, batch_size, int(ladder[-1]),
        tracer=None,
    )
    feed_source = ParallelFeed(
        MmapCorpusSource(data, item_idx, batch_size, bag, ladder=ladder),
        pool,
    )

    example_stream = sync_source.batches(np.random.default_rng(0))
    example = next(example_stream)
    example_stream.close()

    # ONE template state, leaf-copied per pass: the step donates its state
    # buffers, so passes that must start from the SAME weights need their
    # own copy — and it must be a leaf copy of one state, not a second
    # create_train_state(), whose fresh optax closures are new treedef aux
    # data and would recompile the step per state
    state_template = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )

    def fresh_state():
        return jax.tree_util.tree_map(jnp.copy, state_template)

    train_step = make_train_step(model_config, class_weights)
    detector = RecompileDetector()
    detector.track("train_step", train_step, expected_compiles=len(ladder))

    def one_pass(source, state, profiler=None, collect_losses=False):
        """One full epoch (seeded rng → identical batch stream per arm);
        windowed dispatch like the train loop — per-step host syncs would
        hide exactly the overlap this A/B measures. ``profiler`` fences
        its sampled steps (mirroring _train_pass) so the attribution
        split is real device time, not async dispatch."""
        losses = []
        t0 = time.perf_counter()
        with device_batches(
            source.batches(np.random.default_rng(2)), jax.device_put,
            prefetch, profiler,
        ) as stream:
            for step, (_, device_batch) in enumerate(stream):
                sampled = profiler is not None and profiler.sampled(step)
                if sampled and losses:
                    jax.block_until_ready(losses[-1])
                ts = time.perf_counter()
                state, loss = train_step(state, device_batch)
                if sampled:
                    jax.block_until_ready(loss)
                    profiler.record_compute(
                        step, (time.perf_counter() - ts) * 1e3
                    )
                losses.append(loss)
                if step >= 2:
                    jax.block_until_ready(losses[step - 2])
        jax.block_until_ready(losses[-1])
        elapsed = time.perf_counter() - t0
        fetched = (
            [float(x) for x in jax.device_get(losses)]
            if collect_losses else None
        )
        return state, elapsed, len(losses), fetched

    # warmup: compile every ladder width (not timed), both arms' plumbing
    state, *_ = one_pass(sync_source, fresh_state())
    state, *_ = one_pass(feed_source, state)
    detector.check()  # warmup baseline: exactly the ladder's compiles

    # bitwise-identical loss trajectory: fresh state + same seed per arm —
    # the workers must change WHERE batches are built, not what is trained
    _, _, _, losses_sync = one_pass(
        sync_source, fresh_state(), collect_losses=True
    )
    _, _, _, losses_feed = one_pass(
        feed_source, fresh_state(), collect_losses=True
    )
    bitwise_equal = losses_sync == losses_feed

    # profiler attribution per arm (separate pass so fencing can't taint
    # the timed ABBA window); stride spans the epoch after the first pass
    prof_sync = StepProfiler(sample_steps=8)
    prof_feed = StepProfiler(sample_steps=8)
    for prof in (prof_sync, prof_feed):
        prof.observe_epoch_length(max(steps, 1))
        prof.reset()
    state, *_ = one_pass(sync_source, state, profiler=prof_sync)
    state, *_ = one_pass(feed_source, state, profiler=prof_feed)
    attribution_sync = prof_sync.summary()
    attribution_feed = prof_feed.summary()

    try:
        repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3)), 1)
        sync_times: list[float] = []
        feed_times: list[float] = []
        n_steps = 0
        for _ in range(repeats):
            state, t, n_steps, _ = one_pass(sync_source, state)
            sync_times.append(t)
            state, t, n_steps, _ = one_pass(feed_source, state)
            feed_times.append(t)
            state, t, n_steps, _ = one_pass(feed_source, state)
            feed_times.append(t)
            state, t, n_steps, _ = one_pass(sync_source, state)
            sync_times.append(t)
    finally:
        pool.close()

    post_warmup = detector.check()
    speedup = min(sync_times) / min(feed_times)
    feed_rps = real_total / min(feed_times)
    real, slots = feed_source.pad_stats()
    feed_wait_shrank = bool(
        attribution_sync and attribution_feed
        and attribution_feed["feed_wait_ms"]
        < attribution_sync["host_build_ms"]
    )
    # the wall-clock clauses need hardware that can actually parallelize:
    # worker processes inherit the CPU affinity mask, so on a host with
    # too few usable cores the two arms do identical serial work and no
    # feed can win — correctness clauses (bitwise, zero recompiles) still
    # gate, the speedup clauses are reported but skipped
    try:
        host_cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-linux
        host_cores = os.cpu_count() or 1
    min_cores = int(os.environ.get("BENCH_FEED_MIN_CORES", 4))
    min_speedup = _env_float("BENCH_FEED_MIN_SPEEDUP", 1.2)
    speedup_applicable = host_cores >= min_cores
    speedup_ok = speedup >= min_speedup and feed_wait_shrank
    verdict_ok = bool(
        bitwise_equal
        and post_warmup == 0
        and (speedup_ok or not speedup_applicable)
    )

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "feed_ab",
                    "batch": batch_size,
                    "bag": bag,
                    "ladder": list(ladder),
                    "mean_contexts": mean_ctx,
                    "length_sigma": sigma,
                    "n_methods": spec.n_methods,
                    "steps_per_pass": n_steps,
                    "prefetch_batches": prefetch,
                    "feed": {
                        "workers": feed_workers,
                        "arena_slots": pool.slots,
                        "delivery": pool.deliver_mode(),
                    },
                    "pad_efficiency": round(real / slots, 4) if slots else None,
                    "sync_real_contexts_per_sec": round(
                        real_total / min(sync_times), 1
                    ),
                    "feed_real_contexts_per_sec": round(feed_rps, 1),
                    "feed_vs_sync": round(speedup, 4),
                    "attribution_sync": attribution_sync,
                    "attribution_feed": attribution_feed,
                    "feed_wait_shrank": feed_wait_shrank,
                    "bitwise_loss_equal": bitwise_equal,
                    "post_warmup_compiles": post_warmup,
                    "host_cores": host_cores,
                    "speedup_verdict": (
                        ("pass" if speedup_ok else "fail")
                        if speedup_applicable
                        else f"skipped ({host_cores} host cores < "
                        f"{min_cores}: both arms serialize on the same "
                        "CPUs, no feed can win)"
                    ),
                    "verdict_ok": verdict_ok,
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    if not verdict_ok:
        raise SystemExit(
            f"--feed-ab verdict failed: bitwise_loss_equal={bitwise_equal}, "
            f"post_warmup_compiles={post_warmup}, "
            f"feed_vs_sync={speedup:.3f} (need >= {min_speedup}), "
            f"feed_wait_shrank={feed_wait_shrank}"
        )
    print(
        json.dumps(
            {
                "metric": "feed_real_contexts_per_sec",
                "value": round(feed_rps, 1),
                "unit": "contexts/sec",
                # in AB mode the baseline IS the same-recipe workers=0 arm
                "vs_baseline": round(speedup, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


def _ann_ab() -> None:
    """``--ann-ab``: ANN (IVF-PQ) vs exact retrieval on one synthetic
    clustered index — the ISSUE-11 acceptance instrument.

    One clustered vector corpus (Gaussian blobs, seeded) is indexed both
    ways: arm A is the exact ``RetrievalIndex`` (O(N*E) matmul per query),
    arm B the ``AnnRetrievalIndex`` (coarse probe -> LUT-scored PQ codes ->
    exact re-rank) built by ``code2vec_tpu/ann``. Every arm answers the
    SAME queries one at a time (Q=1 — the serving shape), so per-query
    wall-clock is directly comparable; the pinned comparison arm uses ABBA
    best-of like the other AB modes. The ``n_probe`` sweep reports
    recall@{1,10,100} against exact ground truth, QPS, and the REAL
    probed-row fraction (``cell_counts`` of the probed cells / N — pad
    slots cost padded-slab work but don't count as corpus coverage). The
    headline arm is the smallest swept ``n_probe`` reaching recall@10 >=
    0.95. The serve bench's recompile verdict applies to the query path:
    after warmup, any growth of either backend's compiled-fn table fails
    the run.
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)

    from code2vec_tpu.ann.index import build_index, normalize_rows
    from code2vec_tpu.obs.runtime import RecompileDetector, RuntimeHealth
    from code2vec_tpu.serve.retrieval import AnnRetrievalIndex, RetrievalIndex

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    n = knob("BENCH_ANN_N", 1_000_000, 120_000)
    dim = knob("BENCH_ANN_DIM", 128, 32)
    n_list = knob("BENCH_ANN_NLIST", 2048, 512)
    m = knob("BENCH_ANN_M", 16, 8)
    true_clusters = knob("BENCH_ANN_CLUSTERS", 8192, 1024)
    n_queries = knob("BENCH_ANN_QUERIES", 64, 64)
    shortlist = knob("BENCH_ANN_SHORTLIST", 256, 200)
    km_iters = knob("BENCH_ANN_KM_ITERS", 20, 10)
    pq_iters = knob("BENCH_ANN_PQ_ITERS", 15, 8)
    probes = [
        int(tok)
        for tok in os.environ.get("BENCH_ANN_PROBES", "1,2,4,8,16").split(",")
        if tok.strip()
    ]

    # clustered synth corpus: queries are perturbed corpus points, so the
    # true neighbors concentrate the way real code-search queries do
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(true_clusters, dim)).astype(np.float32)
    member = rng.integers(0, true_clusters, n)
    rows = (
        centers[member] + 0.12 * rng.normal(size=(n, dim))
    ).astype(np.float32)
    labels = [f"m{i}" for i in range(n)]
    q_src = rng.integers(0, n, n_queries)
    queries = (
        rows[q_src] + 0.05 * rng.normal(size=(n_queries, dim))
    ).astype(np.float32)

    unit = normalize_rows(rows)
    qn = normalize_rows(queries)
    # exact ground truth (numpy, f64-free: same f32 matmul as the arms)
    truth = np.argsort(-(qn @ unit.T), axis=1)[:, :100]
    truth_sets = {
        k: [set(truth[i, :k].tolist()) for i in range(n_queries)]
        for k in (1, 10, 100)
    }

    t0 = time.perf_counter()
    index, _ = build_index(
        rows, n_list=n_list, m=m, seed=0, kmeans_iters=km_iters,
        pq_iters=pq_iters,
    )
    build_seconds = time.perf_counter() - t0

    exact = RetrievalIndex(labels, rows)

    def one_pass(idx) -> float:
        """Answer every query ONE AT A TIME (the serving shape); returns
        seconds for the whole set."""
        t0 = time.perf_counter()
        for i in range(n_queries):
            idx.top_k(queries[i], 100)
        return time.perf_counter() - t0

    def recall_of(idx) -> dict[str, float]:
        out = {}
        answers = [
            # labels are "m<row>" by construction: decode, don't search
            [int(name[1:]) for name, _ in idx.top_k(queries[i], 100)]
            for i in range(n_queries)
        ]
        for k in (1, 10, 100):
            hits = sum(
                len(set(ans[:k]) & truth_sets[k][i]) / k
                for i, ans in enumerate(answers)
            )
            out[f"recall@{k}"] = round(hits / n_queries, 4)
        return out

    sweep: list[dict] = []
    ann_arms: dict[int, AnnRetrievalIndex] = {}
    for n_probe in probes:
        ann = AnnRetrievalIndex(
            labels, unit, index, n_probe=n_probe, shortlist=shortlist
        )
        ann_arms[n_probe] = ann
        one_pass(ann)  # warmup: compile the Q=1 bucket
        t = min(one_pass(ann) for _ in range(2))
        rec = recall_of(ann)
        sweep.append(
            {
                "n_probe": n_probe,
                **rec,
                "qps": round(n_queries / t, 1),
                "per_query_ms": round(1e3 * t / n_queries, 3),
                "probed_row_fraction": round(
                    ann.probed_fraction(queries), 4
                ),
                "kernel_backend": ann.searcher._backend_label(),
            }
        )

    pinned = next(
        (arm for arm in sweep if arm["recall@10"] >= 0.95), sweep[-1]
    )
    pinned_probe = pinned["n_probe"]
    ann = ann_arms[pinned_probe]

    # the recompile verdict on the query path: every executable both arms
    # will ever need exists after warmup; any growth during the timed
    # window is a silent per-request compile — fail the run
    one_pass(exact)  # exact warmup
    detector = RecompileDetector(health=RuntimeHealth())
    detector.track("exact_query_fns", exact)
    detector.track("ann_query_fns", ann)
    detector.check()

    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3)), 1)
    exact_times: list[float] = []
    ann_times: list[float] = []
    for _ in range(repeats):  # ABBA best-of
        exact_times.append(one_pass(exact))
        ann_times.append(one_pass(ann))
        ann_times.append(one_pass(ann))
        exact_times.append(one_pass(exact))
    post_warmup = detector.check()
    speedup = min(exact_times) / min(ann_times)
    qps = n_queries / min(ann_times)
    verdict_ok = (
        post_warmup == 0 and pinned["recall@10"] >= 0.95 and speedup > 1.0
    )

    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "mode": "ann_ab",
                    "n": n,
                    "dim": dim,
                    "n_list": index.meta["n_list"],
                    "m": index.meta["m"],
                    "capacity": index.meta["capacity"],
                    "shortlist": shortlist,
                    "n_queries": n_queries,
                    "build_seconds": round(build_seconds, 2),
                    "index_code_bytes": int(
                        index.codes.nbytes + index.scales.nbytes
                    ),
                    "exact_matrix_bytes": int(unit.nbytes),
                    "sweep": sweep,
                    "pinned_n_probe": pinned_probe,
                    "pinned_recall": {
                        k: pinned[k]
                        for k in ("recall@1", "recall@10", "recall@100")
                    },
                    "ann_schedule": ann.searcher.schedule.to_dict(),
                    "kernel_backend": ann.searcher._backend_label(),
                    "exact_per_query_ms": round(
                        1e3 * min(exact_times) / n_queries, 3
                    ),
                    "ann_per_query_ms": round(
                        1e3 * min(ann_times) / n_queries, 3
                    ),
                    "ann_vs_exact": round(speedup, 4),
                    "post_warmup_recompiles": post_warmup,
                    "verdict_ok": verdict_ok,
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "ann_queries_per_sec",
                "value": round(qps, 1),
                # in AB mode the baseline IS the same-index exact arm
                "vs_baseline": round(speedup, 4),
                "unit": "queries/sec",
                "backend": backend,
            }
        ),
        flush=True,
    )
    if not verdict_ok:
        raise SystemExit(
            f"ann-ab verdict failed: recall@10={pinned['recall@10']} "
            f"speedup={round(speedup, 3)} "
            f"post_warmup_recompiles={post_warmup}"
        )


def _kernel_provenance(model_config) -> dict:
    """Kernel impl + schedule provenance for a detail block: the stamp must
    say which lowering produced the number, and — for autotuned runs — how
    much schedule search the process paid (the obs/ counters)."""
    from code2vec_tpu.ops.backend import resolve as resolve_backend

    configured = model_config.pallas_backend
    out = {
        "use_pallas": model_config.use_pallas,
        "impl": model_config.pallas_impl if model_config.use_pallas else "xla",
        "backend": configured,
        "strategy": resolve_backend(
            backend=None if configured == "auto" else configured
        ).label,
        "block_b": model_config.pallas_block_b,
        "dma_depth": model_config.pallas_dma_depth,
        "chunk_l": model_config.pallas_chunk_l,
        "table_dtype": model_config.table_dtype,
    }
    if model_config.use_pallas and model_config.pallas_impl == "auto":
        from code2vec_tpu.ops.autotune import counters_snapshot, get_cache

        out["autotune_cache"] = get_cache().path
        out["autotune_counters"] = counters_snapshot()
    return out


def _kernel_ab() -> None:
    """``--kernel-ab``: fused-vs-XLA kernel A/B at real-context accounting.

    Measures the EVAL/SERVING forward (the int8 arms cannot train — the
    step contract forbids quantized master weights) over identical batches
    of a top11-shaped synth corpus for the arms
    {xla, pool_only, fused} × {f32} plus {pool_only, fused} × {int8}, with
    a generalized ABBA protocol: the arm order runs forward then reversed
    per repeat (monotonic drift cancels), best-of per arm. The metric line
    reports the fused-f32 arm's real-context throughput with
    ``vs_baseline`` = fused/xla speedup; the detail block records every
    arm's rate plus kernel impl + schedule provenance.

    ``--autotune`` first runs the Autocomp-style schedule search
    (ops/autotune.py) for this run's shapes and records the winners +
    cache counters — a SECOND identical invocation loads every schedule
    from the persisted cache with zero timing runs (the counters in the
    detail block prove it). ``--dry`` makes that pass serialize-only.

    Off TPU the resolved lowering strategy (ops/backend.py) decides what
    actually runs: the default is the compiled CPU strategy (plain XLA
    with the kernels' exact semantics — ``"interpret": false``), and two
    extra ``*_interp`` arms pin the legacy Pallas-interpreter path so the
    record quantifies compiled-vs-interpret at equal real-context work.
    Under ``C2V_KERNEL_BACKEND=interpret`` every arm runs the interpreter
    and the record is flagged ``"interpret": true`` with the honest note
    that the numbers characterize the interpreter, not the hardware.
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
    from code2vec_tpu.obs.runtime import RecompileDetector, memory_snapshot
    from code2vec_tpu.ops import autotune as at
    from code2vec_tpu.ops.backend import resolve as resolve_backend
    from code2vec_tpu.ops.quant import quantize_table

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    kernel_backend = resolve_backend()
    interpret = kernel_backend.interpret
    batch_size = knob("BENCH_BATCH", 1024, 16)
    bag = knob("BENCH_BAG", 200, 24)
    steps = knob("BENCH_AB_STEPS", 30, 4)  # batches per timed pass
    embed_size = knob("BENCH_EMBED", 100, 8)
    encode_size = knob("BENCH_ENCODE", 100, 16)
    repeats = max(int(os.environ.get("BENCH_AB_REPEATS", 3 if not interpret else 2)), 1)
    block_b = knob("BENCH_PALLAS_BLOCK_B", 8, 8)
    dma_depth = knob("BENCH_PALLAS_DMA_DEPTH", 2, 2)
    chunk_l = knob("BENCH_PALLAS_CHUNK_L", 128, 128)

    spec = SynthSpec(
        n_methods=max(batch_size * steps, 256),
        n_terminals=knob("BENCH_AB_TERMINALS", 360_631, 2_000),
        n_paths=knob("BENCH_AB_PATHS", 342_845, 2_000),
        n_labels=knob("BENCH_AB_LABELS", 8_000, 100),
        mean_contexts=float(knob("BENCH_AB_MEAN_CTX", 120, 12)),
        max_contexts=2 * bag,
        seed=0,
    )
    data = corpus_data_from_raw(generate_corpus_data(spec))

    def cfg(**kw) -> Code2VecConfig:
        return Code2VecConfig(
            terminal_count=spec.n_terminals + 2,
            path_count=spec.n_paths + 1,
            label_count=len(data.label_vocab),
            terminal_embed_size=embed_size,
            path_embed_size=embed_size,
            encode_size=encode_size,
            dropout_prob=0.0,  # eval forward
            dtype=jnp.float32,
            pallas_block_b=block_b,
            pallas_dma_depth=dma_depth,
            pallas_chunk_l=chunk_l,
            **kw,
        )

    # one f32 param set shared by every arm (the tree is impl-invariant)
    base_model = Code2Vec(cfg())
    rng = np.random.default_rng(0)
    epoch = build_method_epoch(data, np.arange(data.n_items), bag, rng)
    batches = list(iter_batches(epoch, batch_size, rng=None, pad_final=True))[:steps]
    first = batches[0]
    params = base_model.init(
        {"params": jax.random.PRNGKey(0)},
        first["starts"], first["paths"], first["ends"],
    )["params"]
    real_slots = sum(
        int((b["paths"][b["example_mask"].astype(bool)] != 0).sum())
        for b in batches
    )
    device_batches = [
        {k: jax.device_put(b[k]) for k in ("starts", "paths", "ends")}
        for b in batches
    ]

    # optional Autocomp pass over THIS run's shapes: populates/consults the
    # persisted schedule cache; the counters delta below is the proof of
    # how much search this invocation actually paid
    autotune_info = None
    if "--autotune" in sys.argv[1:]:
        cache = at.get_cache(os.environ.get("BENCH_AUTOTUNE_CACHE", "").strip() or None)
        before = at.counters_snapshot()
        keys = at.keys_for(
            batch_size, [bag], embed_size, embed_size, encode_size,
            ["f32", "int8"],
        )
        schedules = at.autotune(
            keys, cache=cache, dry="--dry" in sys.argv[1:],
            iters=knob("BENCH_AUTOTUNE_ITERS", 3, 1),
        )
        after = at.counters_snapshot()
        autotune_info = {
            "cache": cache.path,
            "dry": "--dry" in sys.argv[1:],
            "schedules": {k: s.to_dict() for k, s in schedules.items()},
            "counters_delta": {k: after[k] - before[k] for k in after},
        }

    quant = {
        dt: (
            quantize_table(params["terminal_embedding"]["embedding"], dt),
            quantize_table(params["path_embedding"]["embedding"], dt),
        )
        for dt in ("int8",)
    }

    arms: list[tuple[str, Code2VecConfig, tuple | None]] = [
        ("xla_f32", cfg(), None),
        ("pool_only_f32", cfg(use_pallas=True, pallas_impl="pool_only"), None),
        ("fused_f32", cfg(use_pallas=True, pallas_impl="fused"), None),
        (
            "pool_only_int8",
            cfg(use_pallas=True, pallas_impl="pool_only", table_dtype="int8"),
            quant["int8"],
        ),
        (
            "fused_int8",
            cfg(use_pallas=True, pallas_impl="fused", table_dtype="int8"),
            quant["int8"],
        ),
    ]
    if autotune_info is not None:
        arms.append(
            ("auto_f32", cfg(use_pallas=True, pallas_impl="auto"), None)
        )
    if kernel_backend.strategy != "pallas_tpu" and not interpret:
        # the compiled-vs-interpret comparison arms: same params, same
        # batches, same real-context work — only the lowering differs.
        # Skipped when every arm already runs the interpreter (the env
        # pinned it) or on real TPU (nothing interprets there).
        arms += [
            (
                "pool_only_f32_interp",
                cfg(use_pallas=True, pallas_impl="pool_only",
                    pallas_backend="interpret"),
                None,
            ),
            (
                "fused_f32_interp",
                cfg(use_pallas=True, pallas_impl="fused",
                    pallas_backend="interpret"),
                None,
            ),
        ]

    def make_forward(model_config: Code2VecConfig, quant_tables):
        model = Code2Vec(model_config)

        def fwd(params, batch):
            logits, cv, _ = model.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"], deterministic=True,
                quant_tables=quant_tables,
            )
            return jnp.argmax(logits, axis=-1), cv

        return jax.jit(fwd)

    fns = {name: make_forward(mc, qt) for name, mc, qt in arms}
    for name in fns:  # compile + warm, untimed
        jax.block_until_ready(fns[name](params, device_batches[0]))
    # every arm serves ONE static shape: any jit-cache growth during the
    # timed window is a silent recompile — the verdict the acceptance
    # demands ("zero post-warmup recompiles")
    detector = RecompileDetector()
    for name in fns:
        detector.track(name, fns[name])
    detector.check()

    def one_pass(fn) -> float:
        t0 = time.perf_counter()
        for b in device_batches:
            out = fn(params, b)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    best: dict[str, float] = {name: float("inf") for name, _, _ in arms}
    order = [name for name, _, _ in arms]
    for _ in range(repeats):
        # generalized ABBA: forward order then reversed — monotonic drift
        # (cache/frequency warm-up) cancels across the pair of sweeps
        for name in order + order[::-1]:
            best[name] = min(best[name], one_pass(fns[name]))

    post_warmup = detector.check()
    rates = {name: real_slots / best[name] for name in best}
    speedup = best["xla_f32"] / best["fused_f32"]

    detail = {
        "backend": backend,
        "mode": "kernel_ab",
        "strategy": kernel_backend.label,
        "interpret": interpret,
        "batch": batch_size,
        "bag": bag,
        "steps": len(device_batches),
        "embed": embed_size,
        "encode": encode_size,
        "pad_efficiency": round(
            real_slots / (len(device_batches) * batch_size * bag), 4
        ),
        "arms": {
            name: {
                "real_contexts_per_sec": round(rates[name], 1),
                "ms_per_pass": round(best[name] * 1e3, 3),
                "kernel": _kernel_provenance(mc),
            }
            for name, mc, _ in arms
        },
        "speedup_fused_vs_xla_f32": round(speedup, 4),
        "post_warmup_recompiles": post_warmup,
        "autotune": autotune_info,
        "memory": memory_snapshot(),
    }
    if "fused_f32_interp" in best:
        # equal real-context work, only the lowering differs: this is the
        # compiled-CPU-beats-interpreter number
        detail["speedup_compiled_vs_interpret"] = {
            "pool_only_f32": round(
                best["pool_only_f32_interp"] / best["pool_only_f32"], 4
            ),
            "fused_f32": round(
                best["fused_f32_interp"] / best["fused_f32"], 4
            ),
        }
    if interpret:
        detail["note"] = (
            "Pallas interpret mode (no TPU backend): rates characterize "
            "the interpreter, not the hardware — an honest record, not a "
            "hardware claim"
        )
    print(json.dumps({"detail": detail}), file=sys.stderr, flush=True)
    print(
        json.dumps(
            {
                "metric": "fused_kernel_real_contexts_per_sec",
                "value": round(rates["fused_f32"], 1),
                "unit": "contexts/sec",
                # in AB mode the baseline IS the same-spec XLA arm
                "vs_baseline": round(speedup, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


def _serve_bench() -> None:
    """``--serve``: open-loop load test of the online serving stack.

    Builds the real serving pieces — a :class:`ServingEngine` with its AOT
    executable ladder over a skewed (lognormal) width distribution, and
    the continuous micro-batcher in front of it — then drives them with an
    OPEN-LOOP request generator: arrivals follow a seeded exponential
    schedule at ``BENCH_SERVE_QPS`` regardless of completions (a closed
    loop would hide queueing collapse — the generator does not slow down
    because the server is struggling, exactly like real traffic).

    Reported: p50/p99/mean end-to-end latency plus the per-phase split
    (queue_wait / pad / device), measured QPS, REAL context throughput
    (sum of each request's true context count — the padded slots an
    executable processes are accounted separately as ``pad_efficiency``),
    and the zero-post-warmup-recompile assertion: the obs
    RecompileDetector tracks the engine's executable table across the
    whole mixed-width stream and the metric line carries its verdict.

    The fleet observability plane rides along (PR 15): one MID-LOAD
    ``/metrics`` scrape parsed back through the exposition parser lands
    in the detail block (the plane's provenance, like kernel/feed
    provenance), a p99-sampling flight recorder counts how many tail
    requests left full per-request timelines, and a rolling SLO
    error-budget window over the outcome stream puts ``slo_burn_rate`` /
    ``slo_budget_exhausted`` on the metric line next to the recompile
    verdict.

    ``--rolling-swap`` adds the hot-swap arm (serve/swap.py): mid-stream,
    a ``reload`` shadow-compiles a SECOND model version's full ladder on
    a background thread, golden-validates it, and atomically swaps the
    serving pointer while the open-loop load keeps arriving; after the
    stream a ``rollback`` swaps back. The run FAILS unless: zero failed
    requests across the swap, swap-window p99 bounded by
    ``BENCH_SWAP_P99_FACTOR`` (default 3x) of the steady-state p99, zero
    post-warmup recompiles on BOTH generations' engines, and the
    rolled-back version reproduces its pre-swap embeddings BITWISE on the
    very first request (nothing was rebuilt — the old executables stayed
    resident).
    """
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)

    from code2vec_tpu.data.pipeline import derive_bucket_ladder
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.obs.runtime import (
        FlightRecorder,
        RecompileDetector,
        RuntimeHealth,
        memory_snapshot,
        parse_prometheus_text,
        prometheus_text,
    )
    from code2vec_tpu.serve.batcher import MicroBatcher, ServeOverloaded
    from code2vec_tpu.serve.engine import ServingEngine
    from code2vec_tpu.serve.fleet.slo import SloBurnTracker
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def knob(name: str, device_default: int, cpu_default: int) -> int:
        return _recipe_knob(name, device_default, cpu_default, fell_back, backend)

    bag = knob("BENCH_BAG", 200, 32)
    embed_size = knob("BENCH_EMBED", 100, 16)
    encode_size = knob("BENCH_ENCODE", 100, 24)
    n_terminals = knob("BENCH_SERVE_TERMINALS", 360_631, 2_000)
    n_paths = knob("BENCH_SERVE_PATHS", 342_845, 2_000)
    n_labels = knob("BENCH_SERVE_LABELS", 8_000, 100)
    n_requests = knob("BENCH_SERVE_REQUESTS", 2_000, 300)
    target_qps = _env_float("BENCH_SERVE_QPS", 0.0) or (
        150.0 if fell_back or backend == "cpu" else 500.0
    )
    deadline_ms = _env_float("BENCH_SERVE_DEADLINE_MS", 2.0)
    batch_sizes = tuple(
        int(t)
        for t in os.environ.get("BENCH_SERVE_BATCH_SIZES", "1,8").split(",")
        if t.strip()
    )
    # seeded Zipf request mix (the router result-cache's acceptance
    # traffic): BENCH_SERVE_ZIPF=skew,distinct draws every request from a
    # fixed population of `distinct` bags with Zipf(skew) popularity, and
    # every resend PERMUTES its rows — so the cache's order-invariant
    # canonicalization, not byte equality, is what makes repeats hit.
    # --cache-ab turns on the cache-on/off ABBA arm and implies the
    # default mix (1.1 over 64 bags) when the env knob is unset.
    cache_ab = "--cache-ab" in sys.argv[1:]
    zipf_spec = os.environ.get("BENCH_SERVE_ZIPF", "")
    zipf = None
    if zipf_spec or cache_ab:
        parts = (zipf_spec or "1.1,64").split(",")
        zipf = (
            float(parts[0]),
            int(parts[1]) if len(parts) > 1 and parts[1].strip() else 64,
        )

    config = TrainConfig(batch_size=max(batch_sizes), max_path_length=bag)
    model_config = Code2VecConfig(
        terminal_count=n_terminals + 2,
        path_count=n_paths + 1,
        label_count=n_labels,
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,
        dropout_prob=0.0,
    )
    example = {
        "starts": np.zeros((1, bag), np.int32),
        "paths": np.zeros((1, bag), np.int32),
        "ends": np.zeros((1, bag), np.int32),
        "labels": np.zeros(1, np.int32),
        "example_mask": np.ones(1, np.float32),
    }
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(0), example
    )

    # the request mix: heavy-tailed real context counts (data/synth.py
    # models corpora as lognormal) — the mixed-width stream the recompile
    # assertion runs across
    rng = np.random.default_rng(0)
    counts = np.clip(
        np.rint(rng.lognormal(np.log(bag / 6.0), 0.6, n_requests)), 1, bag
    ).astype(np.int64)
    distinct_counts = bag_ids = None
    if zipf is not None:
        skew, distinct = zipf
        distinct_counts = np.clip(
            np.rint(rng.lognormal(np.log(bag / 6.0), 0.6, distinct)), 1, bag
        ).astype(np.int64)
        weights = 1.0 / np.arange(1.0, distinct + 1) ** skew
        weights /= weights.sum()
        bag_ids = rng.choice(distinct, size=n_requests, p=weights)
        # the ladder sees the TRAFFIC-weighted width distribution, not
        # the population's: hot bags dominate bucket occupancy
        counts = distinct_counts[bag_ids]
    ladder = derive_bucket_ladder(counts, bag)

    health = RuntimeHealth()
    engine = ServingEngine(
        state,
        max_width=bag,
        model_dims=(embed_size, embed_size, encode_size),
        ladder=ladder,
        batch_sizes=batch_sizes,
        health=health,
    )
    t0 = time.perf_counter()
    provenance = engine.prepare()
    startup_compile_s = time.perf_counter() - t0
    detector = RecompileDetector(health=health)
    detector.track(
        "serve_executables", engine, expected_compiles=engine._cache_size()
    )

    def request(i: int) -> np.ndarray:
        n = int(counts[i])
        return np.stack(
            [
                rng.integers(1, n_terminals, n),
                rng.integers(1, n_paths, n),
                rng.integers(1, n_terminals, n),
            ],
            axis=1,
        ).astype(np.int32)

    if zipf is not None:
        def make_bag(n: int) -> np.ndarray:
            return np.stack(
                [
                    rng.integers(1, n_terminals, n),
                    rng.integers(1, n_paths, n),
                    rng.integers(1, n_terminals, n),
                ],
                axis=1,
            ).astype(np.int32)

        bags = [make_bag(int(c)) for c in distinct_counts]
        # every resend is a fresh row permutation of its bag: byte-level
        # dedup would miss, canonical multiset digests hit
        requests = [
            bags[b][rng.permutation(len(bags[b]))] for b in bag_ids
        ]
    else:
        requests = [request(i) for i in range(n_requests)]
    # seeded exponential inter-arrival gaps: a Poisson process at the
    # target rate, fixed before the clock starts (open loop)
    gaps = rng.exponential(1.0 / target_qps, n_requests)
    arrivals = np.cumsum(gaps)

    # the observability plane rides the load run like it rides production:
    # a p99-sampling flight recorder behind the batcher, and a rolling
    # SLO error-budget window over the request outcomes — both land in
    # the detail block so bench JSONs carry the plane's provenance the
    # way they carry kernel/feed provenance
    flight = FlightRecorder(health=health)
    burn = SloBurnTracker(["serve"], health=health)
    batcher = MicroBatcher(
        engine, deadline_ms=deadline_ms, max_pending=4096, health=health,
        flight=flight,
    )

    rolling_swap = "--rolling-swap" in sys.argv[1:]
    controller = golden_request = ref_v0 = None
    swap_at = None
    if rolling_swap:
        from code2vec_tpu.serve.swap import (
            Generation,
            GoldenSet,
            SwapController,
        )

        def build_generation(target):
            # the "new checkpoint": same architecture, different weights —
            # compiled + validated entirely on the swap thread while the
            # active generation keeps serving
            seed = 1 if target == "v1" else 0
            new_state = create_train_state(
                config, model_config, jax.random.PRNGKey(seed), example
            )
            shadow = ServingEngine(
                new_state,
                max_width=bag,
                model_dims=(embed_size, embed_size, encode_size),
                ladder=ladder,
                batch_sizes=batch_sizes,
                health=health,
                version=str(target),
            )
            shadow.prepare()
            return Generation(
                version=str(target),
                engine=shadow,
                batcher=MicroBatcher(
                    shadow, deadline_ms=deadline_ms, max_pending=4096,
                    health=health, flight=flight,
                ),
            )

        controller = SwapController(
            Generation(version="v0", engine=engine, batcher=batcher),
            build=build_generation,
            golden=GoldenSet(n_terminals=n_terminals, n_paths=n_paths),
            health=health,
        )
        swap_at = max(1, int(n_requests * 0.4))
        # the rollback contract's witness: one fixed request, served
        # before the swap so its v0 embedding is on record
        golden_request = requests[0]
        ref_v0 = batcher.submit(golden_request).result()
        if cache_ab:
            # the router result-cache's version lifecycle, mirrored here
            # against the real swap machinery: warm an entry under v0,
            # prove commit invalidates (retaining it) and rollback
            # revalidates it bitwise with zero device calls
            from code2vec_tpu.serve.fleet.cache import (
                ResultCache,
                canonical_bag_digest,
            )

            lifecycle_cache = ResultCache(8 * 2**20, version="v0")
            golden_key = ("v0", canonical_bag_digest(golden_request))
            lifecycle_cache.begin(golden_key)
            lifecycle_cache.fill(
                golden_key, ref_v0,
                nbytes=int(ref_v0.code_vector.nbytes + ref_v0.logits.nbytes),
            )
        else:
            lifecycle_cache = None

    futures = []
    submit_times: list[float] = []
    done_times: dict = {}
    rejected = 0
    swap_started_t = swap_committed_t = None
    metrics_scrape = None
    scrape_at = max(1, n_requests // 2)
    t_start = time.perf_counter()
    for i, arr in enumerate(requests):
        delay = arrivals[i] - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)
        if i == scrape_at:
            # one MID-LOAD /metrics scrape, parsed back through the same
            # exposition parser a monitoring stack would use — recorded
            # in the detail block as the plane's provenance (and proof
            # the scrape is a lock-light snapshot: it runs inline on the
            # submission thread without perturbing the open loop)
            t_scrape = time.perf_counter()
            parsed = parse_prometheus_text(
                prometheus_text([({}, health.snapshot())])
            )
            types = parsed.pop("# types")
            metrics_scrape = {
                "at_request": i,
                "scrape_ms": round(
                    (time.perf_counter() - t_scrape) * 1e3, 3
                ),
                "series": len(types),
                "samples": {
                    name: rows[0]["value"]
                    for name, rows in parsed.items()
                    if not rows[0]["labels"]
                },
            }
        if rolling_swap and i == swap_at:
            swap_started_t = time.perf_counter()
            controller.reload("v1", wait=False)
        if (
            swap_started_t is not None
            and swap_committed_t is None
            and controller.state == "idle"
        ):
            swap_committed_t = time.perf_counter()
        try:
            live = controller.active.batcher if rolling_swap else batcher
            future = live.submit(arr)
        except ServeOverloaded:
            rejected += 1
            continue
        submit_times.append(time.perf_counter())
        future.add_done_callback(
            lambda f: done_times.__setitem__(id(f), time.perf_counter())
        )
        futures.append(future)
    failed = []
    results = []
    for future in futures:
        try:
            results.append(future.result())
            burn.record("serve", good=True)
        except Exception as exc:  # noqa: BLE001 - counted, then reported
            failed.append(f"{type(exc).__name__}: {exc}")
            burn.record("serve", good=False)
    for _ in range(rejected):
        burn.record("serve", good=False)
    t_wall = time.perf_counter() - t_start
    if failed and not rolling_swap:
        # same contract as the old gather, which re-raised here: a broken
        # serving path must die BEFORE any metric line reaches stdout
        raise RuntimeError(
            f"{len(failed)} request(s) failed during the load run "
            f"(first: {failed[:3]})"
        )

    cache_detail = None

    def cache_pass(use_cache: bool):
        """One open-loop pass over the Zipf stream through the (always
        v0) batcher, optionally fronted by the result cache — the same
        admission protocol the fleet router runs: hit resolves inline,
        join rides the leader's future, lead submits and fills."""
        from code2vec_tpu.serve.fleet.cache import (
            ResultCache,
            canonical_bag_digest,
        )

        cache = ResultCache(64 * 2**20, version="v0") if use_cache else None
        hits = []  # (index, ServeResult, latency_ms)
        pend = []  # (index, "miss"|"join", future, t_submit)
        done_at: dict = {}
        t0 = time.perf_counter()
        for i, arr in enumerate(requests):
            delay = arrivals[i] - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            ts = time.perf_counter()
            if cache is None:
                fut = batcher.submit(arr)
                fut.add_done_callback(
                    lambda f, i=i: done_at.__setitem__(
                        i, time.perf_counter()
                    )
                )
                pend.append((i, "miss", fut, ts))
                continue
            key = ("v0", canonical_bag_digest(arr))
            state, held = cache.begin(key)
            if state == "hit":
                hits.append((i, held, (time.perf_counter() - ts) * 1e3))
                continue
            if state == "join":
                held.add_done_callback(
                    lambda f, i=i: done_at.__setitem__(
                        i, time.perf_counter()
                    )
                )
                pend.append((i, "join", held, ts))
                continue
            fut = batcher.submit(arr)

            def on_done(f, i=i, cache=cache, key=key):
                done_at[i] = time.perf_counter()
                if f.exception() is None:
                    r = f.result()
                    cache.fill(
                        key, r,
                        nbytes=int(
                            r.code_vector.nbytes + r.logits.nbytes
                        ),
                    )
                else:  # pragma: no cover - load run already validated
                    cache.abandon(key, None)

            fut.add_done_callback(on_done)
            pend.append((i, "miss", fut, ts))
        values = {i: fut.result() for i, _, fut, _ in pend}
        kinds = {i: kind for i, kind, _, _ in pend}
        kinds.update({i: "hit" for i, _, _ in hits})
        t_pass = time.perf_counter() - t0
        # one device call per miss GROUP: each member of a coalesced
        # device batch carries an equal 1/coalesced share
        device_calls = sum(
            1.0 / values[i].coalesced
            for i, kind, _, _ in pend
            if kind == "miss"
        )
        e2e_hit = [ms for _, _, ms in hits]
        e2e_miss = [
            (done_at[i] - ts) * 1e3 for i, _, _, ts in pend if i in done_at
        ]
        vectors = {i: v.code_vector for i, v, _ in hits}
        vectors.update({i: v.code_vector for i, v in values.items()})
        arm = {
            "cache": use_cache,
            "qps": round(n_requests / t_pass, 2) if t_pass > 0 else None,
            "hit_rate": round(len(hits) / n_requests, 4),
            "coalesced": (
                cache.stats()["coalesced"] if cache is not None else 0
            ),
            "device_calls": round(device_calls, 2),
            "device_calls_per_request": round(
                device_calls / n_requests, 4
            ),
            "p50_hit_ms": (
                round(float(np.percentile(e2e_hit, 50)), 3)
                if e2e_hit else None
            ),
            "p50_miss_ms": (
                round(float(np.percentile(e2e_miss, 50)), 3)
                if e2e_miss else None
            ),
        }
        return arm, vectors, kinds

    def run_cache_ab() -> dict:
        """Cache on/off over the SAME seeded Zipf stream, ABBA order (the
        kernel-bench discipline: interleaving cancels thermal/allocator
        drift), best-of per arm; responses must be bitwise-identical
        cached vs uncached."""
        passes = [cache_pass(on) for on in (True, False, False, True)]
        on_arms = [a for a, _, _ in (passes[0], passes[3])]
        off_arms = [a for a, _, _ in (passes[1], passes[2])]
        on_best = max(on_arms, key=lambda a: a["qps"] or 0.0)
        off_best = max(off_arms, key=lambda a: a["qps"] or 0.0)
        # bitwise contract, per request of the cache-on arm against the
        # uncached arm: a MISS computed fresh must match the uncached
        # result for the same byte-identical array; a HIT/JOIN returns
        # the exact payload of an earlier computation of the SAME
        # canonical bag (a different row permutation — float pooling is
        # not order-bitwise-stable, so the match is against the uncached
        # arm's result for that bag's original submission, not index i's)
        on_vecs, on_kinds = passes[0][1], passes[0][2]
        off_vecs = passes[1][1]
        by_bag_off: dict = {}
        for j in range(n_requests):
            by_bag_off.setdefault(int(bag_ids[j]), []).append(off_vecs[j])
        bitwise = True
        for i in range(n_requests):
            if on_kinds.get(i) == "miss":
                ok = np.array_equal(on_vecs[i], off_vecs[i])
            else:
                ok = any(
                    np.array_equal(on_vecs[i], v)
                    for v in by_bag_off[int(bag_ids[i])]
                )
            if not ok:
                bitwise = False
                break
        return {
            "zipf": {"skew": zipf[0], "distinct_bags": zipf[1]},
            "order": "ABBA",
            "cache_on": on_best,
            "cache_off": off_best,
            "bitwise_identical": bitwise,
        }

    if not rolling_swap:
        if cache_ab:
            cache_detail = run_cache_ab()
        batcher.close()

    completed = len(results)
    real_contexts = sum(r.n_contexts for r in results)
    # each group member carries an equal share of its executable's padded
    # B x L slots, so this sums every device call's slots exactly once
    padded_slots = sum(r.batch * r.width / r.coalesced for r in results)
    new_compiles = detector.check()
    lat = {
        name: health.latency(key).summary()
        for name, key in (
            ("e2e", "serve.e2e_ms"),
            ("queue_wait", "serve.queue_wait_ms"),
            ("pad", "serve.pad_ms"),
            ("device", "serve.device_ms"),
        )
    }
    qps = completed / t_wall if t_wall > 0 else 0.0

    swap_detail = None
    p99_factor = _env_float("BENCH_SWAP_P99_FACTOR", 3.0)
    if rolling_swap:
        status = controller.wait(600)
        if swap_committed_t is None and controller.state == "idle":
            swap_committed_t = time.perf_counter()
        last = status["last_swap"] or {}
        # window the per-request e2e samples by SUBMISSION time: steady =
        # before the reload, swap = between reload start and commit (the
        # interval where the shadow build competes for the host)
        e2e = [
            (t_submit, (done_times[id(future)] - t_submit) * 1e3)
            for t_submit, future in zip(submit_times, futures)
            if id(future) in done_times
        ]
        steady = [ms for t, ms in e2e if t < swap_started_t]
        swap_end = swap_committed_t or (t_start + t_wall)
        swap_window = [ms for t, ms in e2e if swap_started_t <= t <= swap_end]
        p99_steady = float(np.percentile(steady, 99)) if steady else None
        p99_swap = (
            float(np.percentile(swap_window, 99)) if swap_window else None
        )
        p99_ratio = (
            round(p99_swap / p99_steady, 3)
            if p99_steady and p99_swap is not None
            else None
        )
        # rollback: v1 serves (different weights), then one pointer swap
        # back and the very next request must be v0-bitwise — the old
        # generation's executables and tables were never torn down. Only
        # reachable after a COMMIT: a failed/stuck swap has no previous
        # generation to roll back to, and must reach the verdict below
        # (not die here on the rollback's own ValueError).
        rollback_bitwise = versions_differ = False
        shadow_post_warmup = 0
        cache_lifecycle = None
        if last.get("outcome") == "committed":
            v1_result = controller.active.batcher.submit(
                golden_request
            ).result()
            if lifecycle_cache is not None:
                # commit: the active version key flips forward — the v0
                # entry goes invisible (a resend MISSES and recomputes on
                # v1) but stays resident for the rollback below
                from code2vec_tpu.serve.fleet.cache import (
                    canonical_bag_digest,
                )

                gk = canonical_bag_digest(golden_request)
                lifecycle_cache.begin_swap()
                lifecycle_cache.end_swap(version="v1")
                state_after_commit, _ = lifecycle_cache.begin(("v1", gk))
                lifecycle_cache.abandon(("v1", gk), None)
                cache_lifecycle = {
                    "invalidated_on_commit": state_after_commit == "lead",
                    "v0_entries_retained": (
                        lifecycle_cache.stats()["versions"].get("v0", 0)
                    ),
                }
            controller.rollback()
            restored = controller.active.batcher.submit(
                golden_request
            ).result()
            if lifecycle_cache is not None:
                # rollback: the version key flips back and the retained
                # v0 entry is a HIT again — bitwise-equal to what the
                # restored generation recomputes, with zero device calls
                # on the hit path
                lifecycle_cache.set_version("v0")
                state_back, held = lifecycle_cache.begin(("v0", gk))
                cache_lifecycle["revalidated_bitwise"] = bool(
                    state_back == "hit"
                    and np.array_equal(
                        held.code_vector, restored.code_vector
                    )
                    and np.array_equal(held.logits, restored.logits)
                )
                cache_lifecycle["device_calls_on_revalidate"] = 0
                if state_back == "lead":  # pragma: no cover - fail path
                    lifecycle_cache.abandon(("v0", gk), None)
            rollback_bitwise = bool(
                np.array_equal(ref_v0.code_vector, restored.code_vector)
                and np.array_equal(ref_v0.logits, restored.logits)
            )
            versions_differ = not np.array_equal(
                ref_v0.code_vector, v1_result.code_vector
            )
            # v1, post-rollback
            shadow_post_warmup = controller.previous.engine.post_warmup_compiles
        swap_detail = {
            "outcome": last.get("outcome"),
            "swap_at_request": swap_at,
            "build_ms": last.get("build_ms"),
            "validate_ms": last.get("validate_ms"),
            "golden_requests": last.get("golden_requests"),
            "swap_window_s": (
                round(swap_end - swap_started_t, 3)
                if swap_started_t is not None
                else None
            ),
            "requests_in_swap_window": len(swap_window),
            "p99_steady_ms": round(p99_steady, 3) if p99_steady else None,
            "p99_swap_ms": round(p99_swap, 3) if p99_swap else None,
            "p99_ratio": p99_ratio,
            "p99_factor": p99_factor,
            "failed_requests": len(failed),
            "versions_differ": versions_differ,
            "rollback_bitwise": rollback_bitwise,
            "post_warmup_recompiles_shadow": shadow_post_warmup,
            "cache": cache_lifecycle,
        }
        if cache_ab:
            # the A/B arm runs on the (rolled-back, still-resident) v0
            # batcher AFTER the swap machinery settles, so both arms
            # measure one stable generation
            cache_detail = run_cache_ab()
        controller.close()

    detail = {
        "backend": backend,
        "mode": "serve",
        "bag": bag,
        "embed": embed_size,
        "encode": encode_size,
        "ladder": list(ladder),
        "batch_sizes": list(batch_sizes),
        "deadline_ms": deadline_ms,
        "target_qps": target_qps,
        "requests": n_requests,
        "completed": completed,
        "rejected": rejected,
        "qps": round(qps, 2),
        "latency_ms": lat,
        "real_contexts_per_sec": round(real_contexts / t_wall, 1),
        "pad_efficiency": round(real_contexts / padded_slots, 4)
        if padded_slots
        else None,
        "coalesce_mean": round(
            sum(r.coalesced for r in results) / completed, 3
        )
        if completed
        else None,
        "executables": engine._cache_size(),
        "startup_compile_s": round(startup_compile_s, 3),
        "schedule_provenance": provenance,
        "post_warmup_recompiles": engine.post_warmup_compiles,
        "detector_new_compiles": new_compiles,
        "failed_requests": len(failed),
        "counters": health.snapshot()["counters"],
        # the observability plane's provenance: the mid-load scrape
        # (parsed exposition, not raw text), the flight recorder's tail
        # captures, and the rolling SLO error-budget verdict
        "metrics_scrape": metrics_scrape,
        "flight": {"recorded": flight.count, "seen": flight.seen},
        "slo_burn": burn.snapshot()["serve"],
        # device-time/MFU accounting: static costs x accumulated fenced
        # device spans, the block tools/perf_report.py ratios against its
        # committed baseline
        "perf": engine.perf_summary(),
        "memory": memory_snapshot(),
        "zipf": (
            {"skew": zipf[0], "distinct_bags": zipf[1]}
            if zipf is not None else None
        ),
    }
    if cache_detail is not None:
        detail["cache_ab"] = cache_detail
    if swap_detail is not None:
        detail["rolling_swap"] = swap_detail
    print(json.dumps({"detail": detail}), file=sys.stderr, flush=True)
    metric = {
        "metric": "serve_requests_per_sec",
        "value": round(qps, 2),
        "unit": "req/sec",
        # first serving benchmark: no prior round to compare to;
        # the acceptance gate is the latency block + the recompile
        # verdict below, not a speedup ratio
        "vs_baseline": 1.0,
        "p50_ms": lat["e2e"]["p50_ms"] if lat["e2e"] else None,
        "p99_ms": lat["e2e"]["p99_ms"] if lat["e2e"] else None,
        "post_warmup_recompiles": engine.post_warmup_compiles,
        # the SLO burn verdict rides the metric line next to the
        # recompile verdict: burn >= 1 with the window's budget consumed
        # means the run would be paging a human in production
        "slo_burn_rate": detail["slo_burn"]["burn_rate"],
        "slo_budget_exhausted": detail["slo_burn"]["exhausted"],
        "flight_recorded": flight.count,
        "mfu": (detail["perf"] or {}).get("mfu"),
        "backend": backend,
    }
    if cache_detail is not None:
        metric["cache_ab"] = {
            "hit_rate": cache_detail["cache_on"]["hit_rate"],
            "device_calls_per_request": (
                cache_detail["cache_on"]["device_calls_per_request"]
            ),
            "device_calls_per_request_uncached": (
                cache_detail["cache_off"]["device_calls_per_request"]
            ),
            "p50_hit_ms": cache_detail["cache_on"]["p50_hit_ms"],
            "p50_miss_ms": cache_detail["cache_on"]["p50_miss_ms"],
            "bitwise_identical": cache_detail["bitwise_identical"],
        }
    if swap_detail is not None:
        metric["rolling_swap"] = {
            key: swap_detail[key]
            for key in (
                "outcome", "p99_steady_ms", "p99_swap_ms", "p99_ratio",
                "failed_requests", "rollback_bitwise",
            )
        }
    print(json.dumps(metric), flush=True)
    total_post_warmup = engine.post_warmup_compiles + (
        swap_detail["post_warmup_recompiles_shadow"] if swap_detail else 0
    )
    if total_post_warmup or new_compiles:
        raise RuntimeError(
            f"serving hot path recompiled post-warmup "
            f"({total_post_warmup} engines / {new_compiles} "
            "detector) — the AOT ladder failed to cover the stream"
        )
    if rolling_swap:
        problems = []
        if failed:
            problems.append(
                f"{len(failed)} request(s) failed across the swap "
                f"(first: {failed[:2]})"
            )
        if swap_detail["outcome"] != "committed":
            problems.append(f"swap outcome {swap_detail['outcome']!r}")
        if (
            swap_detail["p99_ratio"] is not None
            and swap_detail["p99_ratio"] > p99_factor
        ):
            problems.append(
                f"swap-window p99 {swap_detail['p99_swap_ms']} ms is "
                f"{swap_detail['p99_ratio']}x steady-state "
                f"{swap_detail['p99_steady_ms']} ms (> {p99_factor}x)"
            )
        if swap_detail["outcome"] == "committed":
            # only meaningful after a commit — an uncommitted swap is
            # already reported above, without piling on dependent checks
            if not swap_detail["versions_differ"]:
                problems.append(
                    "v1 served identical outputs to v0 — the swap did not "
                    "actually change the serving weights"
                )
            if not swap_detail["rollback_bitwise"]:
                problems.append(
                    "rollback did NOT restore v0's bitwise-identical "
                    "outputs"
                )
            lifecycle = swap_detail.get("cache")
            if lifecycle is not None:
                if not lifecycle["invalidated_on_commit"]:
                    problems.append(
                        "cache served a stale v0 entry after the commit "
                        "flipped the active version"
                    )
                if not lifecycle["revalidated_bitwise"]:
                    problems.append(
                        "rollback did not revalidate the retained v0 "
                        "cache entry bitwise"
                    )
        if problems:
            raise RuntimeError(
                "--rolling-swap verdict failed: " + "; ".join(problems)
            )
    if cache_detail is not None:
        problems = []
        on, off = cache_detail["cache_on"], cache_detail["cache_off"]
        if on["device_calls_per_request"] >= 0.5:
            problems.append(
                f"device-call rate did not decouple from QPS: "
                f"{on['device_calls_per_request']} calls/request with the "
                f"cache on (uncached: "
                f"{off['device_calls_per_request']}) >= 0.5"
            )
        if (
            on["p50_hit_ms"] is None
            or on["p50_miss_ms"] is None
            or on["p50_hit_ms"] >= on["p50_miss_ms"]
        ):
            problems.append(
                f"hit-path p50 ({on['p50_hit_ms']} ms) is not below "
                f"miss-path p50 ({on['p50_miss_ms']} ms)"
            )
        if not cache_detail["bitwise_identical"]:
            problems.append(
                "cached responses are not bitwise-identical to uncached"
            )
        if problems:
            raise RuntimeError(
                "--cache-ab verdict failed: " + "; ".join(problems)
            )


def main() -> None:
    jax, backend, fell_back = _init_backend()
    _bench_tracer(jax)
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import (
        build_method_epoch,
        iter_batches,
        truncated_fraction_of_counts as _truncated_fraction_of_counts,
    )
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.device_epoch import EpochRunner, stage_method_corpus
    from code2vec_tpu.train.step import create_train_state

    # persistent compilation cache: repeat runs (and retries after tunnel
    # resets) skip the ~30s XLA compile
    jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    batch_size = int(os.environ.get("BENCH_BATCH", 1024))
    bag = int(os.environ.get("BENCH_BAG", 200))
    steps = int(os.environ.get("BENCH_STEPS", 60))
    if fell_back and "BENCH_STEPS" not in os.environ:
        # emergency CPU fallback: the full recipe takes seconds/step on one
        # core — fewer steps still yields a (cpu-labeled) number inside the
        # driver's window instead of a timeout with zero data
        steps = 8
    warmup = int(os.environ.get("BENCH_WARMUP_CHUNKS", 5))
    data_axis = int(os.environ.get("BENCH_DATA_AXIS", 1))
    model_axis = int(os.environ.get("BENCH_MODEL_AXIS", 1))
    # ctx axis: shards the bag dim L (long-bag regime, SURVEY §5.7); the
    # batch sharding constraint routes pooling through the streaming-softmax
    # collectives (parallel/context.py semantics, GSPMD-inserted)
    ctx_axis = int(os.environ.get("BENCH_CTX_AXIS", 1))
    # dims: default is the reference top11 recipe; BENCH_EMBED/BENCH_ENCODE
    # override for e.g. the wide-model config (BASELINE config 4: 512/512)
    embed_size = int(os.environ.get("BENCH_EMBED", 100))
    encode_size = int(os.environ.get("BENCH_ENCODE", 100))
    # kernel knobs as first-class recipe knobs (shared parsing/defaults
    # with every A/B mode); BENCH_PALLAS_IMPL picks the kernel variant
    # (--kernel-ab measures them against each other; ops/autotune.py
    # searches them per shape)
    use_pallas = _recipe_flag("BENCH_USE_PALLAS", False, False, fell_back, backend)
    pallas_block_b = _recipe_knob("BENCH_PALLAS_BLOCK_B", 8, 8, fell_back, backend)
    pallas_impl = (
        os.environ.get("BENCH_PALLAS_IMPL", "pool_only").strip().lower()
        or "pool_only"
    )
    pallas_dma_depth = _recipe_knob("BENCH_PALLAS_DMA_DEPTH", 2, 2, fell_back, backend)
    pallas_chunk_l = _recipe_knob("BENCH_PALLAS_CHUNK_L", 128, 128, fell_back, backend)

    # top11-scale synthetic corpus, shrunk in method count (the throughput
    # metric depends on vocab/model/batch shape, not corpus length); vocab
    # sizes are the real top11 ones
    spec = SynthSpec(
        n_methods=max(batch_size * 8, 8192),
        n_terminals=360_631,
        n_paths=342_845,
        n_labels=8_000,
        mean_contexts=120.0,
        max_contexts=400,
        seed=0,
    )
    raw = generate_corpus_data(spec)
    data = corpus_data_from_raw(raw)

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,  # the reference top11 recipe (README.md:34)
        dropout_prob=0.25,
        # f32 measured faster than bf16 at the top11 recipe (dims 100) —
        # the step is scatter/HBM-bound, and bf16 only adds casts around
        # f32 accumulations (tools/run_tpu_ablation.py, docs/ARCHITECTURE.md)
        dtype=jnp.bfloat16
        if os.environ.get("BENCH_DTYPE", "float32").strip().lower()
        in ("bfloat16", "bf16")
        else jnp.float32,
        embed_grad=os.environ.get("BENCH_EMBED_GRAD", "dense"),
        # "xla" | "streaming": attention-pool lowering (same math; the
        # streaming exp/sum chain measured faster in isolation on v5e —
        # ablation has the end-to-end A/B row)
        # unknown values raise at model trace time (fail-loud dispatch)
        attn_impl=os.environ.get("BENCH_ATTN_IMPL", "xla").strip().lower() or "xla",
        encoder_impl=os.environ.get("BENCH_ENCODER_IMPL", "concat").strip().lower()
        or "concat",
        use_pallas=use_pallas,
        pallas_block_b=pallas_block_b,
        pallas_impl=pallas_impl,
        pallas_dma_depth=pallas_dma_depth,
        pallas_chunk_l=pallas_chunk_l,
        # pad the tables so a model axis actually shards them instead of
        # silently replicating (parallel.shardings divisibility rule)
        vocab_pad_multiple=max(model_axis, 1),
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        # unsafe_rbg: ~2 ms/step cheaper dropout bits (ablation winner);
        # fine for a throughput benchmark, selectable for training runs
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
        # bf16 first moment measured faster on TPU (24.6/25.1 vs 25.6/25.6
        # ms, x2 repeats — tools/run_tpu_ablation.py --r4): trims ~280 MB
        # of the per-step moment RMW at top11 scale. Training keeps f32 as
        # ITS default (torch-parity configuration pinned by the train-step
        # differential test); the bench takes the measured winner. On the
        # CPU fallback the flip is a wash (f32 95.3k/107.1k vs bf16
        # 99.7k/104.7k ctx/s, x2 each — docs/ROUND5.md), so the recipe is
        # NOT backend-split; r04's 13% CPU dip was run-to-run noise.
        # Unrecognized values raise rather than silently landing on either
        # arm — a typo'd opt-out must not get recorded as an f32 stamp.
        adam_mu_dtype=_mu_dtype_from_env(),
        # "dense" | "lazy": embedding-table optimizer (train/table_opt.py).
        # Lazy updates only the touched rows (SparseAdam semantics) —
        # staged for TPU measurement via run_tpu_ablation --r5; unknown
        # values raise in create_train_state (fail-loud dispatch)
        table_update=os.environ.get("BENCH_TABLE_UPDATE", "dense")
        .strip().lower() or "dense",
    )

    rng = np.random.default_rng(0)
    epoch = build_method_epoch(data, np.arange(batch_size), bag, rng)
    example = next(iter_batches(epoch, batch_size, rng=rng, pad_final=False))
    state = create_train_state(config, model_config, jax.random.PRNGKey(0), example)
    class_weights = jnp.ones(model_config.label_count, jnp.float32)

    # the measured path is the flagship one: corpus staged to device memory
    # once, per-epoch context sampling on device, scanned chunks of batches
    # per dispatch (train/device_epoch.py). BENCH_DATA_AXIS/BENCH_MODEL_AXIS/
    # BENCH_CTX_AXIS > 1 runs the same path SPMD over a mesh (corpus
    # replicated, batches sharded) — the multi-chip scale-out configuration.
    chunk = int(os.environ.get("BENCH_CHUNK", 16))
    if fell_back:
        if "BENCH_CHUNK" not in os.environ:
            chunk = 4
        if "BENCH_WARMUP_CHUNKS" not in os.environ:
            warmup = 1
    mesh = None
    corpus_placement = None
    if data_axis * model_axis * ctx_axis > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state

        mesh = make_mesh(data=data_axis, model=model_axis, ctx=ctx_axis)
        state = shard_state(mesh, state)
        corpus_placement = NamedSharding(mesh, PartitionSpec())

    # BENCH_SHARD_STAGED=1 (+ BENCH_DATA_AXIS>1): corpus partitioned over
    # the data axis (per-device HBM ~1/data_axis) with shard_map sampling
    shard_staged = mesh is not None and os.environ.get(
        "BENCH_SHARD_STAGED", "0"
    ).strip().lower() in ("1", "true", "yes", "on")
    sample_prefetch = os.environ.get(
        "BENCH_SAMPLE_PREFETCH", "0"
    ).strip().lower() in ("1", "true", "yes", "on")
    # real-context accounting: the device sampler fills min(count, bag)
    # slots per sampled row — everything else in the [B, bag] batch is PAD.
    # Summed over the measured rows this is the work actually done, vs the
    # B x bag x steps padded-slot credit the headline used to claim.
    item_counts = np.diff(data.row_splits)
    counts_capped = np.minimum(item_counts, bag).astype(np.int64)
    if shard_staged:
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            partition_items_balanced,
            stage_method_corpus_sharded,
        )

        runner = ShardedEpochRunner(
            model_config, class_weights, batch_size, bag, chunk, mesh=mesh,
            sample_prefetch=sample_prefetch,
            table_update=config.table_update,
        )
        staged = stage_method_corpus_sharded(
            data, np.arange(data.n_items), rng, mesh
        )
        run_chunk = runner._train_chunk(chunk)
        span = chunk * runner.per_shard
        valid = np.ones((runner.n_shards, span), np.float32)
        # the same deterministic snake partition shard_staged used, so a
        # shard-local row index maps back to its item's context count
        groups = partition_items_balanced(item_counts, runner.n_shards)
        counts_mat = np.zeros((runner.n_shards, staged.items_cap), np.int64)
        for s, g in enumerate(groups):
            counts_mat[s, : len(g)] = counts_capped[g]
        shard_ids = np.arange(runner.n_shards)[:, None]

        def real_of(rows) -> int:
            return int(counts_mat[shard_ids, rows].sum())

        def make_rows():
            # max(counts, 1): an empty shard (n_items < data_axis) still
            # needs a valid row bound; its rows are all-PAD row 0
            return rng.integers(
                0, np.maximum(staged.shard_counts[:, None], 1),
                (runner.n_shards, span),
            ).astype(np.int32)

        def run(state, key, rows):
            key, sub = jax.random.split(key)
            state, loss = run_chunk(
                state, staged.contexts, staged.row_splits, staged.labels,
                rows, valid, sub,
            )
            return state, loss, key
    else:
        runner = EpochRunner(
            model_config, class_weights, batch_size, bag, chunk, mesh=mesh,
            # double-buffered on-device sampling (same batches, same
            # order; see train/device_epoch.py) — measured via the ablation
            sample_prefetch=sample_prefetch,
            table_update=config.table_update,
        )
        staged = stage_method_corpus(
            data, np.arange(data.n_items), rng, device=corpus_placement
        )
        run_chunk = runner._train_chunk(chunk)
        n_valid = chunk * batch_size

        def real_of(rows) -> int:
            # staging preserves item order, so row i IS item i
            return int(counts_capped[rows].sum())

        def make_rows():
            return rng.integers(0, data.n_items, n_valid).astype(np.int32)

        def run(state, key, rows):
            key, sub = jax.random.split(key)
            state, loss = run_chunk(
                state, staged.contexts, staged.row_splits, staged.labels,
                rows, n_valid, sub,
            )
            return state, loss, key

    from code2vec_tpu.obs.trace import get_tracer

    key = jax.random.PRNGKey(1)
    # chunks, not steps; includes compile. Floor at 2 so the steady-state
    # window never starts on the compile chunk — except in the emergency
    # fallback, where every chunk counts against the supervisor's budget
    # and a compile-tainted (clearly labeled cpu) number beats none.
    min_warmup = 1 if fell_back else 2
    with get_tracer().span("bench_warmup", category="bench"):
        for _ in range(max(warmup, min_warmup)):
            state, loss, key = run(state, key, make_rows())
        jax.block_until_ready(loss)

    n_chunks = -(-steps // chunk)
    steps = n_chunks * chunk
    measured_real = 0  # real (non-PAD) context slots in the measured window
    with get_tracer().span("bench_measure", category="bench", chunks=n_chunks):
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            rows = make_rows()
            # a numpy gather-sum over the chunk's rows, ~µs against ms-scale
            # dispatches — the honest numerator costs nothing measurable
            measured_real += real_of(rows)
            state, loss, key = run(state, key, rows)
        jax.block_until_ready(loss)
        elapsed = time.perf_counter() - t0

    # per-step attribution probe: a few FENCED chunks after the measured
    # window (fencing must never taint the throughput number), splitting
    # wall time into host row-gen / H2D / device compute — the breakdown
    # three VERDICT rounds asked for behind the headline ms/step. Under a
    # mesh the rows transfer is folded into the dispatch (an explicitly
    # placed array would fight the chunk's in_shardings), flagged below.
    attr_chunks = int(os.environ.get("BENCH_ATTR_CHUNKS", 3))
    attribution = None
    if attr_chunks > 0:
        host_ms = h2d_ms = comp_ms = 0.0
        for _ in range(attr_chunks):
            a0 = time.perf_counter()
            rows = make_rows()
            a1 = time.perf_counter()
            if mesh is None:
                rows = jax.block_until_ready(jax.device_put(rows))
            a2 = time.perf_counter()
            state, loss, key = run(state, key, rows)
            jax.block_until_ready(loss)
            a3 = time.perf_counter()
            host_ms += (a1 - a0) * 1e3
            h2d_ms += (a2 - a1) * 1e3
            comp_ms += (a3 - a2) * 1e3
        denom = attr_chunks * chunk
        attribution = {
            "host_build_ms": round(host_ms / denom, 4),
            "h2d_ms": round(h2d_ms / denom, 4),
            "compute_ms": round(comp_ms / denom, 4),
            "profiled_steps": denom,
            "h2d_folded_into_compute": mesh is not None,
        }

    # per-chip normalization keeps the metric comparable across mesh sizes
    # (a meshed run measures aggregate throughput over mesh.size chips).
    # The headline counts REAL contexts; padded_slots_per_sec keeps the
    # pre-change accounting visible next to it.
    n_chips = 1 if mesh is None else mesh.size
    padded_slots = batch_size * bag * steps
    padded_slots_per_sec = padded_slots / elapsed / n_chips
    contexts_per_sec = measured_real / elapsed / n_chips
    pad_efficiency = measured_real / padded_slots if padded_slots else 1.0
    previous = _previous_benchmark(backend)
    if previous is None:
        vs_baseline = 1.0
    else:
        prev_value, prev_padded = previous
        # like-for-like: a pre-honesty round stored padded slots, so divide
        # padded slots into it — not real contexts, which would print the
        # accounting change as a phantom ~pad_efficiency× regression
        current = padded_slots_per_sec if prev_padded else contexts_per_sec
        vs_baseline = current / prev_value if prev_value else 1.0

    from code2vec_tpu.obs.runtime import memory_snapshot

    memory = memory_snapshot()

    # headline perf block: analytic fwd+bwd FLOPs at the measured shape
    # over the measured window — achieved FLOP/s and MFU against the
    # per-device-kind peak table (obs/costs.py). The window includes host
    # row-gen between dispatches, so this is a LOWER bound on device MFU.
    from code2vec_tpu.obs import costs as obs_costs

    device_kind = obs_costs.detect_device_kind()
    peak = obs_costs.peak_flops(device_kind)
    step_cost = obs_costs.train_step_cost(
        obs_costs.analytic_forward_cost(
            batch_size, bag,
            terminal_embed=model_config.terminal_embed_size,
            path_embed=model_config.path_embed_size,
            encode=model_config.encode_size,
            labels=model_config.padded(model_config.label_count),
        )
    )
    achieved_flops = step_cost["flops"] * steps / elapsed / n_chips
    perf = {
        "device_kind": device_kind,
        "peak_flops_per_s": peak,
        "flops_per_step": step_cost["flops"],
        "cost_source": step_cost["cost_source"],
        "achieved_flops_per_s_per_chip": round(achieved_flops, 1),
        "mfu": round(achieved_flops / peak, 9),
    }

    # The driver captures the merged stdout/stderr stream and parses the LAST
    # JSON line into BENCH_rN.json's `parsed` field — so the detail line goes
    # first (stderr) and the headline metric is the final thing printed.
    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "steps_per_sec": round(steps / elapsed, 3),
                    "real_contexts_per_sec": round(contexts_per_sec, 1),
                    "padded_slots_per_sec": round(padded_slots_per_sec, 1),
                    "pad_efficiency": round(pad_efficiency, 4),
                    # fraction of the corpus's real contexts the bag cap
                    # silently drops — the loss --max_contexts 0 /
                    # --longbag-ab removes
                    "truncated_context_fraction": round(
                        _truncated_fraction_of_counts(item_counts, bag), 6
                    ),
                    "batch": batch_size,
                    "bag": bag,
                    "mesh": None if mesh is None else dict(mesh.shape),
                    "shard_staged": shard_staged,
                    "final_chunk_loss_sum": float(loss),  # sum over BENCH_CHUNK batch losses
                    "compute_dtype": str(model_config.dtype.__name__ if hasattr(model_config.dtype, "__name__") else model_config.dtype),
                    # run-variable knobs: stamps must be self-describing
                    # across default flips (mu-bf16 landed round 4);
                    # use_pallas=true overrides attn_impl in the dispatch
                    "adam_mu_dtype": config.adam_mu_dtype,
                    "table_update": config.table_update,
                    "attn_impl": model_config.attn_impl,
                    "encoder_impl": model_config.encoder_impl,
                    "use_pallas": model_config.use_pallas,
                    # kernel impl + schedule provenance: which kernel this
                    # round actually measured, with the tuned-schedule
                    # accounting when --pallas_impl auto consulted the cache
                    "kernel": _kernel_provenance(model_config),
                    "sample_prefetch": sample_prefetch,
                    # host-ingest provenance: the headline measures the
                    # device-epoch path (batches sampled ON device — no
                    # host batch builds to parallelize), so feed workers
                    # are structurally idle here; --feed-ab is the host-
                    # pipeline instrument where BENCH_FEED_WORKERS bites
                    "feed": {
                        "workers": _recipe_knob(
                            "BENCH_FEED_WORKERS", 0, 0, fell_back, backend
                        ),
                        "host_pipeline": False,
                    },
                    "attribution": attribution,
                    "perf": perf,
                    "memory": memory,
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "path_contexts_per_sec_per_chip",
                "value": round(contexts_per_sec, 1),
                "unit": "contexts/sec",
                "vs_baseline": round(vs_baseline, 4),
                "mfu": perf["mfu"],
                "backend": backend,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_SUPERVISED", "").strip() != "1":
        sys.exit(_supervise())
    try:
        if "--prefetch-ab" in sys.argv[1:]:
            _prefetch_ab()
        elif "--bucket-ab" in sys.argv[1:]:
            _bucket_ab()
        elif "--kernel-ab" in sys.argv[1:]:
            _kernel_ab()
        elif "--serve" in sys.argv[1:]:
            _serve_bench()
        elif "--ooc-ab" in sys.argv[1:]:
            _ooc_ab()
        elif "--feed-ab" in sys.argv[1:]:
            _feed_ab()
        elif "--ann-ab" in sys.argv[1:]:
            _ann_ab()
        elif "--longbag-ab" in sys.argv[1:]:
            _longbag_ab()
        else:
            main()
    except Exception as exc:  # noqa: BLE001 - always leave a JSON record for the driver
        import traceback

        traceback.print_exc()
        print(_failure_record(f"{type(exc).__name__}: {exc}"), flush=True)
        sys.exit(1)
