"""Headline benchmark: training throughput in path-contexts/sec/chip at
top11 scale (BASELINE.md: the reference publishes no numbers; this run
establishes/extends the baseline).

Setup mirrors the reference's top11 recipe (README.md:34 — batch 1024,
embed 100/100, encode 100) at the top11 corpus scale (605,945 methods,
360,631 terminals, 342,845 paths — top11_dataset/params.txt), with the
TPU-ablation-winning recipe (f32 compute, unsafe_rbg dropout bits, dense
embedding backward — tools/run_tpu_ablation.py, docs/ARCHITECTURE.md;
override via BENCH_DTYPE / BENCH_RNG_IMPL / BENCH_EMBED_GRAD). The measured path is the flagship one: the corpus staged to
device memory once (CSR), per-epoch context subsampling on device, and
scanned chunks of [1024, 200] train steps per dispatch
(train/device_epoch.py). Accounting matches the reference's work per step:
B x L context slots.

Output contract: a detail JSON line goes to stderr first, then the headline
metric JSON {"metric", "value", "unit", "vs_baseline", "backend"} is the
LAST line printed to stdout — the driver parses the final JSON line of the
merged stream. On failure, a metric line with value=null and an "error"
field is still emitted. vs_baseline compares against the newest successful
BENCH_r*.json in the repo (1.0 on the first ever run).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

import numpy as np


def _extract_value(payload: dict) -> float | None:
    """Pull the headline metric out of one BENCH_r*.json.

    The driver writes {n, cmd, rc, tail, parsed}: `parsed` is whichever JSON
    line it captured from the merged stdout/stderr stream, and `tail` holds
    the raw last lines. Accept, in order: a bare {"value": ...} payload (the
    schema this file documented before round 2's verdict corrected it),
    parsed.value, and finally a scan of `tail` for the metric line.
    """
    for candidate in (payload, payload.get("parsed") or {}):
        if isinstance(candidate, dict) and "value" in candidate:
            try:
                return float(candidate["value"])
            except (TypeError, ValueError):
                pass
    tail = payload.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "value" in obj:
                try:
                    return float(obj["value"])
                except (TypeError, ValueError):
                    continue
    return None


def _previous_benchmark() -> float | None:
    best = None
    best_round = -1
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if not isinstance(payload, dict) or payload.get("rc", 0) != 0:
            continue
        value = _extract_value(payload)
        if value is not None and int(m.group(1)) > best_round:
            best_round = int(m.group(1))
            best = value
    return best


def _purge_jax_modules() -> None:
    import importlib

    for mod in [m for m in list(sys.modules) if m == "jax" or m.startswith("jax.")]:
        sys.modules.pop(mod, None)
    importlib.invalidate_caches()


def _init_backend():
    """Import jax and force backend init, retrying once and falling back to
    CPU if the TPU tunnel is wedged (the BENCH_r01 failure mode: rc=1, zero
    perf data). Returns (jax_module, backend_name)."""
    for attempt in range(2):
        try:
            import jax

            # the experimental axon device plugin can pre-empt the
            # JAX_PLATFORMS env var; the config API route is reliable
            if os.environ.get("JAX_PLATFORMS", "").strip():
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            return jax, jax.default_backend()
        except Exception as exc:  # noqa: BLE001 - backend init raises RuntimeError subclasses
            print(f"bench: backend init failed (attempt {attempt + 1}): {exc}", file=sys.stderr)
            _purge_jax_modules()
            if attempt == 0:
                time.sleep(2.0)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax, jax.default_backend()


def main() -> None:
    jax, backend = _init_backend()
    import jax.numpy as jnp

    from code2vec_tpu.data.pipeline import iter_batches, build_method_epoch
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.device_epoch import EpochRunner, stage_method_corpus
    from code2vec_tpu.train.step import create_train_state

    # persistent compilation cache: repeat runs (and retries after tunnel
    # resets) skip the ~30s XLA compile
    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    batch_size = int(os.environ.get("BENCH_BATCH", 1024))
    bag = int(os.environ.get("BENCH_BAG", 200))
    steps = int(os.environ.get("BENCH_STEPS", 60))
    warmup = int(os.environ.get("BENCH_WARMUP_CHUNKS", 5))
    data_axis = int(os.environ.get("BENCH_DATA_AXIS", 1))
    model_axis = int(os.environ.get("BENCH_MODEL_AXIS", 1))
    # dims: default is the reference top11 recipe; BENCH_EMBED/BENCH_ENCODE
    # override for e.g. the wide-model config (BASELINE config 4: 512/512)
    embed_size = int(os.environ.get("BENCH_EMBED", 100))
    encode_size = int(os.environ.get("BENCH_ENCODE", 100))

    # top11-scale synthetic corpus, shrunk in method count (the throughput
    # metric depends on vocab/model/batch shape, not corpus length); vocab
    # sizes are the real top11 ones
    spec = SynthSpec(
        n_methods=max(batch_size * 8, 8192),
        n_terminals=360_631,
        n_paths=342_845,
        n_labels=8_000,
        mean_contexts=120.0,
        max_contexts=400,
        seed=0,
    )
    raw = generate_corpus_data(spec)
    data = corpus_data_from_raw(raw)

    model_config = Code2VecConfig(
        terminal_count=spec.n_terminals + 2,
        path_count=spec.n_paths + 1,
        label_count=len(data.label_vocab),
        terminal_embed_size=embed_size,
        path_embed_size=embed_size,
        encode_size=encode_size,  # the reference top11 recipe (README.md:34)
        dropout_prob=0.25,
        # f32 measured faster than bf16 at the top11 recipe (dims 100) —
        # the step is scatter/HBM-bound, and bf16 only adds casts around
        # f32 accumulations (tools/run_tpu_ablation.py, docs/ARCHITECTURE.md)
        dtype=jnp.bfloat16
        if os.environ.get("BENCH_DTYPE", "float32").strip().lower()
        in ("bfloat16", "bf16")
        else jnp.float32,
        embed_grad=os.environ.get("BENCH_EMBED_GRAD", "dense"),
        use_pallas=os.environ.get("BENCH_USE_PALLAS", "0").strip().lower()
        in ("1", "true", "yes", "on"),
        pallas_block_b=int(os.environ.get("BENCH_PALLAS_BLOCK_B", 8)),
        # pad the tables so a model axis actually shards them instead of
        # silently replicating (parallel.shardings divisibility rule)
        vocab_pad_multiple=max(model_axis, 1),
    )
    config = TrainConfig(
        batch_size=batch_size,
        max_path_length=bag,
        # unsafe_rbg: ~2 ms/step cheaper dropout bits (ablation winner);
        # fine for a throughput benchmark, selectable for training runs
        rng_impl=os.environ.get("BENCH_RNG_IMPL", "unsafe_rbg"),
    )

    rng = np.random.default_rng(0)
    epoch = build_method_epoch(data, np.arange(batch_size), bag, rng)
    example = next(iter_batches(epoch, batch_size, rng=rng, pad_final=False))
    state = create_train_state(config, model_config, jax.random.PRNGKey(0), example)
    class_weights = jnp.ones(model_config.label_count, jnp.float32)

    # the measured path is the flagship one: corpus staged to device memory
    # once, per-epoch context sampling on device, scanned chunks of batches
    # per dispatch (train/device_epoch.py). BENCH_DATA_AXIS/BENCH_MODEL_AXIS
    # > 1 runs the same path SPMD over a mesh (corpus replicated, batches
    # sharded) — the multi-chip scale-out configuration.
    chunk = int(os.environ.get("BENCH_CHUNK", 16))
    mesh = None
    corpus_placement = None
    if data_axis * model_axis > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state

        mesh = make_mesh(data=data_axis, model=model_axis)
        state = shard_state(mesh, state)
        corpus_placement = NamedSharding(mesh, PartitionSpec())

    # BENCH_SHARD_STAGED=1 (+ BENCH_DATA_AXIS>1): corpus partitioned over
    # the data axis (per-device HBM ~1/data_axis) with shard_map sampling
    shard_staged = mesh is not None and os.environ.get(
        "BENCH_SHARD_STAGED", "0"
    ).strip().lower() in ("1", "true", "yes", "on")
    if shard_staged:
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            stage_method_corpus_sharded,
        )

        runner = ShardedEpochRunner(
            model_config, class_weights, batch_size, bag, chunk, mesh=mesh
        )
        staged = stage_method_corpus_sharded(
            data, np.arange(data.n_items), rng, mesh
        )
        run_chunk = runner._train_chunk(chunk)
        span = chunk * runner.per_shard
        valid = np.ones((runner.n_shards, span), np.float32)

        def run(state, key):
            # max(counts, 1): an empty shard (n_items < data_axis) still
            # needs a valid row bound; its rows are all-PAD row 0
            rows = rng.integers(
                0, np.maximum(staged.shard_counts[:, None], 1),
                (runner.n_shards, span),
            ).astype(np.int32)
            key, sub = jax.random.split(key)
            state, loss = run_chunk(
                state, staged.contexts, staged.row_splits, staged.labels,
                rows, valid, sub,
            )
            return state, loss, key
    else:
        runner = EpochRunner(
            model_config, class_weights, batch_size, bag, chunk, mesh=mesh
        )
        staged = stage_method_corpus(
            data, np.arange(data.n_items), rng, device=corpus_placement
        )
        run_chunk = runner._train_chunk(chunk)
        n_valid = chunk * batch_size

        def run(state, key):
            rows = rng.integers(0, data.n_items, n_valid).astype(np.int32)
            key, sub = jax.random.split(key)
            state, loss = run_chunk(
                state, staged.contexts, staged.row_splits, staged.labels,
                rows, n_valid, sub,
            )
            return state, loss, key

    key = jax.random.PRNGKey(1)
    for _ in range(max(warmup, 2)):  # chunks, not steps; includes compile
        state, loss, key = run(state, key)
    jax.block_until_ready(loss)

    n_chunks = -(-steps // chunk)
    steps = n_chunks * chunk
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        state, loss, key = run(state, key)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - t0

    # per-chip normalization keeps the metric comparable across mesh sizes
    # (a meshed run measures aggregate throughput over mesh.size chips)
    n_chips = 1 if mesh is None else mesh.size
    contexts_per_sec = batch_size * bag * steps / elapsed / n_chips
    previous = _previous_benchmark()
    vs_baseline = contexts_per_sec / previous if previous else 1.0

    # The driver captures the merged stdout/stderr stream and parses the LAST
    # JSON line into BENCH_rN.json's `parsed` field — so the detail line goes
    # first (stderr) and the headline metric is the final thing printed.
    print(
        json.dumps(
            {
                "detail": {
                    "backend": backend,
                    "steps_per_sec": round(steps / elapsed, 3),
                    "batch": batch_size,
                    "bag": bag,
                    "mesh": None if mesh is None else dict(mesh.shape),
                    "shard_staged": shard_staged,
                    "final_chunk_loss_sum": float(loss),  # sum over BENCH_CHUNK batch losses
                    "compute_dtype": str(model_config.dtype.__name__ if hasattr(model_config.dtype, "__name__") else model_config.dtype),
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": "path_contexts_per_sec_per_chip",
                "value": round(contexts_per_sec, 1),
                "unit": "contexts/sec",
                "vs_baseline": round(vs_baseline, 4),
                "backend": backend,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 - always leave a JSON record for the driver
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "path_contexts_per_sec_per_chip",
                    "value": None,
                    "unit": "contexts/sec",
                    "vs_baseline": None,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            ),
            flush=True,
        )
        sys.exit(1)
