"""Fused gather→encode→attend→pool kernel, quantized tables, autotuner.

Everything runs in Pallas interpreter mode on CPU (the same code path the
TPU compiles); parity is always against the unfused XLA formulation.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.ops.fused_encode_pool import (
    fused_encode_attend_pool,
    xla_reference_forward,
)
from code2vec_tpu.ops.quant import (
    QuantTable,
    dequantize_table,
    quantize_table,
)

# the ladder the parity matrix sweeps: small enough for the interpreter,
# shaped like a real bucket ladder (several rungs below the top width)
LADDER = (8, 24, 56)


def op_inputs(B, L, Et=6, Ep=5, H=12, seed=0, all_masked_row=None):
    rng = np.random.default_rng(seed)
    Vt, Vp = 37, 29
    tt = jnp.asarray(rng.normal(size=(Vt, Et)).astype(np.float32))
    pt = jnp.asarray(rng.normal(size=(Vp, Ep)).astype(np.float32))
    starts = rng.integers(1, Vt, (B, L)).astype(np.int32)
    mask = (rng.random((B, L)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    if all_masked_row is not None:
        mask[all_masked_row, :] = 0.0
    return dict(
        t_table=tt,
        p_table=pt,
        starts=jnp.asarray(starts),
        paths=jnp.asarray(rng.integers(1, Vp, (B, L)).astype(np.int32)),
        ends=jnp.asarray(rng.integers(1, Vt, (B, L)).astype(np.int32)),
        mask=jnp.asarray(mask),
        dense_kernel=jnp.asarray(
            rng.normal(size=(2 * Et + Ep, H)).astype(np.float32) * 0.1
        ),
        ln_scale=jnp.asarray(1.0 + 0.1 * rng.normal(size=H).astype(np.float32)),
        ln_bias=jnp.asarray(0.1 * rng.normal(size=H).astype(np.float32)),
        attn_param=jnp.asarray(rng.normal(size=H).astype(np.float32)),
    )


def call(inp, **kw):
    return fused_encode_attend_pool(
        inp["t_table"], inp["p_table"], inp["starts"], inp["paths"],
        inp["ends"], inp["mask"], inp["dense_kernel"], inp["ln_scale"],
        inp["ln_bias"], inp["attn_param"], **kw,
    )


def reference(inp, **kw):
    return xla_reference_forward(
        inp["t_table"], inp["p_table"], inp["starts"], inp["paths"],
        inp["ends"], inp["mask"], inp["dense_kernel"], inp["ln_scale"],
        inp["ln_bias"], inp["attn_param"], **kw,
    )


class TestOpParity:
    """Acceptance matrix: every ladder width × {partial, full} batch ×
    both kernel impls matches the unfused XLA path."""

    @pytest.mark.parametrize("width", LADDER)
    @pytest.mark.parametrize("batch", [3, 8])  # 3 = partial block_b tile
    @pytest.mark.parametrize("impl", ["gather_split", "fused"])
    def test_matches_xla(self, width, batch, impl):
        inp = op_inputs(batch, width, seed=width * 100 + batch)
        cv_ref, w_ref = reference(inp)
        cv, w = call(inp, impl=impl, block_b=4, dma_depth=2)
        np.testing.assert_allclose(
            np.asarray(cv), np.asarray(cv_ref), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(w), np.asarray(w_ref), rtol=1e-5, atol=1e-6
        )

    @pytest.mark.parametrize("impl", ["gather_split", "fused"])
    def test_all_masked_row_degenerates_like_xla(self, impl):
        # the fully-masked row must softmax uniformly over the REAL bag
        # length (pallas_attention_pool's exact semantics), not the padded
        inp = op_inputs(5, 21, seed=7, all_masked_row=2)
        cv_ref, w_ref = reference(inp)
        cv, w = call(inp, impl=impl, block_b=4)
        np.testing.assert_allclose(
            np.asarray(w[2]), np.asarray(w_ref[2]), rtol=1e-5
        )
        np.testing.assert_allclose(float(w[2].sum()), 1.0, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(cv[2]), np.asarray(cv_ref[2]), rtol=1e-4, atol=1e-5
        )

    def test_dma_depth_and_chunk_variants_agree(self):
        # schedule knobs change the pipeline, never the math
        inp = op_inputs(6, 40, seed=3)
        base = call(inp, impl="fused", block_b=4, dma_depth=2)
        for depth, chunk in ((1, 128), (3, 128), (2, 64)):
            cv, w = call(
                inp, impl="fused", block_b=4, dma_depth=depth, chunk_l=chunk
            )
            np.testing.assert_allclose(
                np.asarray(cv), np.asarray(base[0]), rtol=1e-6, atol=1e-6
            )

    def test_grads_exact_to_unfused(self):
        inp = op_inputs(4, 17, seed=11)
        names = ("t_table", "p_table", "dense_kernel", "ln_scale", "ln_bias",
                 "attn_param")

        def loss(fn):
            def inner(*diff):
                d = dict(inp, **dict(zip(names, diff)))
                cv, w = fn(d)
                return jnp.sum(cv**2) + jnp.sum(w * jnp.cos(w))

            return inner

        args = tuple(inp[n] for n in names)
        g_ref = jax.grad(loss(reference), argnums=tuple(range(6)))(*args)
        g_fused = jax.grad(
            loss(lambda d: call(d, impl="fused", block_b=4)),
            argnums=tuple(range(6)),
        )(*args)
        for a, b in zip(g_fused, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            )

    def test_offset_grads_match_reference(self):
        # the lazy touched-rows optimizer differentiates w.r.t. zero offset
        # tensors; the fused backward must hand back identical per-slot grads
        inp = op_inputs(3, 9, seed=13)
        off = (
            jnp.zeros((3, 18, 6), jnp.float32),
            jnp.zeros((3, 9, 5), jnp.float32),
        )

        g1 = jax.grad(
            lambda o: jnp.sum(
                call(inp, off_se=o[0], off_p=o[1], impl="fused", block_b=4)[0]
                ** 2
            )
        )(off)
        g2 = jax.grad(
            lambda o: jnp.sum(reference(inp, off_se=o[0], off_p=o[1])[0] ** 2)
        )(off)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            )


class TestQuantTables:
    def test_int8_round_trip_error_bounded(self):
        rng = np.random.default_rng(0)
        table = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
        qt = quantize_table(table, "int8")
        assert qt.values.dtype == jnp.int8
        back = np.asarray(dequantize_table(qt))
        # symmetric per-row absmax: max error is half a quant step per row
        step = np.abs(np.asarray(table)).max(axis=1, keepdims=True) / 127.0
        assert (np.abs(back - np.asarray(table)) <= step * 0.5 + 1e-7).all()

    def test_zero_row_stays_exact_zero(self):
        table = jnp.zeros((4, 8), jnp.float32).at[1].set(1.5)
        qt = quantize_table(table, "int8")
        assert np.asarray(dequantize_table(qt))[0].sum() == 0.0

    def test_quant_table_is_pytree(self):
        qt = quantize_table(jnp.ones((4, 8)), "int8")
        mapped = jax.tree.map(lambda x: x, qt)
        assert isinstance(mapped, QuantTable) and mapped.table_dtype == "int8"

    @pytest.mark.parametrize("impl", ["gather_split", "fused"])
    def test_kernel_dequant_matches_xla_dequant(self, impl):
        inp = op_inputs(4, 20, seed=5)
        qinp = dict(
            inp,
            t_table=quantize_table(inp["t_table"], "int8"),
            p_table=quantize_table(inp["p_table"], "int8"),
        )
        cv_ref, w_ref = reference(qinp)
        cv, w = call(qinp, impl=impl, block_b=4)
        np.testing.assert_allclose(
            np.asarray(cv), np.asarray(cv_ref), rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(w), np.asarray(w_ref), rtol=1e-4, atol=1e-5
        )


def model_fixture(B=6, L=14, dropout=0.0, **cfg_kw):
    rng = np.random.default_rng(0)
    base = dict(
        terminal_count=50, path_count=40, label_count=9,
        terminal_embed_size=8, path_embed_size=6, encode_size=16,
        dropout_prob=dropout,
    )
    batch = dict(
        starts=jnp.asarray(rng.integers(1, 50, (B, L)).astype(np.int32)),
        paths=jnp.asarray(rng.integers(1, 40, (B, L)).astype(np.int32)),
        ends=jnp.asarray(rng.integers(1, 50, (B, L)).astype(np.int32)),
    )
    batch["starts"] = batch["starts"].at[:, L // 2 :].set(0)
    model = Code2Vec(Code2VecConfig(**base, **cfg_kw))
    ref = Code2Vec(Code2VecConfig(**base))
    params = ref.init(
        {"params": jax.random.PRNGKey(0)},
        batch["starts"], batch["paths"], batch["ends"],
    )["params"]
    return model, ref, params, batch


class TestModelDispatch:
    @pytest.mark.parametrize("impl", ["pool_only", "gather_split", "fused"])
    def test_param_tree_identical_and_forward_matches(self, impl):
        model, ref, params, batch = model_fixture(
            use_pallas=True, pallas_impl=impl, pallas_block_b=4
        )
        own = model.init(
            {"params": jax.random.PRNGKey(0)},
            batch["starts"], batch["paths"], batch["ends"],
        )["params"]
        assert jax.tree.structure(own) == jax.tree.structure(params)
        for a, b in zip(jax.tree.leaves(own), jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = model.apply(
            {"params": params}, batch["starts"], batch["paths"], batch["ends"]
        )
        out_ref = ref.apply(
            {"params": params}, batch["starts"], batch["paths"], batch["ends"]
        )
        for a, b in zip(out, out_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    def test_unknown_pallas_impl_fails_loudly(self):
        model, _, params, batch = model_fixture(
            use_pallas=True, pallas_impl="typo"
        )
        with pytest.raises(ValueError, match="pallas_impl"):
            model.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"],
            )

    @pytest.mark.parametrize("table_dtype", ["bf16", "int8"])
    def test_quantized_forward_agreement_thresholds(self, table_dtype):
        model, ref, params, batch = model_fixture(table_dtype=table_dtype)
        logits, cv, _ = model.apply(
            {"params": params}, batch["starts"], batch["paths"], batch["ends"]
        )
        logits_ref, cv_ref, _ = ref.apply(
            {"params": params}, batch["starts"], batch["paths"], batch["ends"]
        )
        cv, cv_ref = np.asarray(cv), np.asarray(cv_ref)
        cos = (cv * cv_ref).sum(-1) / (
            np.linalg.norm(cv, axis=-1) * np.linalg.norm(cv_ref, axis=-1)
        )
        assert cos.min() > 0.99, f"cosine {cos.min()}"
        agree = (
            np.argmax(np.asarray(logits), -1)
            == np.argmax(np.asarray(logits_ref), -1)
        ).mean()
        assert agree >= 0.9, f"top-1 agreement {agree}"

    def test_fused_training_step_runs_dense_and_lazy(self):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state, make_train_step

        rng = np.random.default_rng(1)
        B, L = 6, 14
        model, _, params, batch = model_fixture(
            B, L, dropout=0.25, use_pallas=True, pallas_impl="fused",
            pallas_block_b=4,
        )
        full = dict(
            {k: np.asarray(v) for k, v in batch.items()},
            labels=rng.integers(0, 9, B).astype(np.int32),
            example_mask=np.ones(B, np.float32),
            ids=np.arange(B, dtype=np.int64),
        )
        cw = jnp.ones(9, jnp.float32)
        for table_update in ("dense", "lazy"):
            tc = TrainConfig(
                batch_size=B, max_path_length=L, table_update=table_update
            )
            st = create_train_state(
                tc, model.config, jax.random.PRNGKey(0), full
            )
            step = make_train_step(model.config, cw, table_update)
            st, l1 = step(st, full)
            st, l2 = step(st, full)
            assert np.isfinite(float(l1)) and np.isfinite(float(l2))
            assert float(l2) != float(l1)  # it actually learned something


class TestFusedEndToEnd:
    def test_training_with_fused_device_epoch(self, tmp_path):
        """The fused kernel inside the scanned device-epoch chunk (donated
        state, lax.scan) — the configuration the TPU benchmark exercises
        with BENCH_USE_PALLAS=1 BENCH_PALLAS_IMPL=fused."""
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"]
        )
        cfg = TrainConfig(
            max_epoch=1, batch_size=32, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=16,
            print_sample_cycle=0, use_pallas=True, pallas_impl="fused",
            pallas_block_b=8, device_epoch=True, device_chunk_batches=2,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])


class TestFusedOnMesh:
    """The fused kernels composed with data/model mesh axes: the op's
    custom_partitioning rule shards the batch dim instead of replicating
    the Mosaic call behind an all-gather (same contract as
    TestPallasOnMesh for the pool-only kernel)."""

    @pytest.mark.parametrize("impl", ["gather_split", "fused"])
    def test_matches_xla_path_on_mesh(self, impl):
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_batch, shard_state
        from code2vec_tpu.parallel.step import make_parallel_train_step
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state

        mesh = make_mesh(data=4, model=2, ctx=1)
        rng = np.random.default_rng(0)
        B, L = 16, 24
        base = dict(
            terminal_count=60, path_count=50, label_count=9,
            terminal_embed_size=8, path_embed_size=8, encode_size=16,
            dropout_prob=0.0,
        )
        batch = {
            "ids": np.arange(B, dtype=np.int64),
            "starts": rng.integers(1, 60, (B, L)).astype(np.int32),
            "paths": rng.integers(1, 50, (B, L)).astype(np.int32),
            "ends": rng.integers(1, 60, (B, L)).astype(np.int32),
            "labels": rng.integers(0, 9, B).astype(np.int32),
            "example_mask": np.ones(B, np.float32),
        }
        batch["starts"][:, L // 2 :] = 0

        losses = {}
        for use_fused in (False, True):
            mc = Code2VecConfig(
                **base,
                use_pallas=use_fused,
                pallas_impl=impl,
                pallas_block_b=4,
            )
            tc = TrainConfig(batch_size=B, max_path_length=L)
            state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
            state = shard_state(mesh, state)
            cw = jnp.ones(mc.label_count, jnp.float32)
            step = make_parallel_train_step(mc, cw, mesh, state)
            device_batch = shard_batch(mesh, batch)
            state, loss = step(state, device_batch)
            state, loss2 = step(state, device_batch)
            losses[use_fused] = (float(loss), float(loss2))
        np.testing.assert_allclose(losses[False], losses[True], rtol=2e-5)


class TestTrainingRejectsQuantized:
    def test_train_rejects_table_dtype(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"]
        )
        cfg = TrainConfig(table_dtype="int8", max_epoch=1)
        with pytest.raises(ValueError, match="not trainable"):
            train(cfg, data)

    def test_step_contract_rejects_quantized_master_weights(self):
        # the trace-time pincer: even a hand-built state with non-f32
        # tables must fail at the step contract, not train on dequant noise
        from code2vec_tpu.analysis.contracts import ContractError
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state, make_train_step

        rng = np.random.default_rng(0)
        B, L = 4, 8
        model, _, params, batch = model_fixture(B, L)
        full = dict(
            {k: np.asarray(v) for k, v in batch.items()},
            labels=rng.integers(0, 9, B).astype(np.int32),
            example_mask=np.ones(B, np.float32),
            ids=np.arange(B, dtype=np.int64),
        )
        tc = TrainConfig(batch_size=B, max_path_length=L)
        st = create_train_state(tc, model.config, jax.random.PRNGKey(0), full)
        bad_params = dict(st.params)
        bad_params["terminal_embedding"] = {
            "embedding": st.params["terminal_embedding"]["embedding"].astype(
                jnp.bfloat16
            )
        }
        st = st.replace(params=bad_params)
        step = make_train_step(model.config, jnp.ones(9, jnp.float32))
        with pytest.raises(ContractError, match="float32"):
            step(st, full)

    def test_ctx_axis_error_names_fused_kernel_flags(self):
        # regression for the error path: the message must steer users of
        # the NEW kernel flags too, not just --use_pallas
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import build_mesh

        cfg = TrainConfig(use_pallas=True, context_axis=2, batch_size=32)
        with pytest.raises(ValueError, match="pallas_impl") as exc:
            build_mesh(cfg)
        msg = str(exc.value)
        assert "use_pallas with context_axis" in msg
        for flag in ("pool_only", "gather_split", "fused", "pallas_dma_depth"):
            assert flag in msg


class TestAutotune:
    def _keys(self, at, widths=(8, 16), dtypes=("f32",)):
        return at.keys_for(4, list(widths), 6, 5, 12, list(dtypes))

    def test_dry_round_trip_zero_search_on_second_run(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        cache = at.ScheduleCache(str(tmp_path / "sched.json"))
        before = at.counters_snapshot()
        at.autotune(self._keys(at), cache=cache, dry=True)
        mid = at.counters_snapshot()
        assert mid["autotune_cache_miss"] - before["autotune_cache_miss"] == 2
        assert mid["autotune_schedule_stored"] - before["autotune_schedule_stored"] == 2

        # a FRESH cache object re-reads the persisted file: zero timing
        # runs, every schedule loads from disk
        cache2 = at.ScheduleCache(str(tmp_path / "sched.json"))
        out = at.autotune(self._keys(at), cache=cache2, dry=True)
        after = at.counters_snapshot()
        assert after["autotune_cache_hit"] - mid["autotune_cache_hit"] == 2
        assert after["autotune_cache_miss"] == mid["autotune_cache_miss"]
        assert after["autotune_timing_run"] == mid["autotune_timing_run"]
        assert all(s.source == "cache" for s in out.values())

    def test_timed_autotune_picks_a_winner_and_persists(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        cache = at.ScheduleCache(str(tmp_path / "sched.json"))
        keys = at.keys_for(4, [8], 4, 4, 8, ["f32"])
        before = at.counters_snapshot()
        out = at.autotune(cache=cache, keys=keys, iters=1, repeats=1, vocab=64)
        after = at.counters_snapshot()
        assert after["autotune_timing_run"] > before["autotune_timing_run"]
        (sched,) = out.values()
        assert sched.impl in at.IMPLS and sched.source == "autotune"
        entry = json.load(open(cache.path))["entries"]
        (stored,) = entry.values()
        assert stored["schedule"]["impl"] == sched.impl
        assert stored["timings_ms"]  # provenance: per-variant timings kept

    def test_lookup_schedule_miss_falls_back_without_search(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        cache = at.ScheduleCache(str(tmp_path / "empty.json"))
        before = at.counters_snapshot()
        sched = at.lookup_schedule(4, 99, 6, 5, 12, cache=cache)
        after = at.counters_snapshot()
        # the fallback is whatever default_schedule() resolves to under the
        # ambient kernel backend (pool_only@auto in interpret mode,
        # gather_split@cpu under the compiled CPU strategy)
        assert sched == at.default_schedule() and sched.source == "default"
        assert after["autotune_cache_miss"] == before["autotune_cache_miss"] + 1
        assert after["autotune_timing_run"] == before["autotune_timing_run"]

    def test_corrupt_cache_is_empty_not_fatal(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        p = tmp_path / "bad.json"
        p.write_text("{corrupt")
        cache = at.ScheduleCache(str(p))
        assert cache.entries == {}

    def test_cli_dry_smoke_and_expect_cached(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        argv = [
            "--autotune", "--dry", "--cache", str(tmp_path / "c.json"),
            "--batch", "4", "--widths", "8", "--terminal-embed", "4",
            "--path-embed", "4", "--encode", "8",
        ]
        assert at.main(argv) == 0
        # second identical run: everything cached — --expect-cached passes
        assert at.main(argv + ["--expect-cached"]) == 0
        # a new shape under --expect-cached must fail loudly
        assert (
            at.main(
                [a if a != "8" else "16" for a in argv] + ["--expect-cached"]
            )
            == 2
        )

    def test_model_auto_impl_consults_cache_at_trace_time(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        model, ref, params, batch = model_fixture(
            use_pallas=True, pallas_impl="auto", pallas_block_b=4
        )
        b, l = np.asarray(batch["starts"]).shape
        cache = at.get_cache(str(tmp_path / "model.json"))
        key = at.ShapeKey(
            device_kind=at.device_kind(), batch=b, width=l,
            terminal_embed=8, path_embed=6, encode=16, table_dtype="f32",
        )
        cache.put(key, at.KernelSchedule(impl="gather_split", block_b=4))
        cache.save()
        try:
            before = at.counters_snapshot()
            out = jax.jit(
                lambda p, bt: model.apply(
                    {"params": p}, bt["starts"], bt["paths"], bt["ends"]
                )
            )(params, batch)
            after = at.counters_snapshot()
            # the trace consulted the cache exactly once and used its winner
            assert after["autotune_cache_hit"] == before["autotune_cache_hit"] + 1
            out_ref = ref.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"],
            )
            np.testing.assert_allclose(
                np.asarray(out[1]), np.asarray(out_ref[1]), rtol=1e-4,
                atol=1e-5,
            )
        finally:
            at.reset_cache()


class TestQuantizedServingRoundTrip:
    @pytest.fixture(scope="class")
    def trained_model_dir(self, tmp_path_factory):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        root = tmp_path_factory.mktemp("quant_rt")
        paths = generate_corpus_files(root, SPECS["tiny"])
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"]
        )
        out_dir = str(root / "model")
        cfg = TrainConfig(
            max_epoch=2, batch_size=32, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=16,
            print_sample_cycle=0,
        )
        train(cfg, data, out_dir=out_dir, vectors_path=str(root / "code.vec"))
        return root, paths, out_dir

    @pytest.mark.parametrize("table_dtype", ["bf16", "int8"])
    def test_export_predict_round_trip(self, trained_model_dir, table_dtype):
        # train → checkpoint+meta → quantized Predictor: the quantized
        # serving forward must agree with the f32 one on real contexts
        from code2vec_tpu.predict import Predictor

        root, paths, out_dir = trained_model_dir
        f32 = Predictor(
            out_dir, str(paths["terminal_idx"]), str(paths["path_idx"])
        )
        q = Predictor(
            out_dir, str(paths["terminal_idx"]), str(paths["path_idx"]),
            table_dtype=table_dtype,
        )
        assert q.table_dtype == table_dtype
        assert q._quant_tables is not None
        rng = np.random.default_rng(0)
        contexts = [
            (int(s), int(p), int(e))
            for s, p, e in zip(
                rng.integers(2, 20, 12), rng.integers(1, 15, 12),
                rng.integers(2, 20, 12),
            )
        ]
        pf = f32._predict_contexts("m", list(contexts), 0, top_k=3, rng=None)
        pq = q._predict_contexts("m", list(contexts), 0, top_k=3, rng=None)
        # top-1 must agree; probabilities within quantization tolerance
        assert pf.predictions[0].name == pq.predictions[0].name
        assert abs(pf.predictions[0].prob - pq.predictions[0].prob) < 0.05
        cos = float(
            np.dot(pf.code_vector, pq.code_vector)
            / (np.linalg.norm(pf.code_vector) * np.linalg.norm(pq.code_vector))
        )
        assert cos > 0.99

    def test_export_only_accepts_quantized(self, trained_model_dir):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.export import export_from_checkpoint
        from code2vec_tpu.train.config import TrainConfig

        root, paths, out_dir = trained_model_dir
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"]
        )
        cfg = TrainConfig(
            max_epoch=2, batch_size=32, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=16,
            table_dtype="int8",
        )
        vec = str(root / "code_int8.vec")
        f1 = export_from_checkpoint(cfg, data, out_dir, vec)
        assert os.path.exists(vec)
        assert np.isfinite(f1)


class TestBenchKernelAB:
    def test_metric_id(self):
        import importlib.util

        bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        spec = importlib.util.spec_from_file_location("_bench_kab", bench_path)
        bench = importlib.util.module_from_spec(spec)
        old = sys.argv
        try:
            sys.argv = ["bench.py", "--kernel-ab"]
            spec.loader.exec_module(bench)
            assert bench._metric_id() == (
                "fused_kernel_real_contexts_per_sec", "contexts/sec"
            )
        finally:
            sys.argv = old

    def test_end_to_end_cpu_interpret_record(self, tmp_path):
        # --kernel-ab on CPU: an HONEST interpret-mode record, not a crash.
        # Second invocation with the same shapes: zero autotune timing runs
        # (every schedule from the persisted cache).
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            # this test pins the LEGACY interpret-mode record regardless of
            # the ambient backend (the CI kernel-portability job runs the
            # suite with C2V_KERNEL_BACKEND=cpu)
            C2V_KERNEL_BACKEND="interpret",
            BENCH_SUPERVISED="1",
            BENCH_BATCH="8",
            BENCH_BAG="16",
            BENCH_AB_STEPS="2",
            BENCH_EMBED="4",
            BENCH_ENCODE="8",
            BENCH_AB_TERMINALS="200",
            BENCH_AB_PATHS="150",
            BENCH_AB_LABELS="20",
            BENCH_AB_REPEATS="1",
            BENCH_AUTOTUNE_CACHE=str(tmp_path / "sched.json"),
        )
        bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")

        def run():
            proc = subprocess.run(
                [sys.executable, bench_path, "--kernel-ab", "--autotune", "--dry"],
                env=env, capture_output=True, text=True, timeout=540,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            metric = json.loads(proc.stdout.strip().splitlines()[-1])
            detail = None
            for line in proc.stderr.splitlines():
                line = line.strip()
                if line.startswith("{") and '"detail"' in line:
                    detail = json.loads(line)["detail"]
            return metric, detail

        metric, detail = run()
        assert metric["metric"] == "fused_kernel_real_contexts_per_sec"
        assert metric["value"] and metric["value"] > 0
        assert detail["interpret"] is True and "note" in detail
        for arm in ("xla_f32", "pool_only_f32", "fused_f32",
                    "pool_only_int8", "fused_int8"):
            assert detail["arms"][arm]["real_contexts_per_sec"] > 0
        assert detail["autotune"]["counters_delta"]["autotune_schedule_stored"] == 2

        metric2, detail2 = run()
        delta = detail2["autotune"]["counters_delta"]
        assert delta["autotune_timing_run"] == 0
        assert delta["autotune_cache_miss"] == 0
        assert delta["autotune_cache_hit"] == 2

    def test_end_to_end_compiled_cpu_record(self):
        # --kernel-ab with the compiled CPU strategy pinned: no Pallas
        # interpreter anywhere in the main arms (interpret false, no
        # apologetic note), the resolved strategy in the record, the two
        # *_interp comparison arms quantifying compiled-vs-interpret at
        # equal real-context work, and zero post-warmup recompiles.
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            C2V_KERNEL_BACKEND="cpu",
            BENCH_SUPERVISED="1",
            BENCH_BATCH="8",
            BENCH_BAG="16",
            BENCH_AB_STEPS="2",
            BENCH_EMBED="4",
            BENCH_ENCODE="8",
            BENCH_AB_TERMINALS="200",
            BENCH_AB_PATHS="150",
            BENCH_AB_LABELS="20",
            BENCH_AB_REPEATS="1",
        )
        bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        proc = subprocess.run(
            [sys.executable, bench_path, "--kernel-ab"],
            env=env, capture_output=True, text=True, timeout=540,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        metric = json.loads(proc.stdout.strip().splitlines()[-1])
        detail = None
        for line in proc.stderr.splitlines():
            line = line.strip()
            if line.startswith("{") and '"detail"' in line:
                detail = json.loads(line)["detail"]
        assert metric["value"] and metric["value"] > 0
        assert detail["strategy"] == "cpu"
        assert detail["interpret"] is False and "note" not in detail
        assert detail["post_warmup_recompiles"] == 0
        fused = detail["arms"]["fused_f32"]["kernel"]
        assert fused["backend"] == "auto" and fused["strategy"] == "cpu"
        interp = detail["arms"]["fused_f32_interp"]["kernel"]
        assert interp["strategy"] == "pallas_tpu:interpret"
        cvi = detail["speedup_compiled_vs_interpret"]
        # equal work, different lowering: the compiled strategy must win
        # (the fused arm's interpreter penalty is large even at toy shapes)
        assert cvi["fused_f32"] > 1.0
        assert cvi["pool_only_f32"] > 0
