"""Scale-out data paths: streaming epochs (bounded host RSS) and
host-sharded corpus loading (multi-host pods, SURVEY §7.4 / BASELINE
config 3-4). Multi-process behavior is exercised by simulating hosts with
explicit (index, count) shards in one process — the pure mapping and
assembly logic is identical.
"""

import numpy as np
import pytest

from code2vec_tpu.data import pipeline as pipeline_mod
from code2vec_tpu.data.pipeline import (
    build_epoch,
    iter_batches,
    iter_streaming_batches,
    pad_batch_stream,
    split_items,
)
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_scale")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"], cache=False
    )
    return paths, data


class TestStreamingEpochs:
    def _builder(self, data, bag, rng):
        def build(idx):
            return build_epoch(data, idx, bag, rng)

        return build

    def test_covers_every_item_exactly_once(self, tiny):
        _, data = tiny
        rng = np.random.default_rng(0)
        idx = np.arange(data.n_items)
        seen = []
        for batch in iter_streaming_batches(
            self._builder(data, 16, rng), idx, batch_size=8, rng=rng,
            chunk_items=10,
        ):
            valid = batch["example_mask"].astype(bool)
            seen.extend(batch["ids"][valid].tolist())
        assert sorted(seen) == sorted(data.ids[idx].tolist())

    def test_static_shapes_and_padding(self, tiny):
        _, data = tiny
        rng = np.random.default_rng(1)
        idx = np.arange(data.n_items)
        batches = list(
            iter_streaming_batches(
                self._builder(data, 16, rng), idx, batch_size=8, rng=rng,
                chunk_items=7,
            )
        )
        assert all(b["starts"].shape == (8, 16) for b in batches)
        n_valid = int(sum(b["example_mask"].sum() for b in batches))
        assert n_valid == len(idx)
        # every batch except possibly the last is full
        assert all(
            b["example_mask"].all() for b in batches[:-1]
        )

    def test_chunks_bound_materialization(self, tiny, monkeypatch):
        """No epoch_builder call may see more items than chunk_items — the
        memory bound the streaming path exists to provide."""
        _, data = tiny
        rng = np.random.default_rng(2)
        idx = np.arange(data.n_items)
        sizes = []

        def spy_builder(chunk_idx):
            sizes.append(len(chunk_idx))
            return build_epoch(data, chunk_idx, 16, rng)

        for _ in iter_streaming_batches(
            spy_builder, idx, batch_size=8, rng=rng, chunk_items=10
        ):
            pass
        assert sizes and max(sizes) <= 10

    def test_matches_iter_batches_multiset(self, tiny):
        """Same item set, same static shapes, same number of valid rows as
        the materializing path (orders differ: the stream shuffles items,
        iter_batches shuffles rows)."""
        _, data = tiny
        idx = np.arange(data.n_items)
        bag = int(np.diff(data.row_splits).max())  # no subsampling
        rng_a = np.random.default_rng(3)
        epoch = build_epoch(data, idx, bag, rng_a)
        mat = list(iter_batches(epoch, 8, rng=rng_a, pad_final=True))

        rng_b = np.random.default_rng(3)
        stream = list(
            iter_streaming_batches(
                lambda i: build_epoch(data, i, bag, rng_b), idx, 8, rng_b,
                chunk_items=9,
            )
        )
        assert len(mat) == len(stream)

        def signature(batches):
            # multiset of (label, sorted context triples) over valid rows
            out = []
            for b in batches:
                for r in np.nonzero(b["example_mask"])[0]:
                    trip = sorted(
                        zip(
                            b["starts"][r].tolist(),
                            b["paths"][r].tolist(),
                            b["ends"][r].tolist(),
                        )
                    )
                    out.append((int(b["labels"][r]), tuple(trip)))
            return sorted(out)

        assert signature(mat) == signature(stream)

    def test_variable_task_expansion_across_chunks(self, tiny):
        """The carry buffer must absorb variable-task expansion (chunks
        yield MORE examples than items) without dropping or duplicating."""
        paths, _ = tiny
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            infer_method=True, infer_variable=True, cache=False,
        )
        rng = np.random.default_rng(0)
        idx = np.arange(data.n_items)
        full = build_epoch(data, idx, 16, np.random.default_rng(1))
        assert len(full) > data.n_items  # expansion really happened
        rng2 = np.random.default_rng(2)
        stream_valid = 0
        labels = []
        for batch in iter_streaming_batches(
            lambda i: build_epoch(data, i, 16, rng2), idx, batch_size=8,
            rng=rng, chunk_items=5,
        ):
            valid = batch["example_mask"].astype(bool)
            stream_valid += int(valid.sum())
            labels.extend(batch["labels"][valid].tolist())
        assert stream_valid == len(full)  # method + variable examples
        # same multiset of labels as the materialized epoch
        assert sorted(labels) == sorted(full.labels.tolist())

    def test_end_to_end_training(self, tiny):
        _, data = tiny
        config = TrainConfig(
            max_epoch=2,
            batch_size=16,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            stream_chunk_items=16,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])


class TestPadBatchStream:
    def test_pads_to_step_count_with_masked_templates(self):
        from code2vec_tpu.data.pipeline import empty_batch

        template = empty_batch(2, 4)
        batches = [
            {"labels": np.array([1, 2]), "example_mask": np.ones(2, np.float32)}
        ]
        out = list(pad_batch_stream(iter(batches), 3, template))
        assert len(out) == 3
        assert out[0]["example_mask"].sum() == 2
        assert out[1]["example_mask"].sum() == 0
        assert out[2]["example_mask"].sum() == 0

    def test_empty_stream_yields_only_templates(self):
        from code2vec_tpu.data.pipeline import empty_batch

        template = empty_batch(2, 4)
        out = list(pad_batch_stream(iter([]), 2, template))
        assert len(out) == 2
        assert all(b["example_mask"].sum() == 0 for b in out)
        assert all(b["starts"].shape == (2, 4) for b in out)


class TestHostShardedLoading:
    N_HOSTS = 4

    def _load_shards(self, paths):
        return [
            load_corpus(
                paths["corpus"], paths["path_idx"], paths["terminal_idx"],
                cache=False, shard=(i, self.N_HOSTS),
            )
            for i in range(self.N_HOSTS)
        ]

    @pytest.mark.parametrize("native", [True, False])
    def test_shards_partition_the_corpus(self, tiny, native):
        paths, full = tiny
        shards = [
            load_corpus(
                paths["corpus"], paths["path_idx"], paths["terminal_idx"],
                cache=False, shard=(i, self.N_HOSTS), native=native,
            )
            for i in range(self.N_HOSTS)
        ]
        assert sum(s.n_items for s in shards) == full.n_items
        assert sum(s.n_contexts for s in shards) == full.n_contexts
        for i, s in enumerate(shards):
            assert s.global_n_items == full.n_items
            # round-robin: shard i holds global rows i, i+4, i+8, ...
            np.testing.assert_array_equal(s.ids, full.ids[i :: self.N_HOSTS])
            np.testing.assert_array_equal(
                s.labels, full.labels[i :: self.N_HOSTS]
            )
            # context rows intact per method
            for local in range(min(s.n_items, 5)):
                g = i + local * self.N_HOSTS
                np.testing.assert_array_equal(
                    s.starts[s.row_splits[local] : s.row_splits[local + 1]],
                    full.starts[full.row_splits[g] : full.row_splits[g + 1]],
                )

    def test_label_vocab_is_global_and_identical(self, tiny):
        paths, full = tiny
        shards = self._load_shards(paths)
        for s in shards:
            assert s.label_vocab.stoi == full.label_vocab.stoi

    def test_global_local_mapping_roundtrip(self, tiny):
        paths, full = tiny
        shards = self._load_shards(paths)
        rng = np.random.default_rng(0)
        global_train, global_test = split_items(full.n_items, rng)
        covered = []
        for s in shards:
            local = s.local_rows_of_global(global_train)
            covered.extend(s.global_of_local(local).tolist())
        assert sorted(covered) == sorted(global_train.tolist())

    def test_sharded_training_runs(self, tiny):
        """Single-process sanity: a shard-loaded corpus trains end to end
        (the degenerate 1-process case of pod feeding)."""
        paths, _ = tiny
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            cache=False, shard=(1, 2),
        )
        config = TrainConfig(
            max_epoch=2,
            batch_size=8,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])

    def test_sharded_cache_roundtrip(self, tiny, tmp_path):
        import shutil

        paths, _ = tiny
        local = {
            k: shutil.copy(str(v), tmp_path / f"{k}.txt")
            for k, v in paths.items()
        }
        kw = dict(cache=True, shard=(2, self.N_HOSTS))
        cold = load_corpus(
            local["corpus"], local["path_idx"], local["terminal_idx"], **kw
        )
        warm = load_corpus(
            local["corpus"], local["path_idx"], local["terminal_idx"], **kw
        )
        assert warm.shard == (2, self.N_HOSTS)
        assert warm.global_n_items == cold.global_n_items
        np.testing.assert_array_equal(cold.starts, warm.starts)
        np.testing.assert_array_equal(cold.row_splits, warm.row_splits)
