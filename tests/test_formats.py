"""Golden tests for the L1 artifact contract (SURVEY.md §2.4)."""

import numpy as np
import pytest

from code2vec_tpu import PAD_NAME, QUESTION_TOKEN_INDEX, QUESTION_TOKEN_NAME
from code2vec_tpu.formats import (
    CorpusRecord,
    iter_corpus_records,
    read_code_vectors,
    read_corpus,
    read_params,
    read_vocab,
    write_code_vectors_header,
    append_code_vectors,
    write_params,
)
from code2vec_tpu.formats.corpus_io import write_corpus
from code2vec_tpu.formats.vocab_io import write_vocab_from_names

GOLDEN_CORPUS = """#1
label:getValue
class:src/Foo.java
paths:
3\t7\t4
5\t2\t3
vars:
counter\t@var_0
name\t@var_1

#2
label:setCount_2
class:src/Bar.java
doc:some javadoc
paths:
1\t9\t2
vars:

"""


class TestVocabIO:
    def test_round_trip_with_pad(self, tmp_path):
        p = tmp_path / "terminal_idxs.txt"
        write_vocab_from_names(p, ["@method_0", "int", "@var_0"])
        vocab = read_vocab(p)
        assert vocab.stoi[PAD_NAME] == 0
        assert vocab.stoi["@method_0"] == 1
        assert vocab.stoi["@var_0"] == 3
        assert len(vocab) == 4

    def test_extra_token_shift(self, tmp_path):
        # @question injection shifts every file index > 0 by one
        # (reference: model/dataset_reader.py:22-41).
        p = tmp_path / "terminal_idxs.txt"
        write_vocab_from_names(p, ["@method_0", "int"])
        vocab = read_vocab(p, extra_tokens=[QUESTION_TOKEN_NAME])
        assert vocab.stoi[PAD_NAME] == 0
        assert vocab.stoi[QUESTION_TOKEN_NAME] == QUESTION_TOKEN_INDEX == 1
        assert vocab.stoi["@method_0"] == 2
        assert vocab.stoi["int"] == 3

    def test_blank_name_tolerated(self, tmp_path):
        p = tmp_path / "path_idxs.txt"
        p.write_text("0\t<PAD/>\n1\t\n2\tSimpleName^MethodCallExpr\n")
        vocab = read_vocab(p)
        assert vocab.itos[1] == ""
        assert len(vocab) == 3

    def test_real_reference_vocab_file(self):
        # The reference ships dataset/terminal_idxs.txt — parse it for real.
        vocab = read_vocab(
            "/root/reference/dataset/terminal_idxs.txt",
            extra_tokens=[QUESTION_TOKEN_NAME],
        )
        assert vocab.stoi[PAD_NAME] == 0
        assert vocab.stoi[QUESTION_TOKEN_NAME] == 1
        # file line "1\t@method_0" shifts to 2
        assert vocab.stoi["@method_0"] == 2
        assert len(vocab) == 11951  # 11950 file entries + @question


class TestCorpusIO:
    def test_parse_golden(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text(GOLDEN_CORPUS)
        records = read_corpus(p)
        assert len(records) == 2
        r1, r2 = records
        assert r1.id == 1
        assert r1.label == "getValue"
        assert r1.source == "src/Foo.java"
        assert r1.path_contexts == [(3, 7, 4), (5, 2, 3)]
        assert r1.aliases == [("counter", "@var_0"), ("name", "@var_1")]
        assert r2.doc == "some javadoc"
        assert r2.path_contexts == [(1, 9, 2)]
        assert r2.aliases == []

    def test_missing_trailing_blank(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("#5\nlabel:run\npaths:\n1\t1\t1")  # no trailing newline
        records = read_corpus(p)
        assert len(records) == 1 and records[0].id == 5

    def test_round_trip(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text(GOLDEN_CORPUS)
        records = read_corpus(p)
        p2 = tmp_path / "corpus2.txt"
        write_corpus(p2, records)
        assert read_corpus(p2) == records

    def test_streaming_matches_batch(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text(GOLDEN_CORPUS)
        assert list(iter_corpus_records(p)) == read_corpus(p)


class TestParamsIO:
    def test_round_trip(self, tmp_path):
        p = tmp_path / "params.txt"
        write_params(p, {"max_length": 8, "max_width": 3, "method_count": 42})
        assert read_params(p) == {
            "max_length": "8",
            "max_width": "3",
            "method_count": "42",
        }

    def test_real_reference_params(self):
        params = read_params("/root/reference/dataset/params.txt")
        assert params["max_length"] == "8"
        assert params["max_width"] == "3"


class TestVectorsIO:
    def test_round_trip(self, tmp_path):
        p = tmp_path / "code.vec"
        write_code_vectors_header(p, 2, 3)
        vecs = np.array([[1.0, 2.5, -3.0], [0.0, 0.5, 9.0]], np.float32)
        append_code_vectors(p, ["getvalue", "setcount"], vecs)
        labels, arr = read_code_vectors(p)
        assert labels == ["getvalue", "setcount"]
        np.testing.assert_allclose(arr, vecs)
