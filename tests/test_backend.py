"""Multi-backend kernel portability (ops/backend.py — ISSUE 19).

Covers the shared resolver (precedence: explicit interpret > explicit
backend > C2V_KERNEL_BACKEND env > device auto), the compiled CPU
strategy's bitwise parity against the interpret-mode Pallas reference
for both hot kernels (fused encode-pool and the ANN LUT), golden-request
parity at the model level under the PR-12 GoldenSet tolerance rules
(embeddings bitwise, logits within reduction-order tolerance), mesh-path
parity on the 8-device harness, and the autotune cache's backend axis
(round-trip + pre-backend entry deserialization).

The suite runs with NO reliance on the conftest interpret pin: every
test that cares about the env sets it explicitly via monkeypatch.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops import backend as kb

ON_GPU = jax.default_backend() == "gpu"


# ---------------------------------------------------------------------------
# resolver units
# ---------------------------------------------------------------------------
class TestResolver:
    def test_device_auto_on_cpu(self, monkeypatch):
        monkeypatch.delenv(kb.ENV_VAR, raising=False)
        bs = kb.resolve()
        assert (bs.backend, bs.strategy, bs.interpret) == ("cpu", "cpu", False)
        assert bs.label == "cpu"

    @pytest.mark.parametrize(
        "env,expect",
        [
            ("cpu", ("cpu", "cpu", False)),
            ("gpu", ("gpu", "pallas_gpu", True)),  # off-GPU -> interpreter
            ("tpu", ("tpu", "pallas_tpu", True)),  # off-TPU -> interpreter
            ("interpret", ("cpu", "pallas_tpu", True)),
        ],
    )
    def test_env_resolution(self, monkeypatch, env, expect):
        monkeypatch.setenv(kb.ENV_VAR, env)
        bs = kb.resolve()
        assert (bs.backend, bs.strategy, bs.interpret) == expect

    def test_explicit_backend_beats_env(self, monkeypatch):
        monkeypatch.setenv(kb.ENV_VAR, "cpu")
        assert kb.resolve(backend="interpret").interpret is True
        assert kb.resolve(backend="gpu").strategy == "pallas_gpu"

    def test_legacy_interpret_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv(kb.ENV_VAR, "cpu")
        bs = kb.resolve(interpret=True)
        assert bs.strategy == "pallas_tpu" and bs.interpret is True
        # interpret=False compiles for the device actually present
        bs = kb.resolve(interpret=False)
        assert bs.interpret is False
        assert bs.strategy == ("pallas_gpu" if ON_GPU else "cpu")

    def test_explicit_backend_with_interpret_override(self, monkeypatch):
        monkeypatch.delenv(kb.ENV_VAR, raising=False)
        bs = kb.resolve(backend="gpu", interpret=True)
        assert (bs.strategy, bs.interpret) == ("pallas_gpu", True)
        bs = kb.resolve(backend="gpu", interpret=False)
        assert (bs.strategy, bs.interpret) == ("pallas_gpu", False)
        # the cpu strategy never interprets, whatever the flag says
        assert kb.resolve(backend="cpu", interpret=True).interpret is False

    def test_unknown_backend_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="backend must be one of"):
            kb.resolve(backend="mps")
        monkeypatch.setenv(kb.ENV_VAR, "bogus")
        with pytest.raises(ValueError, match="backend must be one of"):
            kb.resolve()

    def test_label_forms(self):
        assert kb.BackendStrategy("tpu", "pallas_tpu", True).label == (
            "pallas_tpu:interpret"
        )
        assert kb.BackendStrategy("cpu", "cpu", False).label == "cpu"


# ---------------------------------------------------------------------------
# compiled CPU strategy: bitwise parity with the interpret-mode reference
# ---------------------------------------------------------------------------
def _fused_inputs(b=5, l=9, et=4, ep=6, h=8, seed=0):
    rng = np.random.default_rng(seed)
    t_table = jnp.asarray(rng.normal(size=(30, et)).astype(np.float32))
    p_table = jnp.asarray(rng.normal(size=(25, ep)).astype(np.float32))
    starts = jnp.asarray(rng.integers(1, 30, (b, l)).astype(np.int32))
    paths = jnp.asarray(rng.integers(1, 25, (b, l)).astype(np.int32))
    ends = jnp.asarray(rng.integers(1, 30, (b, l)).astype(np.int32))
    mask = jnp.asarray((rng.random((b, l)) > 0.3).astype(np.float32))
    mask = mask.at[0].set(0.0)  # a fully-masked row rides along
    kern = jnp.asarray(
        rng.normal(size=(2 * et + ep, h)).astype(np.float32) * 0.2
    )
    ln_s = jnp.asarray(rng.normal(size=h).astype(np.float32) * 0.1 + 1.0)
    ln_b = jnp.asarray(rng.normal(size=h).astype(np.float32) * 0.1)
    attn = jnp.asarray(rng.normal(size=h).astype(np.float32))
    return t_table, p_table, starts, paths, ends, mask, kern, ln_s, ln_b, attn


class TestCompiledCpuParity:
    def test_gather_split_bitwise_vs_interpreter(self):
        from code2vec_tpu.ops.fused_encode_pool import fused_encode_attend_pool

        args = _fused_inputs()
        cv_c, w_c = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="cpu"
        )
        cv_i, w_i = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="interpret"
        )
        assert np.array_equal(np.asarray(cv_c), np.asarray(cv_i))
        assert np.array_equal(np.asarray(w_c), np.asarray(w_i))

    def test_fused_impl_rewrites_to_gather_split_on_cpu(self):
        from code2vec_tpu.ops.fused_encode_pool import fused_encode_attend_pool

        args = _fused_inputs()
        cv_f, w_f = fused_encode_attend_pool(
            *args, impl="fused", block_b=2, backend="cpu"
        )
        cv_g, w_g = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="cpu"
        )
        assert np.array_equal(np.asarray(cv_f), np.asarray(cv_g))
        assert np.array_equal(np.asarray(w_f), np.asarray(w_g))

    def test_cpu_strategy_matches_xla_reference(self):
        from code2vec_tpu.ops.fused_encode_pool import (
            fused_encode_attend_pool,
            xla_reference_forward,
        )

        args = _fused_inputs()
        cv_c, w_c = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="cpu"
        )
        cv_r, w_r = xla_reference_forward(*args)
        np.testing.assert_allclose(
            np.asarray(cv_c), np.asarray(cv_r), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(w_c), np.asarray(w_r), rtol=1e-5, atol=1e-6
        )

    def test_cpu_strategy_never_enters_interpreter(self, monkeypatch):
        # the proof the serving path needs: with the interpreter made to
        # explode, the compiled CPU strategy still runs both kernels
        import jax.experimental.pallas as pl

        def boom(*a, **kw):
            if kw.get("interpret"):
                raise AssertionError("Pallas interpreter entered")
            return orig(*a, **kw)

        orig = pl.pallas_call
        from code2vec_tpu.ann import lut_kernel
        from code2vec_tpu.ops import fused_encode_pool, pallas_attention

        for mod in (fused_encode_pool, pallas_attention, lut_kernel):
            monkeypatch.setattr(mod.pl, "pallas_call", boom)
        args = _fused_inputs()
        fused_encode_pool.fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="cpu"
        )
        pallas_attention.pallas_attention_pool(
            jnp.ones((4, 8, 8)), jnp.ones((4, 8)), jnp.ones(8),
            block_b=2, backend="cpu",
        )
        lut, probed, codes, scales, bias = _lut_inputs()
        lut_kernel.lut_score_cells(
            lut, probed, codes, scales, bias, impl="pallas", backend="cpu"
        )

    def test_pool_only_bitwise_vs_interpreter(self):
        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

        rng = np.random.default_rng(1)
        ctx = jnp.asarray(rng.normal(size=(6, 10, 8)).astype(np.float32))
        mask = jnp.asarray((rng.random((6, 10)) > 0.4).astype(np.float32))
        attn = jnp.asarray(rng.normal(size=8).astype(np.float32))
        cv_c, w_c = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="cpu"
        )
        cv_i, w_i = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="interpret"
        )
        assert np.array_equal(np.asarray(cv_c), np.asarray(cv_i))
        assert np.array_equal(np.asarray(w_c), np.asarray(w_i))

    def test_grad_through_cpu_strategy(self):
        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

        rng = np.random.default_rng(2)
        ctx = jnp.asarray(rng.normal(size=(4, 8, 8)).astype(np.float32))
        mask = jnp.asarray((rng.random((4, 8)) > 0.4).astype(np.float32))
        attn = jnp.asarray(rng.normal(size=8).astype(np.float32))

        def loss(c, a, backend):
            cv, _ = pallas_attention_pool(
                c, mask, a, block_b=2, backend=backend
            )
            return jnp.sum(cv**2)

        g_ctx_c, g_attn_c = jax.grad(loss, argnums=(0, 1))(ctx, attn, "cpu")
        g_ctx_i, g_attn_i = jax.grad(loss, argnums=(0, 1))(
            ctx, attn, "interpret"
        )
        assert np.all(np.isfinite(np.asarray(g_ctx_c)))
        # the backward is shared closed-form XLA: identical across strategies
        np.testing.assert_allclose(
            np.asarray(g_ctx_c), np.asarray(g_ctx_i), rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(g_attn_c), np.asarray(g_attn_i), rtol=1e-6, atol=1e-7
        )


# ---------------------------------------------------------------------------
# golden request set: compiled CPU strategy vs interpret-mode reference at
# the model level, judged by the PR-12 GoldenSet rules (swap.py:
# embeddings bitwise, logits rtol=1e-5 atol=1e-6)
# ---------------------------------------------------------------------------
class TestGoldenRequests:
    def test_model_forward_golden_parity(self):
        from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig

        base = dict(
            terminal_count=40, path_count=35, label_count=9,
            terminal_embed_size=4, path_embed_size=6, encode_size=8,
            dropout_prob=0.0, use_pallas=True, pallas_impl="gather_split",
            pallas_block_b=2,
        )
        compiled = Code2Vec(Code2VecConfig(**base, pallas_backend="cpu"))
        reference = Code2Vec(
            Code2VecConfig(**base, pallas_backend="interpret")
        )
        rng = np.random.default_rng(3)
        key = jax.random.PRNGKey(0)
        # n_per_width requests at and just under each ladder rung — the
        # GoldenSet sweep shape (serve/swap.py)
        widths = (8, 16)
        init_s = jnp.asarray(rng.integers(1, 40, (2, 8)).astype(np.int32))
        init_p = jnp.asarray(rng.integers(1, 35, (2, 8)).astype(np.int32))
        params = compiled.init(key, init_s, init_p, init_s)
        for w in widths:
            s = rng.integers(1, 40, (4, w)).astype(np.int32)
            p = rng.integers(1, 35, (4, w)).astype(np.int32)
            e = rng.integers(1, 40, (4, w)).astype(np.int32)
            s[:, w - 2:] = 0  # requests "just under" the rung
            logits_c, cv_c, _ = compiled.apply(params, s, p, e)
            logits_r, cv_r, _ = reference.apply(params, s, p, e)
            assert np.array_equal(np.asarray(cv_c), np.asarray(cv_r)), (
                f"embeddings diverge bitwise from the interpret-mode "
                f"reference at width {w}"
            )
            np.testing.assert_allclose(
                np.asarray(logits_c), np.asarray(logits_r),
                rtol=1e-5, atol=1e-6,
            )


# ---------------------------------------------------------------------------
# mesh-path parity on the 8-device harness (SNIPPETS.md [2] pattern:
# Mesh + PartitionSpec + shard_map under jit)
# ---------------------------------------------------------------------------
class TestMeshParity:
    def _inputs(self):
        rng = np.random.default_rng(4)
        ctx = jnp.asarray(rng.normal(size=(16, 12, 8)).astype(np.float32))
        mask = jnp.asarray((rng.random((16, 12)) > 0.3).astype(np.float32))
        attn = jnp.asarray(rng.normal(size=8).astype(np.float32))
        return ctx, mask, attn

    def test_shard_map_bitwise(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

        ctx, mask, attn = self._inputs()
        ref_cv, ref_w = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="cpu"
        )
        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        fn = lambda c, m, a: pallas_attention_pool(  # noqa: E731
            c, m, a, block_b=2, backend="cpu"
        )
        sharded = shard_map(
            fn, mesh=mesh,
            in_specs=(P("data"), P("data"), P()),
            out_specs=(P("data"), P("data")),
            check_rep=False,  # custom_partitioning has no replication rule
        )
        with mesh:
            cv, w = jax.jit(sharded)(ctx, mask, attn)
        assert np.array_equal(np.asarray(cv), np.asarray(ref_cv))
        assert np.array_equal(np.asarray(w), np.asarray(ref_w))

    def test_custom_partitioning_bitwise(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

        ctx, mask, attn = self._inputs()
        ref_cv, ref_w = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="cpu"
        )
        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        cs = jax.device_put(ctx, NamedSharding(mesh, P("data")))
        ms = jax.device_put(mask, NamedSharding(mesh, P("data")))
        As = jax.device_put(attn, NamedSharding(mesh, P()))
        cv, w = jax.jit(
            lambda c, m, a: pallas_attention_pool(
                c, m, a, block_b=2, backend="cpu"
            )
        )(cs, ms, As)
        assert np.array_equal(np.asarray(cv), np.asarray(ref_cv))
        assert np.array_equal(np.asarray(w), np.asarray(ref_w))


# ---------------------------------------------------------------------------
# ANN LUT kernel: strategy routing + GPU formulation validation
# ---------------------------------------------------------------------------
def _lut_inputs(q=3, m=4, entries=16, n_list=6, cap=8, p=2, seed=5):
    rng = np.random.default_rng(seed)
    lut = jnp.asarray(rng.normal(size=(q, m, entries)).astype(np.float32))
    probed = jnp.asarray(rng.integers(0, n_list, (q, p)).astype(np.int32))
    codes = jnp.asarray(
        rng.integers(0, entries, (n_list, cap, m)).astype(np.uint8)
    )
    scales = jnp.asarray(
        rng.random((n_list, cap)).astype(np.float32) + 0.5
    )
    bias = np.zeros((n_list, cap), np.float32)
    bias[:, cap - 1] = -np.inf  # a pad slot per cell
    return lut, probed, codes, scales, jnp.asarray(bias)


class TestLutBackends:
    def test_cpu_backend_routes_to_xla(self):
        from code2vec_tpu.ann.lut_kernel import (
            lut_score_cells,
            xla_lut_score_cells,
        )

        lut, probed, codes, scales, bias = _lut_inputs()
        got = lut_score_cells(
            lut, probed, codes, scales, bias, impl="pallas", backend="cpu"
        )
        ref = xla_lut_score_cells(lut, probed, codes, scales, bias)
        assert np.array_equal(np.asarray(got), np.asarray(ref))

    def test_gpu_formulation_validates_under_interpreter(self):
        from code2vec_tpu.ann.lut_kernel import (
            gpu_lut_score_cells,
            xla_lut_score_cells,
        )

        lut, probed, codes, scales, bias = _lut_inputs()
        got = gpu_lut_score_cells(
            lut, probed, codes, scales, bias, interpret=True
        )
        ref = xla_lut_score_cells(lut, probed, codes, scales, bias)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_gpu_backend_resolution_runs_gpu_formulation(self):
        # backend="gpu" off-GPU resolves to the GPU formulation under the
        # interpreter — CPU-only CI still validates the Triton body
        from code2vec_tpu.ann.lut_kernel import (
            lut_score_cells,
            xla_lut_score_cells,
        )

        lut, probed, codes, scales, bias = _lut_inputs()
        got = lut_score_cells(
            lut, probed, codes, scales, bias, impl="pallas", backend="gpu"
        )
        ref = xla_lut_score_cells(lut, probed, codes, scales, bias)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-6
        )


class TestGpuFormulationFused:
    def test_gather_split_gpu_formulation_under_interpreter(self):
        # the pallas_gpu lowering of gather_split (no TPU memory spaces)
        # is bitwise-identical arithmetic: validate it on CPU via the
        # interpreter against the TPU formulation
        from code2vec_tpu.ops.fused_encode_pool import fused_encode_attend_pool

        args = _fused_inputs(seed=6)
        cv_g, w_g = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="gpu"
        )
        cv_t, w_t = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="interpret"
        )
        assert np.array_equal(np.asarray(cv_g), np.asarray(cv_t))
        assert np.array_equal(np.asarray(w_g), np.asarray(w_t))

    def test_pool_gpu_formulation_under_interpreter(self):
        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

        rng = np.random.default_rng(7)
        ctx = jnp.asarray(rng.normal(size=(4, 8, 8)).astype(np.float32))
        mask = jnp.asarray((rng.random((4, 8)) > 0.4).astype(np.float32))
        attn = jnp.asarray(rng.normal(size=8).astype(np.float32))
        cv_g, w_g = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="gpu"
        )
        cv_t, w_t = pallas_attention_pool(
            ctx, mask, attn, block_b=2, backend="interpret"
        )
        assert np.array_equal(np.asarray(cv_g), np.asarray(cv_t))
        assert np.array_equal(np.asarray(w_g), np.asarray(w_t))

    @pytest.mark.skipif(not ON_GPU, reason="needs a real GPU backend")
    def test_compiled_gpu_lowering(self):
        # on actual GPU hardware the pallas_gpu strategy compiles via
        # Triton; parity against the XLA reference is the contract
        from code2vec_tpu.ops.fused_encode_pool import (
            fused_encode_attend_pool,
            xla_reference_forward,
        )

        args = _fused_inputs(seed=8)
        cv_g, w_g = fused_encode_attend_pool(
            *args, impl="gather_split", block_b=2, backend="gpu"
        )
        cv_r, w_r = xla_reference_forward(*args)
        np.testing.assert_allclose(
            np.asarray(cv_g), np.asarray(cv_r), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(w_g), np.asarray(w_r), rtol=1e-4, atol=1e-5
        )


# ---------------------------------------------------------------------------
# autotune: the backend axis on the schedule cache
# ---------------------------------------------------------------------------
class TestAutotuneBackendAxis:
    def test_kernel_schedule_roundtrip(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        cache = at.ScheduleCache(str(tmp_path / "sched.json"))
        key = at.ShapeKey("cpu", 8, 16, 4, 6, 8, "f32")
        sched = at.KernelSchedule(
            impl="gather_split", backend="cpu", source="autotune"
        )
        cache.put(key, sched, interpret=False)
        cache.save()
        reloaded = at.ScheduleCache(str(tmp_path / "sched.json")).get(key)
        assert reloaded.backend == "cpu"
        assert reloaded.impl == "gather_split"

    def test_lut_schedule_roundtrip(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        cache = at.ScheduleCache(str(tmp_path / "sched.json"))
        key = at.LutShapeKey("cpu", 4, 16, 8, 32)
        cache.put(key, at.LutSchedule(impl="xla", backend="cpu"))
        cache.save()
        reloaded = at.ScheduleCache(str(tmp_path / "sched.json")).get_lut(key)
        assert reloaded.backend == "cpu"

    def test_pre_backend_entries_deserialize(self, tmp_path):
        # old cache files have no "backend" key: they must load with the
        # "auto" default — no version bump, no migration
        from code2vec_tpu.ops import autotune as at

        key = at.ShapeKey("cpu", 8, 16, 4, 6, 8, "f32")
        old_entry = at.KernelSchedule(impl="fused").to_dict()
        del old_entry["backend"]
        path = tmp_path / "old.json"
        path.write_text(json.dumps({
            "version": 1,
            "entries": {key.cache_key(): {"schedule": old_entry}},
        }))
        sched = at.ScheduleCache(str(path)).get(key)
        assert sched.backend == "auto"
        assert sched.impl == "fused"

    def test_default_schedule_per_backend(self, monkeypatch):
        from code2vec_tpu.ops import autotune as at

        monkeypatch.setenv(kb.ENV_VAR, "cpu")
        sched = at.default_schedule()
        assert (sched.impl, sched.backend) == ("gather_split", "cpu")
        assert at.default_lut_schedule().backend == "cpu"
        monkeypatch.setenv(kb.ENV_VAR, "interpret")
        sched = at.default_schedule()
        # the interpret pin keeps the legacy default (pool_only, auto) so
        # pre-backend suites see unchanged miss-fallback behavior
        assert (sched.impl, sched.backend) == ("pool_only", "auto")
        assert at.default_lut_schedule().backend == "auto"

    def test_variant_labels_carry_backend(self):
        from code2vec_tpu.ops import autotune as at

        s = at.KernelSchedule(impl="gather_split", block_b=8, backend="cpu")
        assert at._variant_label(s).endswith("@cpu")
        assert "@" not in at._variant_label(
            at.KernelSchedule(impl="xla", backend="auto")
        )

    def test_enumerate_variants_backend_axis(self):
        from code2vec_tpu.ops import autotune as at

        cpu_variants = at.enumerate_variants(8, 16, "f32", backend="cpu")
        assert all(v.backend == "cpu" for v in cpu_variants)
        assert {v.impl for v in cpu_variants} == {"xla", "gather_split"}
        gpu_variants = at.enumerate_variants(8, 16, "f32", backend="gpu")
        assert all(v.backend == "gpu" for v in gpu_variants)
        lut_cpu = at.enumerate_lut_variants(128, backend="cpu")
        assert [v.impl for v in lut_cpu] == ["xla"]

    def test_timed_autotune_under_cpu_backend(self, tmp_path, monkeypatch):
        # a full (non-dry) search under the compiled CPU strategy stores a
        # backend-tagged winner with interpret=False in the entry
        from code2vec_tpu.ops import autotune as at

        monkeypatch.setenv(kb.ENV_VAR, "cpu")
        cache = at.ScheduleCache(str(tmp_path / "t.json"))
        keys = at.keys_for(4, [8], 4, 4, 8, ["f32"])
        schedules = at.autotune(keys, cache=cache, iters=1)
        (sched,) = schedules.values()
        assert sched.backend == "cpu"
        assert sched.source == "autotune"
        entry = cache.entries[keys[0].cache_key()]
        assert entry["interpret"] is False
        assert any("@cpu" in lbl for lbl in entry["timings_ms"])
