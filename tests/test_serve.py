"""Online serving (code2vec_tpu.serve): AOT executable ladder, continuous
micro-batcher, sharded top-k retrieval, protocol + CLI.

The load-bearing contracts pinned here:

- batched micro-batcher results are BITWISE equal to one-at-a-time
  dispatch (row-independent forward + exact-zero PAD lanes — the PR-4
  bucketing invariant carried into serving);
- a warmed server performs ZERO post-warmup compiles across a
  mixed-width request stream (the obs RecompileDetector tracks the
  engine's executable table like a jit cache);
- deadline coalescing, backpressure shedding, and graceful shutdown
  draining behave as documented;
- device top-k retrieval (single-device AND mesh-sharded) ranks
  identically to a NumPy normalize->matmul->argsort reference.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from code2vec_tpu.obs.runtime import (
    LatencyHistogram,
    RecompileDetector,
    RuntimeHealth,
)
from code2vec_tpu.serve.batcher import (
    MicroBatcher,
    ServeOverloaded,
    ServerClosed,
)
from code2vec_tpu.serve.engine import ServingEngine
from code2vec_tpu.serve.retrieval import RetrievalIndex

pytestmark = pytest.mark.serve

BAG = 16
LADDER = (4, 8, 16)
BATCH_SIZES = (1, 4)
N_TERMINALS, N_PATHS, N_LABELS = 50, 40, 6


@pytest.fixture(scope="module")
def tiny_state():
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    cfg = TrainConfig(batch_size=4, max_path_length=BAG)
    mc = Code2VecConfig(
        terminal_count=N_TERMINALS, path_count=N_PATHS, label_count=N_LABELS,
        terminal_embed_size=8, path_embed_size=8, encode_size=12,
        dropout_prob=0.0,
    )
    example = {
        "starts": np.zeros((1, BAG), np.int32),
        "paths": np.zeros((1, BAG), np.int32),
        "ends": np.zeros((1, BAG), np.int32),
        "labels": np.zeros(1, np.int32),
        "example_mask": np.ones(1, np.float32),
    }
    return create_train_state(cfg, mc, jax.random.PRNGKey(0), example)


def make_engine(tiny_state, **kw):
    kw.setdefault("max_width", BAG)
    kw.setdefault("model_dims", (8, 8, 12))
    kw.setdefault("ladder", LADDER)
    kw.setdefault("batch_sizes", BATCH_SIZES)
    kw.setdefault("health", RuntimeHealth())
    return ServingEngine(tiny_state, **kw)


@pytest.fixture(scope="module")
def engine(tiny_state):
    eng = make_engine(tiny_state)
    eng.prepare()
    return eng


def requests_of(widths, seed=0):
    """One [n, 3] mapped-context array per width, deterministic."""
    rng = np.random.default_rng(seed)
    out = []
    for n in widths:
        out.append(
            np.stack(
                [
                    rng.integers(1, N_TERMINALS, n),
                    rng.integers(1, N_PATHS, n),
                    rng.integers(1, N_TERMINALS, n),
                ],
                axis=1,
            ).astype(np.int32)
        )
    return out


# ---------------------------------------------------------------------------
# engine: AOT ladder
# ---------------------------------------------------------------------------


def test_prepare_compiles_full_ladder(engine):
    assert engine._cache_size() == len(LADDER) * len(BATCH_SIZES)
    assert len(engine.provenance) == len(LADDER) * len(BATCH_SIZES)
    for record in engine.provenance:
        assert record["batch"] in BATCH_SIZES
        assert record["width"] in LADDER
        assert record["compile_ms"] > 0
        # schedule provenance consulted per executable (cache miss here —
        # no autotune pass ran — but the record must say so explicitly)
        assert record["schedule"]["impl"]
        assert record["schedule_cached"] is False
    assert engine.post_warmup_compiles == 0


def test_width_and_batch_size_selection(engine):
    assert [engine.width_for(n) for n in (1, 4, 5, 8, 9, 16, 99)] == [
        4, 4, 8, 8, 16, 16, 16,
    ]
    assert [engine.batch_size_for(k) for k in (1, 2, 4, 7)] == [1, 4, 4, 4]


def test_prepare_is_idempotent(engine):
    before = engine._cache_size()
    engine.prepare()
    assert engine._cache_size() == before
    assert engine.post_warmup_compiles == 0


def test_off_ladder_shape_is_a_post_warmup_compile(tiny_state):
    eng = make_engine(tiny_state, ladder=(BAG,), batch_sizes=(1,))
    eng.prepare()
    det = RecompileDetector()
    det.track("serve_executables", eng, expected_compiles=eng._cache_size())
    # (2, 16) was never compiled: batch 2 is outside the (1,) size set
    ids = np.ones((2, BAG), np.int32)
    eng.run(ids, ids, ids)
    assert eng.post_warmup_compiles == 1
    assert det.check() == 1


def test_ladder_must_reach_max_width(tiny_state):
    # below max_width still rejects; ABOVE it is the longbag contract
    # (rungs raise the serveable width — tests/test_longbag.py pins it)
    with pytest.raises(ValueError, match="reach max_width"):
        make_engine(tiny_state, ladder=(4, 8))
    eng = make_engine(tiny_state, ladder=(4, 8, BAG, 128))
    assert eng.max_width == 128 and eng.base_width == BAG


def test_narrow_bag_ladder_is_never_empty():
    """A bag below derive_bucket_ladder's min_width must still yield a
    one-rung ladder (the documented 'top width is always max_contexts'
    contract) — an empty ladder crashed every padding consumer."""
    from code2vec_tpu.data.pipeline import (
        derive_bucket_ladder,
        nearest_bucket_width,
    )

    assert derive_bucket_ladder(np.asarray([1, 2, 3]), 4) == (4,)
    assert derive_bucket_ladder(np.zeros(0, np.int64), 7) == (7,)
    assert nearest_bucket_width(3, (4,)) == 4
    with pytest.raises(ValueError, match="empty"):
        nearest_bucket_width(1, ())


def test_overlong_request_rejected_at_submit(engine):
    with MicroBatcher(engine, deadline_ms=0.0, health=RuntimeHealth()) as b:
        with pytest.raises(ValueError, match="subsample before submitting"):
            b.submit(requests_of([BAG + 4])[0])


# ---------------------------------------------------------------------------
# micro-batcher: determinism, coalescing, backpressure, shutdown
# ---------------------------------------------------------------------------


def test_batched_bitwise_equals_one_at_a_time(engine):
    widths = [3, 7, 12, 5, 1, 16, 9, 2]
    reqs = requests_of(widths)
    # batched: generous deadline so concurrent submissions coalesce
    with MicroBatcher(engine, deadline_ms=250.0, health=RuntimeHealth()) as b:
        futures = [b.submit(r) for r in reqs]
        batched = [f.result(timeout=60) for f in futures]
    assert any(r.coalesced > 1 for r in batched)
    # one-at-a-time: zero deadline, sequential submission
    with MicroBatcher(engine, deadline_ms=0.0, health=RuntimeHealth()) as b:
        single = [b.submit(r).result(timeout=60) for r in reqs]
    for r in single:
        assert r.coalesced == 1
    for got, ref, n in zip(batched, single, widths):
        # bitwise: every per-row op in the forward is row-independent and
        # PAD lanes contribute exact zeros, so neither the micro-batch
        # size nor the bucket width changes a request's values
        assert np.array_equal(got.logits, ref.logits)
        assert np.array_equal(got.code_vector, ref.code_vector)
        assert np.array_equal(got.attention, ref.attention)
        assert got.n_contexts == ref.n_contexts == n


def test_zero_post_warmup_recompiles_mixed_stream(tiny_state):
    health = RuntimeHealth()
    eng = make_engine(tiny_state, health=health)
    eng.prepare()
    det = RecompileDetector()
    det.track("serve_executables", eng, expected_compiles=eng._cache_size())
    rng = np.random.default_rng(7)
    widths = rng.integers(1, BAG + 1, 100).tolist()
    with MicroBatcher(eng, deadline_ms=1.0, health=health) as b:
        futures = [b.submit(r) for r in requests_of(widths, seed=7)]
        for f in futures:
            f.result(timeout=120)
    assert det.check() == 0
    assert eng.post_warmup_compiles == 0
    snap = health.snapshot()
    assert snap["counters"]["serve_requests"] == 100
    assert snap["latencies_ms"]["serve.e2e_ms"]["count"] == 100


def test_deadline_coalesces_and_single_request_falls_back(engine):
    health = RuntimeHealth()
    with MicroBatcher(engine, deadline_ms=500.0, health=health) as b:
        futures = [b.submit(r) for r in requests_of([3, 5, 7])]
        results = [f.result(timeout=60) for f in futures]
    # all three arrived well inside the window: one device call
    assert {r.coalesced for r in results} == {3}
    assert {r.batch for r in results} == {4}
    with MicroBatcher(engine, deadline_ms=0.0, health=health) as b:
        r = b.submit(requests_of([5])[0]).result(timeout=60)
    # low-load fallback: a lone request dispatches alone at batch size 1
    assert r.coalesced == 1 and r.batch == 1


class _GatedEngine:
    """Engine stub whose device call blocks until released — makes queue
    states deterministic for backpressure/shutdown tests."""

    def __init__(self, inner):
        self._inner = inner
        self.gate = threading.Event()
        self.batch_sizes = inner.batch_sizes

    def observe_width(self, n):
        self._inner.observe_width(n)

    def pad_requests(self, contexts):
        return self._inner.pad_requests(contexts)

    def run(self, starts, paths, ends):
        assert self.gate.wait(timeout=60), "gate never released"
        return self._inner.run(starts, paths, ends)


def test_backpressure_rejects_when_pending_full(engine):
    gated = _GatedEngine(engine)
    b = MicroBatcher(gated, deadline_ms=0.0, max_pending=2,
                     health=RuntimeHealth())
    try:
        first = b.submit(requests_of([3])[0])  # dequeued, blocks on gate
        time.sleep(0.2)  # let the batcher pull it off the queue
        queued = [b.submit(r) for r in requests_of([4, 5])]  # fills pending
        with pytest.raises(ServeOverloaded, match="queue is full"):
            b.submit(requests_of([6])[0])
        gated.gate.set()
        for f in [first, *queued]:
            assert f.result(timeout=60).n_contexts > 0
    finally:
        gated.gate.set()
        b.close()


@pytest.mark.usefixtures("zero_leaked_handles")
def test_graceful_shutdown_drains_in_flight(engine):
    gated = _GatedEngine(engine)
    b = MicroBatcher(gated, deadline_ms=0.0, max_pending=16,
                     health=RuntimeHealth())
    futures = [b.submit(r) for r in requests_of([3, 9, 14, 2, 6])]
    closer = threading.Thread(target=b.close)
    closer.start()
    time.sleep(0.2)
    gated.gate.set()  # release the device while close() is draining
    closer.join(timeout=60)
    assert not closer.is_alive()
    for f in futures:  # every accepted request resolved before close returned
        assert f.done()
        assert f.result().n_contexts > 0
    with pytest.raises(ServerClosed):
        b.submit(requests_of([3])[0])


def test_close_drains_request_enqueued_in_poll_gap(engine):
    """Regression (fleet eviction path): a request accepted just as the
    batcher thread's idle poll times out and close() flips the flag must
    still be SERVED by the final drain — not failed by close()'s sweep.
    The race is forced deterministically: a queue whose timeout-ful get
    claims to be empty, so the loop can only see the item through the
    post-closed get_nowait drain."""
    import queue as _queue

    class RacyQueue(_queue.Queue):
        force_empty = False

        def get(self, block=True, timeout=None):
            if self.force_empty and timeout is not None:
                raise _queue.Empty
            return super().get(block, timeout)

    b = MicroBatcher(engine, deadline_ms=0.0, health=RuntimeHealth())
    racy = RacyQueue(maxsize=256)
    racy.force_empty = True  # the polling loop never sees the item
    b._queue = racy
    future = b.submit(requests_of([5])[0])
    b.close()
    result = future.result(timeout=30)  # old code: ServerClosed here
    assert result.n_contexts == 5
    assert np.isfinite(result.code_vector).all()


def test_queue_depth_gauge_exported(engine):
    health = RuntimeHealth()
    with MicroBatcher(engine, deadline_ms=0.0, health=health) as b:
        b.submit(requests_of([4])[0]).result(timeout=60)
    gauges = health.snapshot()["gauges"]
    assert "serve_queue_depth" in gauges  # one obs schema, no ad-hoc state
    assert gauges["serve_queue_depth"] == 0  # drained


def test_engine_errors_propagate_to_futures(engine):
    class _Exploding(_GatedEngine):
        def run(self, *a):
            raise RuntimeError("device on fire")

    b = MicroBatcher(_Exploding(engine), deadline_ms=0.0,
                     health=RuntimeHealth())
    try:
        f = b.submit(requests_of([3])[0])
        with pytest.raises(RuntimeError, match="device on fire"):
            f.result(timeout=60)
    finally:
        b.close()


# ---------------------------------------------------------------------------
# histogram fallback: no recorded ladder
# ---------------------------------------------------------------------------


def test_request_histogram_freezes_fallback_ladder(tiny_state):
    health = RuntimeHealth()
    eng = make_engine(
        tiny_state, ladder=None, warmup_requests=8, health=health
    )
    eng.prepare()
    assert eng.active_ladder == (BAG,)  # top width only until frozen
    pre_freeze = eng._cache_size()
    widths = [2, 3, 2, 4, 3, 2, 16, 3, 2, 4]
    with MicroBatcher(eng, deadline_ms=0.0, health=health) as b:
        for f in [b.submit(r) for r in requests_of(widths)]:
            f.result(timeout=60)
    assert eng.ladder is not None
    assert eng.ladder[-1] == BAG
    assert len(eng.ladder) > 1  # the skewed stream earned a narrow rung
    assert eng._cache_size() > pre_freeze
    # the freeze itself is warmup, not churn
    assert eng.post_warmup_compiles == 0


# ---------------------------------------------------------------------------
# retrieval: parity vs NumPy argsort
# ---------------------------------------------------------------------------


def _np_reference(labels, rows, query, k):
    unit = rows.astype(np.float32) / np.maximum(
        np.linalg.norm(rows.astype(np.float32), axis=1, keepdims=True), 1e-12
    )
    q = query.astype(np.float32)
    q = q / max(np.linalg.norm(q), 1e-12)
    sims = unit @ q
    order = np.argsort(-sims)[:k]
    return [(labels[int(i)], float(sims[i])) for i in order]


def test_topk_matches_numpy_reference():
    rng = np.random.default_rng(11)
    labels = [f"method_{i}" for i in range(57)]
    rows = rng.normal(size=(57, 12)).astype(np.float32)
    index = RetrievalIndex(labels, rows)
    for seed in range(5):
        q = np.random.default_rng(seed).normal(size=12).astype(np.float32)
        got = index.top_k(q, 7)
        ref = _np_reference(labels, rows, q, 7)
        assert [n for n, _ in got] == [n for n, _ in ref]
        assert np.allclose([s for _, s in got], [s for _, s in ref], atol=1e-5)


def test_topk_sharded_matches_numpy_reference():
    from code2vec_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 on CPU)")
    mesh = make_mesh(data=1, model=4, ctx=1, devices=jax.devices()[:4])
    rng = np.random.default_rng(13)
    labels = [f"m{i}" for i in range(50)]  # 50 % 4 != 0: exercises padding
    rows = rng.normal(size=(50, 8)).astype(np.float32)
    index = RetrievalIndex(labels, rows, mesh=mesh)
    q = rng.normal(size=8).astype(np.float32)
    got = index.top_k(q, 5)
    ref = _np_reference(labels, rows, q, 5)
    assert [n for n, _ in got] == [n for n, _ in ref]
    assert np.allclose([s for _, s in got], [s for _, s in ref], atol=1e-5)
    # pad rows must never surface, even when k spans the whole index
    everything = index.top_k(q, 50)
    assert len(everything) == 50
    assert {n for n, _ in everything} == set(labels)


def test_topk_batch_and_k_clamp():
    labels = ["a", "b", "c"]
    rows = np.eye(3, dtype=np.float32)
    index = RetrievalIndex(labels, rows)
    results = index.top_k_batch(np.eye(3, dtype=np.float32), k=10)
    assert [r[0][0] for r in results] == ["a", "b", "c"]
    assert all(len(r) == 3 for r in results)  # k clamped to n


def test_topk_compiles_bounded_by_k_buckets():
    """A client sweeping top_k must not compile one query fn per distinct
    k on the request path — k rounds up to a power-of-two bucket and the
    results slice back, so compiles are bounded by log2(n)."""
    rng = np.random.default_rng(5)
    labels = [f"m{i}" for i in range(57)]
    rows = rng.normal(size=(57, 8)).astype(np.float32)
    index = RetrievalIndex(labels, rows)
    q = rng.normal(size=8).astype(np.float32)
    for k in range(1, 20):
        got = index.top_k(q, k)
        assert len(got) == k
        assert [n for n, _ in got] == [
            n for n, _ in _np_reference(labels, rows, q, k)
        ]
    # k 1..19 spans buckets {1, 2, 4, 8, 16, 32}: six compiles, not 19
    assert index._cache_size() <= 6


def test_topk_compiles_bounded_by_query_batch_buckets():
    """Regression (ISSUE 11 satellite): the executable table also buckets
    by QUERY-BATCH size. A client alternating single and batched neighbor
    queries used to pay one hidden jit retrace per distinct Q while
    `_cache_size` (keyed by k alone) reported no growth — now Q pads to a
    power-of-two bucket, each table entry compiles exactly once, and the
    probe is exact."""
    from code2vec_tpu.obs.runtime import RecompileDetector, RuntimeHealth

    rng = np.random.default_rng(6)
    labels = [f"m{i}" for i in range(40)]
    rows = rng.normal(size=(40, 8)).astype(np.float32)
    index = RetrievalIndex(labels, rows)
    for n_q in (1, 3, 1, 5, 2, 9, 4, 1, 7):
        results = index.top_k_batch(
            rng.normal(size=(n_q, 8)).astype(np.float32), 5
        )
        assert len(results) == n_q  # padded rows never surface
    # Q 1..9 spans buckets {1, 2, 4, 8, 16} at one k bucket: five entries
    assert index._cache_size() <= 5
    # and repeats of the same shapes are zero-recompile (detector-visible)
    det = RecompileDetector(health=RuntimeHealth())
    det.track("retrieval_query_fns", index)
    det.check()
    for n_q in (1, 3, 5, 9, 2):
        index.top_k_batch(rng.normal(size=(n_q, 8)).astype(np.float32), 5)
    assert det.check() == 0
    # batched results rank identically to one-at-a-time queries (sims
    # approx: the padded matmul may tile its reduction differently)
    batch = rng.normal(size=(3, 8)).astype(np.float32)
    batched = index.top_k_batch(batch, 4)
    singles = [index.top_k(batch[i], 4) for i in range(3)]
    assert [[n for n, _ in row] for row in batched] == [
        [n for n, _ in row] for row in singles
    ]
    for b_row, s_row in zip(batched, singles):
        assert np.allclose(
            [s for _, s in b_row], [s for _, s in s_row], atol=1e-5
        )


# ---------------------------------------------------------------------------
# obs: latency histogram
# ---------------------------------------------------------------------------


def test_latency_histogram_percentiles():
    hist = LatencyHistogram()
    for v in range(1, 101):  # 1..100 ms
        hist.record(float(v))
    s = hist.summary()
    assert s["count"] == 100
    assert s["p50_ms"] == pytest.approx(50, abs=1)
    assert s["p99_ms"] == pytest.approx(99, abs=1)
    assert s["max_ms"] == 100
    assert LatencyHistogram().summary() is None


def test_latency_histogram_bounded():
    hist = LatencyHistogram(max_samples=10)
    for v in range(100):
        hist.record(float(v))
    assert hist.count == 100
    assert len(hist._samples) == 10


def test_latency_histogram_window_evicts_oldest():
    """Past the cap the buffer is a sliding window: a cold-start outlier
    must leave after exactly max_samples further records, not 2x."""
    hist = LatencyHistogram(max_samples=4)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0):
        hist.record(v)
    assert sorted(hist._samples) == [5.0, 6.0, 7.0, 8.0]


# ---------------------------------------------------------------------------
# predictor: ladder-aware padding (the repeat-prediction executable reuse)
# ---------------------------------------------------------------------------

PY = """
def add(a, b):
    total = a + b
    return total


def mul(a, b):
    product = a * b
    return product


def is_even(n):
    even = n % 2 == 0
    return even
"""


@pytest.fixture(scope="module")
def trained_py(tmp_path_factory):
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.export import export_from_checkpoint
    from code2vec_tpu.pyextract import extract_python_dataset
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.loop import train

    root = tmp_path_factory.mktemp("serve_py")
    src, ds, out = root / "src", root / "ds", root / "out"
    for d in (src, ds, out):
        d.mkdir()
    (src / "util.py").write_text(PY)
    extract_python_dataset(str(ds), str(src), [("util.py", "*")])
    data = load_corpus(
        ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
    )
    cfg = TrainConfig(
        max_epoch=20, batch_size=2, encode_size=32, terminal_embed_size=16,
        path_embed_size=16, max_path_length=64, lr=0.01, print_sample_cycle=0,
    )
    train(cfg, data, out_dir=str(out))
    # exported vectors power the neighbors/search endpoint
    export_from_checkpoint(cfg, data, str(out), str(out / "code.vec"))
    return ds, out


def test_meta_records_bucket_ladder(trained_py):
    _, out = trained_py
    meta = json.loads((out / "model_meta.json").read_text())
    ladder = meta["bucket_ladder"]
    assert ladder and ladder[-1] == 64
    assert ladder == sorted(set(ladder))


def test_unrecorded_ladder_routes_server_to_histogram_fallback(trained_py):
    """An old checkpoint (no bucket_ladder in meta) must put the SERVER on
    the request-stream histogram fallback — the Predictor's geometric
    guess is for its own offline forwards only."""
    from code2vec_tpu.predict import Predictor

    ds, out = trained_py
    meta_path = out / "model_meta.json"
    original = meta_path.read_text()
    meta = json.loads(original)
    meta.pop("bucket_ladder")
    try:
        meta_path.write_text(json.dumps(meta))
        p = Predictor(str(out), str(ds / "terminal_idxs.txt"),
                      str(ds / "path_idxs.txt"))
        assert not p.ladder_recorded
        assert p.ladder  # the offline guess still exists and is non-empty
        eng = ServingEngine.from_predictor(p, health=RuntimeHealth())
        assert eng.ladder is None  # histogram fallback armed
        assert eng.active_ladder == (p.bag,)
    finally:
        meta_path.write_text(original)


def test_predictor_pads_to_ladder_not_full_bag(trained_py):
    from code2vec_tpu.predict import Predictor

    ds, out = trained_py
    p = Predictor(str(out), str(ds / "terminal_idxs.txt"),
                  str(ds / "path_idxs.txt"))
    assert p.ladder[-1] == p.bag
    results = p.predict_source(PY, "*", language="python", top_k=2)
    assert len(results) == 3
    # tiny methods pad to a narrow rung, not the 64-wide bag
    from code2vec_tpu.data.pipeline import nearest_bucket_width

    widths = {nearest_bucket_width(m.n_contexts, p.ladder) for m in results}
    assert max(widths) < p.bag
    # repeat predictions across differently-sized methods reuse at most
    # len(ladder) compiled variants of the jitted forward
    assert p._forward._cache_size() <= len(p.ladder)


# ---------------------------------------------------------------------------
# protocol: dict -> dict handling + stdio transport (no sockets)
# ---------------------------------------------------------------------------


@pytest.fixture()
def served(trained_py):
    from code2vec_tpu.serve.__main__ import build_parser, build_server

    ds, out = trained_py
    args = build_parser().parse_args([
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
    ])
    server, events = build_server(args)
    yield server
    server.close()
    if events is not None:
        events.close()


def test_server_predict_and_health(served):
    resp = served.handle({
        "op": "predict", "source": PY, "language": "python", "top_k": 3,
    })
    assert resp["ok"]
    assert len(resp["methods"]) == 3
    for m in resp["methods"]:
        assert m["n_contexts"] > 0
        assert len(m["predictions"]) == 3
        probs = [p["prob"] for p in m["predictions"]]
        assert probs == sorted(probs, reverse=True)
        assert m["timing"]["width"] in served.engine.active_ladder
    health = served.handle({"op": "health"})
    assert health["ok"]
    assert health["post_warmup_compiles"] == 0
    assert health["executables"] == len(served.engine.active_ladder) * len(
        served.engine.batch_sizes
    )
    # the retrieval block mirrors the engine's executable provenance
    assert health["retrieval"]["backend"] == "exact"
    assert health["retrieval"]["size"] == served.retrieval.n
    assert health["retrieval"]["query_executables"] >= 0


def test_server_neighbors_from_source(served):
    resp = served.handle({
        "op": "neighbors", "source": PY, "language": "python",
        "method_name": "add", "top_k": 3,
    })
    assert resp["ok"]
    (m,) = resp["methods"]
    assert len(m["neighbors"]) == 3
    sims = [n["similarity"] for n in m["neighbors"]]
    assert sims == sorted(sims, reverse=True)
    # 'add' was exported from the same checkpoint: it finds itself
    assert m["neighbors"][0]["similarity"] > 0.9


def test_server_neighbors_parity_with_numpy(served):
    q = np.random.default_rng(3).normal(
        size=served.retrieval.dim
    ).astype(np.float32)
    got = served.handle({"op": "neighbors", "vector": q.tolist(), "top_k": 4})
    # reference straight off the index's own (already-normalized) rows
    ref = _np_reference(
        served.retrieval.labels,
        np.asarray(served.retrieval._rows)[: served.retrieval.n],
        q,
        4,
    )
    assert [n["name"] for n in got["neighbors"]] == [n for n, _ in ref]


def test_server_bad_requests(served):
    assert served.handle({"op": "nope"})["error_kind"] == "bad_request"
    assert served.handle({"op": "predict"})["error_kind"] == "bad_request"
    resp = served.handle({"op": "neighbors", "vector": [1.0]})
    assert resp["error_kind"] == "bad_request"


def test_protocol_error_paths_are_structured_never_fatal(served):
    """Satellite contract: malformed JSONL, unknown op, oversized bag and
    mid-stream EOF each produce a structured error response — the worker
    process must never crash on any of them (fleet probing would read a
    crash as an eviction)."""
    from code2vec_tpu.serve.protocol import serve_stdio

    # oversized bag: the protocol normally subsamples to the bag, so the
    # batcher's loud submit-time reject is the defense line — pin that a
    # bag overflow surfaces as a structured bad_request, never an escape
    class _OversizeBatcher:
        def submit(self, arr):
            raise ValueError(
                f"request has {len(arr)} contexts, more than the model's "
                "max bag width 4; subsample before submitting"
            )

    real_batcher = served.batcher
    served.batcher = _OversizeBatcher()
    try:
        resp = served.handle(
            {"op": "embed", "source": PY, "language": "python"}
        )
    finally:
        served.batcher = real_batcher
    assert resp["error_kind"] == "bad_request"
    assert "max bag width" in resp["error"]

    in_lines = [
        '{"op": "health", "id": 1}\n',
        "{not json at all\n",
        '{"op": "frobnicate", "id": 2}\n',
        '["a", "list", "not", "object"]\n',
        '{"op": "embed", "id": 3}\n',            # missing source
        '{"op": "neighbors", "vector": "x"}\n',  # malformed vector
        '{"op": "health", "id": 4',              # mid-stream EOF: truncated
    ]

    class _Out:
        lines: list = []

        def write(self, s):
            self.lines.append(s)

        def flush(self):
            pass

    out = _Out()
    out.lines = []
    serve_stdio(served, iter(in_lines), out)
    responses = [json.loads(line) for line in out.lines]
    assert len(responses) == len(in_lines)
    assert responses[0]["ok"] and responses[0]["id"] == 1
    for bad in (1, 2, 3, 4, 5, 6):
        assert responses[bad]["error_kind"] == "bad_request", responses[bad]
    assert "bad request line" in responses[1]["error"]
    assert "unknown op" in responses[2]["error"]
    assert "bad request line" in responses[6]["error"]  # the truncated tail


def test_per_op_metrics_one_schema(served):
    served.handle({"op": "predict", "source": PY, "language": "python"})
    served.handle({"op": "health"})
    served.handle({"op": "nope"})
    snap = served.health.snapshot()
    assert snap["counters"]["serve.op.predict.requests"] >= 1
    assert snap["counters"]["serve.op.health.requests"] >= 1
    assert snap["latencies_ms"]["serve.op.predict.e2e_ms"]["count"] >= 1
    # unknown ops never mint metric names
    assert "serve.op.nope.requests" not in snap["counters"]


def test_variable_only_checkpoint_rejects_predict_op(served):
    """Same guard as Predictor.predict_source: a variable-task-only head
    must not serve method-name predictions (embed still works — the code
    vector does not depend on the label head's task)."""
    served.predictor.meta = {
        **served.predictor.meta, "infer_method_name": False,
    }
    resp = served.handle({"op": "predict", "source": PY, "language": "python"})
    assert resp["error_kind"] == "bad_request"
    assert "variable-name task" in resp["error"]
    assert served.handle(
        {"op": "embed", "source": PY, "language": "python"}
    )["ok"]


def test_handle_maps_resolve_time_errors(served):
    """A device-call failure surfaces on the future at resolve time — the
    sync handle() (the HTTP path) must turn it into an error payload, not
    let it escape and reset the connection."""
    import concurrent.futures

    class _BoomBatcher:
        def submit(self, arr):
            f = concurrent.futures.Future()
            f.set_exception(RuntimeError("device on fire"))
            return f

    real = served.batcher
    served.batcher = _BoomBatcher()
    try:
        resp = served.handle(
            {"op": "predict", "source": PY, "language": "python"}
        )
    finally:
        served.batcher = real
    assert resp["error_kind"] == "internal"
    assert "device on fire" in resp["error"]


def test_stdio_roundtrip_pipelined(served):
    from code2vec_tpu.serve.protocol import serve_stdio

    requests = [
        {"id": 1, "op": "predict", "source": PY, "language": "python",
         "top_k": 2},
        {"id": 2, "op": "embed", "source": PY, "language": "python",
         "method_name": "mul"},
        "this is not json",
        {"id": 3, "op": "health"},
        {"id": 4, "op": "shutdown"},
    ]
    in_lines = [
        (r if isinstance(r, str) else json.dumps(r)) + "\n" for r in requests
    ]

    class _Out:
        def __init__(self):
            self.lines = []

        def write(self, s):
            self.lines.append(s)

        def flush(self):
            pass

    out = _Out()
    serve_stdio(served, iter(in_lines), out)
    responses = [json.loads(line) for line in out.lines]
    assert len(responses) == 5
    assert responses[0]["id"] == 1 and responses[0]["ok"]
    assert len(responses[0]["methods"]) == 3
    assert responses[1]["id"] == 2
    (mul,) = responses[1]["methods"]
    assert len(mul["code_vector"]) == 32
    assert responses[2]["error_kind"] == "bad_request"
    assert responses[3]["id"] == 3 and responses[3]["post_warmup_compiles"] == 0
    assert responses[4]["shutting_down"]


def test_http_transport_roundtrip(served):
    import urllib.request

    from code2vec_tpu.serve.protocol import make_http_server

    try:
        httpd = make_http_server(served, "127.0.0.1", 0)
    except OSError as exc:  # pragma: no cover - sandboxed CI
        pytest.skip(f"cannot bind localhost: {exc}")
    port = httpd.server_address[1]
    thread = threading.Thread(
        target=httpd.serve_forever, kwargs={"poll_interval": 0.05}, daemon=True
    )
    thread.start()
    try:
        body = json.dumps({
            "op": "predict", "source": PY, "language": "python", "top_k": 1,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = json.loads(resp.read())
        assert payload["ok"] and len(payload["methods"]) == 3
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30
        ) as resp:
            health = json.loads(resp.read())
        assert health["ok"] and health["post_warmup_compiles"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=10)


# ---------------------------------------------------------------------------
# bench --serve: the open-loop load harness
# ---------------------------------------------------------------------------


def test_bench_serve_arm_reports_latency_and_zero_recompiles(tmp_path):
    bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_SUPERVISED="1",
        BENCH_SERVE_REQUESTS="60",
        BENCH_SERVE_QPS="300",
        BENCH_BAG="16",
        BENCH_EMBED="8",
        BENCH_ENCODE="12",
        BENCH_SERVE_TERMINALS="200",
        BENCH_SERVE_PATHS="150",
        BENCH_SERVE_LABELS="20",
    )
    proc = subprocess.run(
        [sys.executable, bench_path, "--serve"],
        capture_output=True, text=True, env=env, timeout=600,
        cwd=os.path.dirname(bench_path),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    metric = json.loads(proc.stdout.strip().splitlines()[-1])
    assert metric["metric"] == "serve_requests_per_sec"
    assert metric["value"] > 0
    assert metric["post_warmup_recompiles"] == 0
    assert 0 < metric["p50_ms"] <= metric["p99_ms"]
    detail_line = next(
        l for l in proc.stderr.splitlines() if l.startswith('{"detail"')
    )
    detail = json.loads(detail_line)["detail"]
    assert detail["mode"] == "serve"
    assert detail["completed"] == 60
    assert detail["detector_new_compiles"] == 0
    assert detail["real_contexts_per_sec"] > 0
    assert 0 < detail["pad_efficiency"] <= 1
    assert detail["latency_ms"]["device"]["count"] > 0
    assert len(detail["schedule_provenance"]) == detail["executables"]
    # PR-15 observability provenance: the mid-load /metrics scrape is
    # recorded PARSED (a malformed exporter would have died in the
    # parser), the flight recorder observed the stream, and the SLO
    # error-budget block carries the verdict the metric line mirrors
    scrape = detail["metrics_scrape"]
    assert scrape["series"] > 0
    # mid-load: exactly the requests submitted before the scrape point
    assert scrape["samples"]["c2v_serve_requests_total"] == scrape[
        "at_request"
    ]
    assert detail["flight"]["seen"] == 60
    burn = detail["slo_burn"]
    assert burn["good"] == 60 and burn["bad"] == 0
    assert burn["exhausted"] is False
    assert metric["slo_budget_exhausted"] is False
    assert metric["slo_burn_rate"] == 0.0


# ---------------------------------------------------------------------------
# CLI end-to-end: the CI serve-smoke scenario
# ---------------------------------------------------------------------------


def test_cli_stdio_end_to_end(trained_py):
    """Start the real server process, pipeline concurrent requests over
    stdio, assert responses + zero post-warmup recompiles + clean exit."""
    ds, out = trained_py
    requests = [
        {"id": i, "op": "predict", "source": PY, "language": "python",
         "top_k": 2}
        for i in range(4)
    ]
    requests.append({"id": 98, "op": "health"})
    requests.append({"id": 99, "op": "shutdown"})
    payload = "".join(json.dumps(r) + "\n" for r in requests)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "code2vec_tpu.serve",
            "--model_path", str(out),
            "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
            "--path_idx_path", str(ds / "path_idxs.txt"),
            "--transport", "stdio",
            "--deadline_ms", "5",
        ],
        input=payload, capture_output=True, text=True, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    responses = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert len(responses) == len(requests)
    by_id = {r["id"]: r for r in responses}
    for i in range(4):
        assert by_id[i]["ok"], by_id[i]
        assert len(by_id[i]["methods"]) == 3
    assert by_id[98]["post_warmup_compiles"] == 0
    assert by_id[98]["counters"]["serve_requests"] >= 12  # 4 reqs x 3 methods
    assert by_id[99]["shutting_down"]


def test_cli_sigterm_drains_accepted_requests(trained_py):
    """Satellite regression: SIGTERM mid-stream must DRAIN — every
    request written before the signal gets its response, the process
    exits 0 (the contract fleet eviction and rolling restarts rely on;
    previously queued requests died with the process)."""
    import signal

    ds, out = trained_py
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "code2vec_tpu.serve",
            "--model_path", str(out),
            "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
            "--path_idx_path", str(ds / "path_idxs.txt"),
            "--transport", "stdio",
            "--deadline_ms", "5",
        ],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, bufsize=1, env=env,
    )
    try:
        n_requests = 6
        for i in range(n_requests):
            proc.stdin.write(json.dumps({
                "id": i, "op": "embed", "source": PY, "language": "python",
                "method_name": "add",
            }) + "\n")
        proc.stdin.flush()
        # first response proves the server is mid-stream, then SIGTERM
        first = json.loads(proc.stdout.readline())
        assert first["ok"]
        proc.send_signal(signal.SIGTERM)
        remaining = [json.loads(line) for line in proc.stdout]
        stderr = proc.stderr.read()
        returncode = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:  # pragma: no cover - hung server
            proc.kill()
    assert returncode == 0, stderr[-4000:]
    responses = [first] + remaining
    # every accepted request was answered before exit
    assert sorted(r["id"] for r in responses) == list(range(n_requests))
    assert all(r["ok"] for r in responses), responses
