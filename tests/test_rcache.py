"""Router-level content-addressed result cache (serve/fleet/cache.py).

The load-bearing contracts pinned here:

- the canonical key addresses CONTENT, not bytes: any permutation of the
  same path-context bag digests identically (multisets — duplicates
  count), op-relevant knobs fold in, correlation fields (``id``,
  ``trace``) never do;
- S3-FIFO keeps byte usage under capacity and one-hit wonders wash
  through the probationary queue without displacing the hot set;
- concurrent identical misses coalesce onto one leader (one device
  call); error payloads resolve joiners but are never cached;
- keys embed the fleet generation version: a committed rolling swap
  invalidates instantly (misses recompute), the old generation's entries
  stay RESIDENT, and ``rollback`` makes them valid again bitwise;
- through the router: a cache hit never consumes SLO queue budget or
  reaches a replica, and the whole lifecycle holds on a REAL 2-replica
  subprocess fleet across reload + rollback (the CI rcache-smoke
  scenario).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from code2vec_tpu.obs.runtime import RuntimeHealth, prometheus_text
from code2vec_tpu.serve.fleet.cache import (
    ResultCache,
    canonical_bag_digest,
    canonical_request_key,
    payload_nbytes,
)

from test_fleet import (  # noqa: F401 - trained_tiny is a fixture
    PY,
    FakeReplica,
    make_router,
    trained_tiny,
)

pytestmark = pytest.mark.rcache


# ---------------------------------------------------------------------------
# canonical keys: content addressing
# ---------------------------------------------------------------------------


def test_bag_digest_is_order_invariant_multiset():
    bag = [[3, 7, 2], [1, 5, 9], [3, 7, 2]]
    d = canonical_bag_digest(bag)
    assert canonical_bag_digest(list(reversed(bag))) == d
    assert canonical_bag_digest(tuple(map(tuple, bag))) == d
    assert canonical_bag_digest(np.asarray(bag, dtype=np.int32)) == d
    # a multiset, not a set: the duplicate row counts
    assert canonical_bag_digest(bag[:2]) != d
    # triples are ordered within a row (start/path/end are distinct roles)
    assert canonical_bag_digest([[1, 2, 3]]) != canonical_bag_digest(
        [[3, 2, 1]]
    )


def test_request_key_addresses_content_not_bytes():
    base = {
        "op": "embed",
        "contexts": [[1, 2, 3], [4, 5, 6]],
        "language": "python",
    }
    key = canonical_request_key(base)
    assert key is not None
    permuted = dict(base, contexts=[[4, 5, 6], [1, 2, 3]])
    assert canonical_request_key(permuted) == key
    # correlation fields are not content
    assert canonical_request_key(
        dict(base, id=42, trace={"trace_id": "deadbeef"})
    ) == key
    # a different bag is a different key; so is a different op
    assert canonical_request_key(
        dict(base, contexts=[[1, 2, 3]])
    ) != key
    assert canonical_request_key(dict(base, op="predict")) != key


def test_request_key_folds_op_relevant_knobs():
    base = {"op": "predict", "source": "def f(): pass"}
    key = canonical_request_key(base)
    assert canonical_request_key(dict(base, top_k=5)) != key
    # conservative by construction: knob-absent and knob-at-default are
    # DIFFERENT keys (redundant miss beats a wrong hit)
    assert canonical_request_key(dict(base, top_k=10)) != key
    # granularity matters for neighbors only — and neighbors-by-vector
    # digests the wire floats
    vec = {"op": "neighbors", "vector": [1.0, 2.5], "top_k": 3}
    assert canonical_request_key(vec) is not None
    assert canonical_request_key(
        dict(vec, granularity="file")
    ) != canonical_request_key(vec)
    assert canonical_request_key(
        dict(vec, vector=[2.5, 1.0])
    ) != canonical_request_key(vec)


def test_request_key_uncacheable_forms():
    assert canonical_request_key({"op": "health"}) is None
    assert canonical_request_key({"op": "reload", "model_path": "x"}) is None
    assert canonical_request_key({"op": "nope", "source": "x"}) is None
    assert canonical_request_key({"op": "embed"}) is None  # no body
    assert canonical_request_key(
        {"op": "embed", "contexts": [["a", "b"]]}
    ) is None  # malformed rows
    assert canonical_request_key(
        {"op": "embed", "source": "x", "method_name": object()}
    ) is None  # unserializable knob


def test_payload_nbytes_is_wire_size():
    assert payload_nbytes({"ok": True}) == len(b'{"ok":true}')
    assert payload_nbytes({"x": object()}) is None


# ---------------------------------------------------------------------------
# S3-FIFO eviction, byte-accounted
# ---------------------------------------------------------------------------


def _fill(cache, key, value, nbytes):
    state, _ = cache.begin(key)
    assert state == "lead"
    cache.fill(key, value, nbytes=nbytes)


def test_s3_fifo_hot_entry_survives_one_hit_wonder_flood():
    cache = ResultCache(1000)
    hot = ("v0", "hot")
    _fill(cache, hot, {"v": "hot"}, 80)
    for i in range(50):
        _fill(cache, ("v0", f"wonder{i}"), {"v": i}, 80)
        if i % 3 == 0:  # keep the hot entry referenced
            state, held = cache.begin(hot)
            assert state == "hit", f"hot entry evicted at wonder {i}"
            assert held == {"v": "hot"}
    stats = cache.stats()
    assert stats["bytes"] <= stats["capacity_bytes"]
    assert stats["evictions"] > 0
    state, _ = cache.begin(hot)
    assert state == "hit"


def test_s3_fifo_ghost_readmission_goes_to_main():
    cache = ResultCache(1000)
    victim = ("v0", "victim")
    _fill(cache, victim, {"v": 0}, 80)
    # flood until the never-re-referenced victim is evicted to ghost
    i = 0
    while victim in cache._entries:
        _fill(cache, ("v0", f"k{i}"), {"v": i}, 80)
        i += 1
        assert i < 200, "victim never evicted"
    assert victim in cache._ghost
    # a ghost's return skips probation: straight into the main queue
    _fill(cache, victim, {"v": 1}, 80)
    assert cache._entries[victim].in_main is True


def test_oversize_payload_rejected_not_cached():
    cache = ResultCache(100)
    key = ("v0", "big")
    _fill(cache, key, {"v": "x" * 500}, 500)
    stats = cache.stats()
    assert stats["rejected_oversize"] == 1
    assert stats["entries"] == 0 and stats["bytes"] == 0
    state, _ = cache.begin(key)
    assert state == "lead"  # next request retries cold


# ---------------------------------------------------------------------------
# miss coalescing
# ---------------------------------------------------------------------------


def test_coalescing_joiners_inherit_leader_fill():
    cache = ResultCache(1 << 16)
    key = ("v0", "k")
    state, leader = cache.begin(key)
    assert state == "lead"
    s2, held = cache.begin(key)
    assert s2 == "join" and held is leader
    cache.fill(key, {"ok": True})
    assert held.result(1) == {"ok": True}
    state, held = cache.begin(key)
    assert state == "hit" and held == {"ok": True}
    assert cache.stats()["coalesced"] == 1


def test_coalescing_abandon_resolves_but_never_caches():
    cache = ResultCache(1 << 16)
    key = ("v0", "err")
    _, leader = cache.begin(key)
    _, held = cache.begin(key)
    cache.abandon(key, {"error": "boom"})
    assert held.result(1) == {"error": "boom"}  # joiners inherit verbatim
    state, _ = cache.begin(key)
    assert state == "lead"  # the next identical request retries cold
    assert cache.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# versioned invalidation
# ---------------------------------------------------------------------------


def test_version_lifecycle_commit_and_rollback_bitwise():
    cache = ResultCache(1 << 16, version="m#g0")
    req = {"op": "embed", "contexts": [[1, 2, 3]]}
    key = cache.key_for(req)
    assert key is not None and key[0] == "m#g0"
    payload = {"ok": True, "vector": [0.125, 0.25]}
    _fill(cache, key, payload, 64)

    # mid-roll the fleet is mixed-version: the cache stands down entirely
    cache.begin_swap()
    assert cache.active_version is None
    assert cache.key_for(req) is None
    assert cache.stats()["swapping"] is True

    # commit flips the visible version; old entries stay RESIDENT
    cache.end_swap("m#g1")
    key_v1 = cache.key_for(req)
    assert key_v1 == ("m#g1", key[1])
    state, _ = cache.begin(key_v1)
    assert state == "lead"  # invalidated: recompute on the new weights
    cache.abandon(key_v1, None)
    assert cache.stats()["versions"].get("m#g0") == 1

    # rollback: the retained entry is valid again, the SAME object
    cache.set_version("m#g0")
    state, held = cache.begin(cache.key_for(req))
    assert state == "hit" and held is payload


def test_failed_swap_keeps_incumbent_entries_live():
    cache = ResultCache(1 << 16, version="m#g0")
    key = cache.key_for({"op": "embed", "source": "x"})
    _fill(cache, key, {"ok": True}, 16)
    cache.begin_swap()
    cache.end_swap()  # roll failed: incumbent never stopped being true
    assert cache.active_version == "m#g0"
    state, _ = cache.begin(cache.key_for({"op": "embed", "source": "x"}))
    assert state == "hit"


# ---------------------------------------------------------------------------
# through the router (in-process fake replicas)
# ---------------------------------------------------------------------------


def _counting_behavior():
    calls = {"n": 0}
    lock = threading.Lock()

    def behavior(req):
        op = req.get("op")
        if op in ("embed", "predict", "neighbors"):
            with lock:
                calls["n"] += 1
                return {"ok": True, "vector": [float(calls["n"])]}
        if op == "reload":
            return {"ok": True}
        if op == "swap_status":
            return {"swap": {"state": "idle", "last_swap": {
                "outcome": "committed", "version": "m#g1"}}}
        if op == "rollback":
            return {"swap": {"active_version": "m#g0"}}
        return {"ok": True, "op": op}

    return behavior, calls


def test_router_hit_skips_replica_and_queue_budget():
    behavior, calls = _counting_behavior()
    fake = FakeReplica(0, behavior=behavior)
    health = RuntimeHealth()
    router = make_router(
        [fake], health=health,
        result_cache=ResultCache(1 << 20, health=health),
    )
    try:
        req = {"op": "embed", "source": PY, "language": "python",
               "method_name": "add"}
        first = router.handle(dict(req))
        second = router.handle(dict(req))
        assert first == second == {"ok": True, "vector": [1.0]}
        assert calls["n"] == 1
        data_ops = [r for r in fake.sent if r.get("op") == "embed"]
        assert len(data_ops) == 1
        counters = health.snapshot()["counters"]
        assert counters["slo.embed.completed"] == 1
        assert counters["slo.embed.cache_hits"] == 1
        # the health/metrics surfaces carry the cache block
        block = router.handle({"op": "health"})["fleet"]["cache"]
        assert block["hits"] == 1 and block["entries"] == 1
        text = prometheus_text([({}, health.snapshot())])
        assert "c2v_cache_hits_total 1" in text
        assert "c2v_cache_bytes" in text
    finally:
        router.close()


def test_router_permuted_contexts_resend_hits():
    behavior, calls = _counting_behavior()
    fake = FakeReplica(0, behavior=behavior)
    router = make_router(
        [fake], result_cache=ResultCache(1 << 20),
    )
    try:
        bag = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
        first = router.handle({"op": "embed", "contexts": bag})
        second = router.handle(
            {"op": "embed", "contexts": list(reversed(bag)), "id": 7}
        )
        assert second == {"id": 7, **first}
        assert calls["n"] == 1
    finally:
        router.close()


def test_router_coalesces_thundering_herd_to_one_dispatch():
    behavior, calls = _counting_behavior()
    fake = FakeReplica(0, latency_s=0.15, behavior=behavior)
    router = make_router(
        [fake], result_cache=ResultCache(1 << 20),
    )
    try:
        req = {"op": "embed", "source": "def f(): pass"}
        resolvers = [router.handle_async(dict(req)) for _ in range(6)]
        payloads = [r() for r in resolvers]
        assert all(p == {"ok": True, "vector": [1.0]} for p in payloads)
        assert calls["n"] == 1
        stats = router._cache.stats()
        assert stats["coalesced"] == 5 and stats["misses"] == 1
    finally:
        router.close()


def test_router_error_payloads_are_not_cached():
    attempts = {"n": 0}

    def flaky(req):
        attempts["n"] += 1
        if attempts["n"] == 1:
            return {"error": "transient backend failure"}
        return {"ok": True, "vector": [1.0]}

    fake = FakeReplica(0, behavior=flaky)
    router = make_router([fake], result_cache=ResultCache(1 << 20))
    try:
        req = {"op": "embed", "source": "x"}
        assert router.handle(dict(req)).get("error")
        assert router.handle(dict(req)) == {"ok": True, "vector": [1.0]}
        assert attempts["n"] == 2  # the error never served a second time
        state, _ = router._cache.begin(router._cache.key_for(req))
        assert state == "hit"  # ...but the success was cached
    finally:
        router.close()


def test_router_without_cache_is_inert():
    behavior, calls = _counting_behavior()
    fake = FakeReplica(0, behavior=behavior)
    router = make_router([fake])  # --result_cache_mb 0: no cache object
    try:
        for _ in range(5):
            assert router.handle({"op": "embed", "source": "x"})["ok"]
        assert calls["n"] == 5
        assert router.handle({"op": "health"})["fleet"]["cache"] is None
    finally:
        router.close()


def test_router_swap_flips_cache_version_and_rollback_restores():
    behavior, calls = _counting_behavior()
    fakes = [FakeReplica(0, behavior=behavior),
             FakeReplica(1, behavior=behavior)]
    for fake in fakes:
        fake.last_health = {"version": "m#g0"}  # boot-time version seed
    router = make_router(
        fakes, result_cache=ResultCache(1 << 20),
    )
    try:
        cache = router._cache
        assert cache.active_version == "m#g0"
        req = {"op": "embed", "source": PY, "language": "python"}
        warm = router.handle(dict(req))
        assert router.handle(dict(req)) == warm and calls["n"] == 1

        rolled = router.handle(
            {"op": "reload", "model_path": "out_v2", "wait": True}
        )
        assert rolled["ok"] and rolled["rolling"]["outcome"] == "committed"
        assert cache.active_version == "m#g1"
        # invalidated on commit: the resend recomputes on the new weights
        assert router.handle(dict(req)) == {"ok": True, "vector": [2.0]}
        assert calls["n"] == 2
        # ...while the old generation's entry stays resident
        assert cache.stats()["versions"].get("m#g0", 0) >= 1

        back = router.handle({"op": "rollback"})
        assert back["ok"], back
        assert cache.active_version == "m#g0"
        # revalidated bitwise: the EXACT pre-swap payload, no dispatch
        assert router.handle(dict(req)) == warm
        assert calls["n"] == 2
    finally:
        router.close()


# ---------------------------------------------------------------------------
# real 2-replica fleet e2e: the CI rcache-smoke scenario
# ---------------------------------------------------------------------------


def test_fleet_result_cache_survives_rolling_swap_and_rollback(trained_tiny):
    """Boot a REAL 2-replica fleet with the result cache on, warm it on
    generation g0, roll to g1 (cache invalidates — misses recompute, g0
    entries stay resident), then roll back and get the ORIGINAL payload
    served bitwise from cache with zero device calls."""
    from code2vec_tpu.serve.fleet.__main__ import build_parser, build_router

    ds, out = trained_tiny
    args = build_parser().parse_args([
        "--replicas", "2",
        "--model_path", str(out),
        "--terminal_idx_path", str(ds / "terminal_idxs.txt"),
        "--path_idx_path", str(ds / "path_idxs.txt"),
        "--deadline_ms", "2",
        "--boot_timeout_s", "600",
        "--result_cache_mb", "8",
    ])
    router, events = build_router(args)

    def completed():
        return router.health.snapshot()["counters"].get(
            "slo.embed.completed", 0
        )

    try:
        req = {"op": "embed", "source": PY, "language": "python",
               "method_name": "add"}
        warm = router.handle(dict(req))
        assert warm.get("ok"), warm
        n0 = completed()
        hit = router.handle(dict(req))
        assert hit == warm  # bitwise: the exact cached payload
        assert completed() == n0  # no replica touched

        # pre-mapped contexts: a permuted resend of the same bag hits
        bag = [[0, 0, 0], [1, 1, 1]]
        by_ctx = router.handle({"op": "embed", "contexts": bag})
        assert by_ctx.get("ok"), by_ctx
        n1 = completed()
        permuted = router.handle(
            {"op": "embed", "contexts": list(reversed(bag))}
        )
        assert permuted == by_ctx
        assert completed() == n1

        rolled = router.handle(
            {"op": "reload", "model_path": str(out), "wait": True}
        )
        assert rolled["ok"], rolled
        assert rolled["rolling"]["outcome"] == "committed"
        block = router.handle({"op": "health"})["fleet"]["cache"]
        assert block["active_version"].endswith("#g1")
        assert any(v.endswith("#g0") for v in block["versions"])

        # invalidated on commit: the same request is a miss (recomputes)
        n2 = completed()
        on_g1 = router.handle(dict(req))
        assert on_g1.get("ok"), on_g1
        assert completed() == n2 + 1

        back = router.handle({"op": "rollback"})
        assert back["ok"], back
        block = router.handle({"op": "health"})["fleet"]["cache"]
        assert block["active_version"].endswith("#g0")

        # revalidated bitwise: g0's retained entry, zero device calls
        n3 = completed()
        restored = router.handle(dict(req))
        assert restored == warm
        assert completed() == n3
        assert block["hits"] >= 2 and block["misses"] >= 2
        for replica in router.handle({"op": "health"})["fleet"]["replicas"]:
            assert replica["post_warmup_compiles"] == 0
    finally:
        router.close()
        if events is not None:
            events.close()
