"""Tests for code2vec_tpu/analysis: the jaxlint AST rules (paired
positive/negative fixtures per rule), inline suppression + baseline
round-trip, the JSON output schema, the sharding-contract checker against
declared mesh axes, the CLI runner, the ``@shape_contract`` trace-time
layer (including the no-steady-state-sync property, asserted via trace
count), and the recompile → lint-rule correlation hint.

The acceptance pincer lives in :class:`TestWeakStepPincer`: the same
weak-typed-scalar-into-the-train-step defect is caught statically by
jaxlint AND rejected at trace time by the step's contract.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.analysis import jaxlint
from code2vec_tpu.analysis.contracts import (
    ArgSpec,
    ContractError,
    shape_contract,
    spec,
)
from code2vec_tpu.analysis.jaxlint import lint_source
from code2vec_tpu.analysis.sharding_check import check_source, declared_axes

REPO = Path(__file__).resolve().parents[1]
AXES = {"AXIS_DATA": "data", "AXIS_MODEL": "model", "AXIS_CTX": "ctx"}


def lint(src: str):
    return lint_source(textwrap.dedent(src), "mod.py")


def rule_ids(findings, *, include_suppressed=False):
    return {
        f.rule
        for f in findings
        if include_suppressed or not f.suppressed
    }


def shard(src: str, axes=None):
    return check_source(textwrap.dedent(src), "mod.py", axes or AXES)


# ---------------------------------------------------------------------------
# JX000 parse-error


class TestJX000ParseError:
    def test_syntax_error_flagged_with_message_fingerprint(self):
        findings = lint("def broken(:\n")
        assert rule_ids(findings) == {"JX000"}
        (f,) = findings
        assert "does not parse" in f.message
        # the SyntaxError message is the snippet, so two DIFFERENT syntax
        # errors in the same file fingerprint separately (one baselined
        # occurrence can't mask the next)
        other = lint("x = (1\n")
        assert jaxlint.fingerprint(f) != jaxlint.fingerprint(other[0])

    def test_valid_file_clean(self):
        assert "JX000" not in rule_ids(lint("x = 1\n"))


# ---------------------------------------------------------------------------
# JX001 weak-type-literal


class TestJX001WeakTypeLiteral:
    def test_scan_carry_literal_flagged(self):
        findings = lint(
            """
            import jax

            def run(xs):
                return jax.lax.scan(lambda c, x: (c + x, c), 0.0, xs)
            """
        )
        assert "JX001" in rule_ids(findings)

    def test_dtypeless_jnp_array_scalar_flagged(self):
        findings = lint(
            """
            import jax.numpy as jnp

            step = jnp.array(0)
            """
        )
        assert "JX001" in rule_ids(findings)

    def test_strong_carry_and_explicit_dtype_clean(self):
        findings = lint(
            """
            import jax
            import jax.numpy as jnp

            step = jnp.array(0, jnp.int32)
            full = jnp.full((4,), 1.0, jnp.float32)

            def run(xs):
                return jax.lax.scan(
                    lambda c, x: (c + x, c), jnp.zeros(()), xs
                )
            """
        )
        assert "JX001" not in rule_ids(findings)

    def test_fori_loop_and_while_loop_inits(self):
        findings = lint(
            """
            import jax

            def count(n):
                return jax.lax.fori_loop(0, n, lambda i, c: c + i, 0)

            def drain(x):
                return jax.lax.while_loop(lambda c: c[1] > 0, step, (x, 1))
            """
        )
        assert "JX001" in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX002 host-sync-in-trace


class TestJX002HostSyncInTrace:
    def test_float_of_traced_value_flagged(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """
        )
        assert "JX002" in rule_ids(findings)

    def test_item_numpy_devget_print_flagged(self):
        findings = lint(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                a = x.item()
                b = np.asarray(x)
                c = jax.device_get(x)
                print(x)
                return a, b, c
            """
        )
        msgs = [f.message for f in findings if f.rule == "JX002"]
        assert len(msgs) == 4

    def test_static_conversions_clean(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                n = float(x.shape[0])  # shape access is static
                return x * n

            def host_side(x):
                return float(x)  # not traced
            """
        )
        assert "JX002" not in rule_ids(findings)

    def test_fn_passed_by_name_to_jit_is_traced(self):
        findings = lint(
            """
            import jax

            def body(x):
                return float(x)

            step = jax.jit(body)
            """
        )
        assert "JX002" in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX003 tracer-branch


class TestJX003TracerBranch:
    def test_if_on_traced_value_flagged(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """
        )
        assert "JX003" in rule_ids(findings)

    def test_while_on_traced_value_flagged(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                while x > 0:
                    x = x - 1
                return x
            """
        )
        assert "JX003" in rule_ids(findings)

    def test_static_branches_clean(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x, flag=None):
                if flag is None:
                    return x
                if x.shape[0] > 2:
                    return x * 2
                if isinstance(x, tuple):
                    return x[0]
                return x
            """
        )
        assert "JX003" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX004 impure-trace


class TestJX004ImpureTrace:
    def test_time_and_np_random_flagged(self):
        findings = lint(
            """
            import time
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                t = time.perf_counter()
                r = np.random.normal()
                return x * t + r
            """
        )
        msgs = [f for f in findings if f.rule == "JX004"]
        assert len(msgs) == 2

    def test_jax_random_and_host_side_time_clean(self):
        findings = lint(
            """
            import time
            import jax

            @jax.jit
            def f(x, key):
                return x + jax.random.normal(key, x.shape)

            def wall():
                return time.perf_counter()  # not traced
            """
        )
        assert "JX004" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX005 missing-donate


class TestJX005MissingDonate:
    def test_decorated_update_without_donation_flagged(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def step(state, batch):
                state = state.apply_gradients(grads=batch)
                return state
            """
        )
        assert "JX005" in rule_ids(findings)

    def test_call_form_without_donation_flagged(self):
        findings = lint(
            """
            import jax

            def step(state, batch):
                state = state.replace(step=state.step + 1)
                return state

            jitted = jax.jit(step)
            """
        )
        assert "JX005" in rule_ids(findings)

    def test_donating_variants_clean(self):
        findings = lint(
            """
            from functools import partial

            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, batch):
                state = state.apply_gradients(grads=batch)
                return state

            def raw(state, batch):
                return state.replace(step=state.step + 1)

            jitted = jax.jit(raw, donate_argnums=(0,))
            """
        )
        assert "JX005" not in rule_ids(findings)

    def test_pure_function_clean(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x, y):
                return x + y
            """
        )
        assert "JX005" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX006 set-iteration-order


class TestJX006SetIterationOrder:
    def test_for_over_set_flagged(self):
        findings = lint(
            """
            names = {"b", "a"}
            out = []
            for n in names & {"a"}:
                out.append(n)
            for n in set(out):
                out.append(n)
            """
        )
        # only the literal set()/set-call iterations are flagged (the
        # binop result is opaque — lint-grade, no guessing)
        assert "JX006" in rule_ids(findings)

    def test_comprehension_over_set_flagged(self):
        findings = lint(
            """
            leaves = [x for x in {"p", "q"}]
            """
        )
        assert "JX006" in rule_ids(findings)

    def test_sorted_set_clean(self):
        findings = lint(
            """
            names = {"b", "a"}
            out = [n for n in sorted(names)]
            for n in sorted(set(out)):
                out.append(n)
            """
        )
        assert "JX006" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# JX007 host-sync-step-loop


class TestJX007HostSyncStepLoop:
    def test_per_step_float_flagged(self):
        findings = lint(
            """
            def epoch(train_step, state, batches):
                total = 0.0
                for batch in batches:
                    state, loss = train_step(state, batch)
                    total += float(loss)
                return state, total
            """
        )
        assert "JX007" in rule_ids(findings)

    def test_per_step_item_flagged(self):
        findings = lint(
            """
            def epoch(eval_step, state, batches):
                out = []
                for batch in batches:
                    res = eval_step(state, batch)
                    out.append(res.item())
                return out
            """
        )
        assert "JX007" in rule_ids(findings)

    def test_accumulate_then_sync_once_clean(self):
        findings = lint(
            """
            def epoch(train_step, state, batches):
                losses = []
                for batch in batches:
                    state, loss = train_step(state, batch)
                    losses.append(loss)
                return state, float(sum(map(float, losses)) / len(losses))
            """
        )
        assert "JX007" not in rule_ids(findings)

    def test_float_in_non_step_loop_clean(self):
        findings = lint(
            """
            def parse(rows):
                return [float(r) for r in rows]

            def walk(rows):
                out = 0.0
                for r in rows:
                    out += float(r)
                return out
            """
        )
        assert "JX007" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# suppression + baseline


class TestSuppressionAndBaseline:
    SRC = """
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """

    def test_inline_suppression_by_id(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)  # jaxlint: disable=JX002
            """
        )
        assert "JX002" in rule_ids(findings, include_suppressed=True)
        assert "JX002" not in rule_ids(findings)

    def test_bare_disable_suppresses_all(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)  # jaxlint: disable
            """
        )
        assert all(f.suppressed for f in findings)

    def test_other_id_does_not_suppress(self):
        findings = lint(
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)  # jaxlint: disable=JX001
            """
        )
        assert "JX002" in rule_ids(findings)

    def test_baseline_round_trip(self, tmp_path):
        findings = lint(self.SRC)
        assert findings and not any(f.baselined for f in findings)
        bl = tmp_path / "baseline.json"
        jaxlint.write_baseline(findings, bl)
        loaded = jaxlint.load_baseline(bl)
        again = lint(self.SRC)
        jaxlint.apply_baseline(again, loaded)
        assert all(f.baselined for f in again)

    def test_baseline_counts_not_blanket(self, tmp_path):
        findings = lint(self.SRC)
        bl = tmp_path / "baseline.json"
        jaxlint.write_baseline(findings, bl)
        # the same defect introduced a SECOND time is a new finding: the
        # baseline stores per-fingerprint counts, not blanket rule passes
        doubled = lint(
            self.SRC
            + """
            @jax.jit
            def g(y):
                return float(y)
            """
        )
        jaxlint.apply_baseline(doubled, jaxlint.load_baseline(bl))
        jx002 = [f for f in doubled if f.rule == "JX002"]
        assert sum(f.baselined for f in jx002) == 1
        assert sum(not f.baselined for f in jx002) == 1

    def test_missing_baseline_is_empty(self, tmp_path):
        assert jaxlint.load_baseline(tmp_path / "nope.json") == {}

    def test_fingerprint_survives_line_shift(self):
        a = lint(self.SRC)[0]
        shifted = lint("\n\n\n" + textwrap.dedent(self.SRC))[0]
        assert a.line != shifted.line
        assert jaxlint.fingerprint(a) == jaxlint.fingerprint(shifted)


# ---------------------------------------------------------------------------
# sharding checker


class TestShardingChecker:
    def test_undeclared_axis_flagged(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            row = P("bath", None)
            """
        )
        assert {f.rule for f in findings} == {"SC001"}
        assert "'bath'" in findings[0].message

    def test_repeated_bad_axis_emits_once(self):
        # one spec repeating an undeclared axis is ONE defect — duplicate
        # identical findings would also inflate the baseline count
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            row = P("bogus", "bogus")
            """
        )
        assert [f.rule for f in findings if f.rule == "SC001"] == ["SC001"]

    def test_declared_axes_clean(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            batch = P("data", None)
            both = P("data", "model")
            repl = P(None)
            """
        )
        assert findings == []

    def test_axis_resolved_through_mesh_constant(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            from code2vec_tpu.parallel.mesh import AXIS_DATA

            ok = P(AXIS_DATA)
            """
        )
        assert findings == []

    def test_duplicate_axis_flagged(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            bad = P("data", "data")
            """
        )
        assert {f.rule for f in findings} == {"SC002"}

    def test_tuple_slot_duplicate_flagged(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            bad = P(("data", "model"), "model")
            """
        )
        assert {f.rule for f in findings} == {"SC002"}

    def test_ctx_axis_in_param_rules_flagged(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            def param_sharding_rules():
                return {"table": P("ctx", None)}
            """
        )
        assert {f.rule for f in findings} == {"SC003"}

    def test_ctx_axis_on_batch_clean(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            def batch_shardings():
                return {"starts": P("data", "ctx")}
            """
        )
        assert findings == []

    def test_unresolvable_names_are_skipped(self):
        findings = shard(
            """
            from jax.sharding import PartitionSpec as P

            def make(axis):
                return P(axis)  # helper arg: UNKNOWN, never guessed
            """
        )
        assert findings == []

    def test_real_mesh_module_declares_axes(self):
        decls = declared_axes(
            (REPO / "code2vec_tpu" / "parallel" / "mesh.py").read_text()
        )
        assert decls["AXIS_CTX"] == "ctx"
        assert set(decls.values()) >= {"data", "model", "ctx"}


# ---------------------------------------------------------------------------
# CLI runner


class TestRunnerCLI:
    def _write(self, tmp_path, body):
        f = tmp_path / "snippet.py"
        f.write_text(textwrap.dedent(body))
        return f

    def _run(self, tmp_path, *extra):
        from code2vec_tpu.analysis.__main__ import main

        return main(
            [
                str(tmp_path),
                "--root",
                str(tmp_path),
                "--baseline",
                str(tmp_path / "baseline.json"),
                *extra,
            ]
        )

    def test_clean_tree_exits_zero(self, tmp_path, capsys):
        self._write(tmp_path, "x = 1\n")
        assert self._run(tmp_path) == 0
        assert "0 new finding(s)" in capsys.readouterr().out

    def test_finding_exits_one_with_hint(self, tmp_path, capsys):
        self._write(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """,
        )
        assert self._run(tmp_path) == 1
        out = capsys.readouterr().out
        assert "JX002" in out and "fix:" in out and "snippet.py:" in out

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        self._write(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """,
        )
        assert self._run(tmp_path, "--write-baseline") == 0
        capsys.readouterr()
        assert self._run(tmp_path) == 0
        assert "1 baselined" in capsys.readouterr().out

    def test_json_schema(self, tmp_path, capsys):
        self._write(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """,
        )
        assert self._run(tmp_path, "--json") == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == 1 and doc["tool"] == "jaxlint"
        assert set(doc["summary"]) == {
            "total", "new", "baselined", "suppressed", "by_severity",
        }
        (finding,) = [f for f in doc["findings"] if f["rule"] == "JX002"]
        assert set(finding) == {
            "rule", "name", "severity", "path", "line", "col", "message",
            "hint", "snippet", "fingerprint", "suppressed", "baselined",
        }
        assert finding["severity"] == "error"
        assert finding["path"] == "snippet.py"

    def test_list_rules(self, tmp_path, capsys):
        assert self._run(tmp_path, "--list-rules") == 0
        out = capsys.readouterr().out
        for rid in jaxlint.RULES:
            assert rid in out

    def test_repo_runs_clean(self, capsys):
        """Acceptance: `python -m code2vec_tpu.analysis` on this repo has
        zero unsuppressed, unbaselined findings."""
        from code2vec_tpu.analysis.__main__ import main

        assert main([]) == 0, capsys.readouterr().out

    def test_diff_only_out_of_scope_is_noop(self, tmp_path, capsys):
        # a tmp 'repo' with no git at all: --diff-only falls back to the
        # full scan (never silently passes)
        self._write(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """,
        )
        assert self._run(tmp_path, "--diff-only", "HEAD") == 1
        err = capsys.readouterr().err
        assert "full scan" in err

    def test_diff_only_write_baseline_rejected(self, tmp_path, capsys):
        # a baseline written from a restricted scan would drop accepted
        # fingerprints in every unscanned file
        with pytest.raises(SystemExit) as exc:
            self._run(tmp_path, "--diff-only", "HEAD", "--write-baseline")
        assert exc.value.code == 2
        assert "full scan" in capsys.readouterr().err

    def test_diff_only_mesh_change_triggers_full_scan(self, tmp_path, capsys):
        # renaming a mesh axis invalidates PartitionSpecs in UNCHANGED
        # files — --diff-only must widen to the full scan, or the PR job
        # passes and the push job on main breaks
        mesh = tmp_path / "parallel" / "mesh.py"
        mesh.parent.mkdir()
        mesh.write_text('AXIS_DATA = "data"\n')
        stale = tmp_path / "shardings.py"
        stale.write_text(
            "from jax.sharding import PartitionSpec\n"
            'SPEC = PartitionSpec("data")\n'
        )

        def git(*a):
            subprocess.run(
                ["git", "-C", str(tmp_path), *a],
                check=True,
                capture_output=True,
            )

        git("init", "-q")
        git("add", "-A")
        git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "x")
        mesh.write_text('AXIS_DATA = "rows"\n')  # stale.py left untouched
        rc = self._run(
            tmp_path, "--diff-only", "HEAD", "--mesh-file", str(mesh)
        )
        captured = capsys.readouterr()
        assert rc == 1
        assert "SC001" in captured.out and "shardings.py" in captured.out
        assert "full scan" in captured.err

    def test_diff_only_resource_site_change_triggers_full_scan(
        self, tmp_path, capsys
    ):
        # adding a resource construction can change RS005's repo-wide
        # ownership verdicts on UNCHANGED files — --diff-only must widen
        # to the full scan (same rationale as the lock-graph widening)
        stale = tmp_path / "leaky.py"
        stale.write_text(
            "def read(p):\n"
            "    f = open(p)\n"
            "    return f.read()\n"
        )
        worker = tmp_path / "worker.py"
        worker.write_text("import threading\n")

        def git(*a):
            subprocess.run(
                ["git", "-C", str(tmp_path), *a],
                check=True,
                capture_output=True,
            )

        git("init", "-q")
        git("add", "-A")
        git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "x")
        worker.write_text(  # leaky.py left untouched
            "import threading\n"
            "\n"
            "def spawn(fn):\n"
            "    t = threading.Thread(target=fn, daemon=True)\n"
            "    t.start()\n"
            "    return t\n"
        )
        rc = self._run(tmp_path, "--diff-only", "HEAD")
        captured = capsys.readouterr()
        assert rc == 1
        assert "RS001" in captured.out and "leaky.py" in captured.out
        assert "resource construction" in captured.err
        assert "full scan" in captured.err

    def test_tools_wrapper_smoke(self):
        res = subprocess.run(
            [sys.executable, str(REPO / "tools" / "jaxlint.py"),
             "--list-rules"],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert res.returncode == 0 and "JX001" in res.stdout


# ---------------------------------------------------------------------------
# trace-time contracts


class TestShapeContract:
    def test_spec_parsing(self):
        s = spec("B,L", "int")
        assert s.dims == ("B", "L") and s.dtypes == "int"
        assert spec("").dims == ()
        assert spec("4,?").dims == (4, "?")
        assert isinstance(spec(dtype=jnp.int32), ArgSpec)
        with pytest.raises(ValueError, match="category"):
            spec("B", "quaternion")

    def test_pass_and_rank_mismatch(self):
        @shape_contract(x=spec("B,L", "int"))
        def f(x):
            return x.sum()

        f(jnp.zeros((2, 3), jnp.int32))
        with pytest.raises(ContractError, match="rank"):
            f(jnp.zeros((2, 3, 4), jnp.int32))

    def test_dtype_category_and_exact(self):
        @shape_contract(x=spec("B", "float"), y=spec("B", jnp.int32))
        def f(x, y):
            return x, y

        f(jnp.zeros(3, jnp.bfloat16), jnp.zeros(3, jnp.int32))
        with pytest.raises(ContractError, match="dtype"):
            f(jnp.zeros(3, jnp.int32), jnp.zeros(3, jnp.int32))
        with pytest.raises(ContractError, match="dtype"):
            f(jnp.zeros(3), jnp.zeros(3, jnp.int16))

    def test_symbols_bind_consistently_within_call(self):
        @shape_contract(a="B,L", b="B")
        def f(a, b):
            return a, b

        f(jnp.zeros((2, 5)), jnp.zeros(2))
        # a fresh call may bind different sizes (bucketed widths)...
        f(jnp.zeros((4, 9)), jnp.zeros(4))
        # ...but within one call the symbol must agree
        with pytest.raises(ContractError, match="B=2"):
            f(jnp.zeros((2, 5)), jnp.zeros(3))

    def test_exact_dim_pin(self):
        @shape_contract(x="3,?")
        def f(x):
            return x

        f(jnp.zeros((3, 7)))
        with pytest.raises(ContractError, match="pins"):
            f(jnp.zeros((4, 7)))

    def test_weak_rejected_strong_accepted(self):
        @shape_contract(x=spec("", "int"))
        def f(x):
            return x + 1

        f(jnp.asarray(0, jnp.int32))
        with pytest.raises(ContractError, match="WEAK"):
            f(jnp.asarray(0))  # dtype-less: weak int32

    def test_allow_weak_opt_in(self):
        @shape_contract(x=spec("", "int", allow_weak=True))
        def f(x):
            return x + 1

        f(jnp.asarray(0))

    def test_dict_and_attribute_contracts(self):
        @shape_contract(batch={"ids": spec("B,L", "int")})
        def f(batch):
            return batch["ids"]

        f({"ids": jnp.zeros((2, 3), jnp.int32), "extra": 1})
        with pytest.raises(ContractError, match="missing required key"):
            f({"other": jnp.zeros((2, 3), jnp.int32)})

        class Carrier:
            step = jnp.asarray(7, jnp.int32)

        @shape_contract(state={"step": spec("", jnp.int32)})
        def g(state):
            return state.step

        g(Carrier())
        with pytest.raises(ContractError, match="no attribute"):
            g(object())

    def test_checked_once_per_trace_no_steady_state_sync(self):
        """Under jit the wrapper body runs at TRACE time only: same-shape
        calls hit the jit cache and never re-enter the contract check —
        the zero-steady-state-cost property."""

        @shape_contract(x=spec("B,L", "float"))
        def f(x):
            return x * 2.0

        jf = jax.jit(f)
        for _ in range(4):
            jf(jnp.ones((2, 3))).block_until_ready()
        assert f.contract_checks == 1
        # a new static shape is a new trace: checked exactly once more
        jf(jnp.ones((2, 5))).block_until_ready()
        assert f.contract_checks == 2

    def test_violation_raises_at_trace_time_under_jit(self):
        @shape_contract(x=spec("B,L", "int"))
        def f(x):
            return x.sum()

        with pytest.raises(ContractError, match="dtype"):
            jax.jit(f)(jnp.ones((2, 3), jnp.float32))


# ---------------------------------------------------------------------------
# the acceptance pincer: weak scalar into the jitted train step


class TestWeakStepPincer:
    FIXTURE = """
        import jax
        import jax.numpy as jnp

        def resume(state, train_step, batches):
            # restoring a counter without a dtype: weak int32 — the jit
            # cache sees a different signature than the strong int32 the
            # step returns, so every shape compiles twice
            state = state.replace(step=jnp.array(0))
            for b in batches:
                state, loss = train_step(state, b)
            return state
        """

    def _state_and_step(self):
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state, make_train_step

        mc = Code2VecConfig(
            terminal_count=30,
            path_count=20,
            label_count=5,
            terminal_embed_size=8,
            path_embed_size=6,
            encode_size=16,
        )
        rng = np.random.default_rng(0)
        B, L = 4, 6
        batch = {
            "starts": jnp.asarray(
                rng.integers(1, 30, (B, L)).astype(np.int32)
            ),
            "paths": jnp.asarray(rng.integers(1, 20, (B, L)).astype(np.int32)),
            "ends": jnp.asarray(rng.integers(1, 30, (B, L)).astype(np.int32)),
            "labels": jnp.asarray(rng.integers(0, 5, B).astype(np.int32)),
            "example_mask": jnp.ones((B,), jnp.float32),
        }
        state = create_train_state(
            TrainConfig(batch_size=B), mc, jax.random.PRNGKey(0), batch
        )
        step = make_train_step(mc, jnp.ones((5,), jnp.float32))
        return state, step, batch

    def test_static_arm_jaxlint_flags_the_fixture(self):
        findings = lint(self.FIXTURE)
        assert "JX001" in rule_ids(findings)

    def test_dynamic_arm_contract_rejects_at_trace_time(self):
        state, step, batch = self._state_and_step()
        # healthy state passes (and the loss is finite)
        new_state, loss = step(state, batch)
        assert np.isfinite(float(loss))
        # the PR-4 defect, resurrected deliberately: a weak-typed counter
        weak = state.replace(step=jnp.asarray(0))
        with pytest.raises(ContractError, match=r"WEAK.*JX001"):
            step(weak, batch)

    def test_shape_skew_rejected_at_trace_time(self):
        state, step, batch = self._state_and_step()
        skewed = dict(batch, labels=jnp.zeros((7,), jnp.int32))
        with pytest.raises(ContractError, match="B="):
            step(state, skewed)


# ---------------------------------------------------------------------------
# recompile → lint-rule correlation hint


class TestRecompileHint:
    class _Events:
        def __init__(self):
            self.events = []

        def emit(self, kind, **fields):
            self.events.append((kind, fields))

    class _FakeJit:
        def __init__(self):
            self.size = 1

        def _cache_size(self):
            return self.size

    def test_recompile_event_carries_lint_hints(self):
        from code2vec_tpu.obs.runtime import RecompileDetector

        events = self._Events()
        det = RecompileDetector(events=events)
        fn = self._FakeJit()
        det.track("train_step", fn)
        assert det.check() == 0  # warmup observation
        fn.size = 3
        assert det.check() == 2
        (kind, fields), = events.events
        assert kind == "recompile"
        assert fields["lint_hints"] == sorted(jaxlint.RECOMPILE_HINT_RULES)
        assert "JX001" in fields["lint_hints"]

    def test_hint_rules_exist_in_rule_table(self):
        for rid in jaxlint.RECOMPILE_HINT_RULES:
            assert rid in jaxlint.RULES
