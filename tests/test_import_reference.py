"""Importing a reference torch checkpoint (tools/import_reference_checkpoint).

The state_dict fixture mirrors the exact tensor layout the reference saves
(model/model.py:21-42 via torch.save(state_dict), main.py:231); the tool's
own parity probe (torch eval forward vs our deterministic forward on a
real batch) is the correctness oracle, and these tests pin the conversion
surface around it: happy path (both heads), dimension cross-checks, and
that the written directory serves predict-style restore + vector export.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

_TOOL = os.path.join(
    os.path.dirname(__file__), "..", "tools", "import_reference_checkpoint.py"
)
_EXPORT_TOOL = os.path.join(
    os.path.dirname(__file__), "..", "tools", "export_reference_checkpoint.py"
)


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tool():
    return _load(_TOOL, "_import_tool")


@pytest.fixture(scope="module")
def export_tool():
    return _load(_EXPORT_TOOL, "_export_tool")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    from code2vec_tpu.data.reader import load_corpus
    from code2vec_tpu.data.synth import SynthSpec, generate_corpus_files

    out = tmp_path_factory.mktemp("refckpt_ds")
    spec = SynthSpec(
        n_methods=30, n_terminals=50, n_paths=60, n_labels=10,
        mean_contexts=8.0, max_contexts=20, seed=7,
    )
    paths = generate_corpus_files(out, spec)
    data = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"], cache=False
    )
    return paths, data


def _make_state_dict(data, *, margin: bool, dt=12, dp=14, encode=16, seed=3):
    import torch

    g = torch.Generator().manual_seed(seed)
    T = len(data.terminal_vocab)
    P = len(data.path_vocab)
    L = len(data.label_vocab)
    sd = {
        "terminal_embedding.weight": torch.randn(T, dt, generator=g),
        "path_embedding.weight": torch.randn(P, dp, generator=g),
        "input_linear.weight": torch.randn(encode, 2 * dt + dp, generator=g) * 0.2,
        "input_layer_norm.weight": torch.rand(encode, generator=g) + 0.5,
        "input_layer_norm.bias": torch.randn(encode, generator=g) * 0.1,
        "attention_parameter": torch.randn(encode, generator=g) * 0.3,
    }
    if margin:
        sd["output_linear"] = torch.randn(L, encode, generator=g) * 0.2
    else:
        sd["output_linear.weight"] = torch.randn(L, encode, generator=g) * 0.2
        sd["output_linear.bias"] = torch.randn(L, generator=g) * 0.1
    return sd


def _run_tool(tool, tmp_path, paths, sd_path, extra=()):
    out_dir = tmp_path / "imported"
    tool.main(
        [
            "--reference_model", str(sd_path),
            "--corpus_path", paths["corpus"],
            "--terminal_idx_path", paths["terminal_idx"],
            "--path_idx_path", paths["path_idx"],
            "--model_path", str(out_dir),
            "--max_path_length", "20",
            "--no_corpus_cache",
            *extra,
        ]
    )
    return out_dir


def test_plain_head_import_round_trip(tool, dataset, tmp_path, capsys):
    import torch

    paths, data = dataset
    sd = _make_state_dict(data, margin=False)
    sd_path = tmp_path / "code2vec.model"
    torch.save(sd, sd_path)

    out_dir = _run_tool(tool, tmp_path, paths, sd_path)

    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["probe_max_abs_logit_diff"] < 2e-4
    assert report["angular_margin_loss"] is False
    assert os.path.exists(os.path.join(out_dir, "model_meta.json"))
    assert os.path.exists(os.path.join(out_dir, "label_vocab.txt"))

    # the written dir restores through the normal checkpoint surface and
    # reproduces the torch tensors exactly (conversion is lossless)
    import jax

    from code2vec_tpu.checkpoint import restore_checkpoint
    from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    model_config = Code2VecConfig(
        terminal_count=len(data.terminal_vocab),
        path_count=len(data.path_vocab),
        label_count=len(data.label_vocab),
        terminal_embed_size=12, path_embed_size=14, encode_size=16,
        vocab_pad_multiple=1,
    )
    config = TrainConfig(batch_size=4, max_path_length=20)
    rng = np.random.default_rng(0)
    epoch = build_method_epoch(data, np.arange(4), 20, rng)
    batch = next(iter_batches(epoch, 4, rng=rng, pad_final=False))
    template = create_train_state(
        config, model_config, jax.random.PRNGKey(0), batch
    )
    restored, meta = restore_checkpoint(str(out_dir), template, prefer_best=True)
    restored = {"params": restored.params}
    emb = np.asarray(restored["params"]["terminal_embedding"]["embedding"])
    np.testing.assert_array_equal(
        emb, sd["terminal_embedding.weight"].numpy()
    )
    kern = np.asarray(restored["params"]["input_dense"]["kernel"])
    np.testing.assert_array_equal(kern, sd["input_linear.weight"].numpy().T)
    assert meta.vocab_pad_multiple == 1


def test_margin_head_import(tool, dataset, tmp_path, capsys):
    import torch

    paths, data = dataset
    sd = _make_state_dict(data, margin=True)
    sd_path = tmp_path / "code2vec.model"
    torch.save(sd, sd_path)

    out_dir = _run_tool(tool, tmp_path, paths, sd_path)
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["angular_margin_loss"] is True
    assert report["probe_max_abs_logit_diff"] < 2e-4
    meta = json.loads((out_dir / "model_meta.json").read_text())
    assert meta["angular_margin_loss"] is True


def test_dimension_mismatch_refuses(tool, dataset, tmp_path):
    import torch

    paths, data = dataset
    sd = _make_state_dict(data, margin=False)
    # one extra label row: the corpus no longer matches the checkpoint
    sd["output_linear.weight"] = torch.randn(len(data.label_vocab) + 1, 16)
    sd["output_linear.bias"] = torch.randn(len(data.label_vocab) + 1)
    sd_path = tmp_path / "code2vec.model"
    torch.save(sd, sd_path)

    with pytest.raises(SystemExit, match="do not match"):
        _run_tool(tool, tmp_path, paths, sd_path)


def test_unknown_layout_refuses(tool, dataset, tmp_path):
    import torch

    paths, _data = dataset
    sd_path = tmp_path / "code2vec.model"
    torch.save({"some.other.weight": torch.zeros(3)}, sd_path)
    with pytest.raises(SystemExit, match="unrecognized state_dict layout"):
        _run_tool(tool, tmp_path, paths, sd_path)


@pytest.mark.parametrize("margin", [False, True], ids=["plain", "margin"])
def test_export_round_trips_to_reference_format(
    tool, export_tool, dataset, tmp_path, capsys, margin
):
    """ours → theirs (tools/export_reference_checkpoint): importing a
    state_dict and exporting it back reproduces every tensor exactly —
    the conversion is lossless in both directions."""
    import torch

    paths, data = dataset
    sd = _make_state_dict(data, margin=margin)
    sd_path = tmp_path / "code2vec.model"
    torch.save(sd, sd_path)
    out_dir = _run_tool(tool, tmp_path, paths, sd_path)
    capsys.readouterr()

    rt_path = tmp_path / "roundtrip.model"
    export_tool.main(
        ["--model_path", str(out_dir), "--output", str(rt_path)]
    )
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["probe_max_abs_logit_diff"] < 2e-4
    assert report["angular_margin_loss"] is margin

    rt = torch.load(rt_path, map_location="cpu", weights_only=True)
    assert set(rt) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(
            rt[k].numpy(), sd[k].numpy(), err_msg=k
        )


def test_export_slices_vocab_padding(export_tool, dataset, tmp_path, capsys):
    """A model trained with vocab_pad_multiple > 1 (sharded tables) exports
    with the pad rows/head columns sliced off — the reference has no
    padding, and pad ids never receive gradient, so the slice is exact."""
    import jax
    import torch

    from code2vec_tpu.checkpoint import TrainMeta, save_checkpoint
    from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches
    from code2vec_tpu.models.code2vec import Code2VecConfig
    from code2vec_tpu.predict import save_inference_meta
    from code2vec_tpu.train.config import TrainConfig
    from code2vec_tpu.train.step import create_train_state

    _paths, data = dataset
    pad = 8  # vocab sizes here are not multiples of 8 -> real pad rows
    model_config = Code2VecConfig(
        terminal_count=len(data.terminal_vocab),
        path_count=len(data.path_vocab),
        label_count=len(data.label_vocab),
        terminal_embed_size=12, path_embed_size=14, encode_size=16,
        vocab_pad_multiple=pad,
    )
    assert model_config.padded(model_config.terminal_count) > model_config.terminal_count
    config = TrainConfig(
        batch_size=4, max_path_length=20,
        terminal_embed_size=12, path_embed_size=14, encode_size=16,
        vocab_pad_multiple=pad, infer_method_name=True,
    )
    rng = np.random.default_rng(1)
    epoch = build_method_epoch(data, np.arange(4), 20, rng)
    batch = next(iter_batches(epoch, 4, rng=rng, pad_final=False))
    state = create_train_state(config, model_config, jax.random.PRNGKey(2), batch)

    out_dir = tmp_path / "padded_model"
    os.makedirs(out_dir)
    save_checkpoint(
        str(out_dir), state,
        TrainMeta(rng_impl=config.rng_impl, vocab_pad_multiple=pad),
        slot="best",
    )
    save_inference_meta(str(out_dir), config, model_config, data)

    rt_path = tmp_path / "padded.model"
    export_tool.main(["--model_path", str(out_dir), "--output", str(rt_path)])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["probe_max_abs_logit_diff"] < 2e-4

    rt = torch.load(rt_path, map_location="cpu", weights_only=True)
    T, L = len(data.terminal_vocab), len(data.label_vocab)
    assert rt["terminal_embedding.weight"].shape == (T, 12)
    assert rt["path_embedding.weight"].shape == (len(data.path_vocab), 14)
    assert rt["output_linear.weight"].shape == (L, 16)
    # the kept rows/columns are exactly the unpadded slices of the params
    np.testing.assert_array_equal(
        rt["terminal_embedding.weight"].numpy(),
        np.asarray(state.params["terminal_embedding"]["embedding"])[:T],
    )
    np.testing.assert_array_equal(
        rt["output_linear.weight"].numpy(),
        np.asarray(state.params["output_dense"]["kernel"]).T[:L],
    )


def test_exports_vectors_from_imported_checkpoint(tool, dataset, tmp_path, capsys):
    """The imported dir plugs into --export_only: code.vec comes out with
    one row per corpus method (the switcher's first smoke test)."""
    import torch

    paths, data = dataset
    sd = _make_state_dict(data, margin=False)
    sd_path = tmp_path / "code2vec.model"
    torch.save(sd, sd_path)
    out_dir = _run_tool(tool, tmp_path, paths, sd_path)
    capsys.readouterr()

    from code2vec_tpu.export import export_from_checkpoint
    from code2vec_tpu.train.config import TrainConfig

    config = TrainConfig(
        batch_size=8, max_path_length=20,
        terminal_embed_size=12, path_embed_size=14, encode_size=16,
    )
    vec_path = tmp_path / "code.vec"
    export_from_checkpoint(config, data, str(out_dir), str(vec_path))
    lines = vec_path.read_text().strip().splitlines()
    assert len(lines) == data.n_items + 1  # header + one row per method
