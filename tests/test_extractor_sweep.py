"""Bulk extractor hardening: a seeded grammar-derived Java generator
(productions follow the constructs pinned by the golden corpus in
test_extractor.py) sweeps hundreds of random programs through
extract_source. Every generated program is valid supported Java, so any
exception is an extractor bug; methods with bodies must produce contexts.

Also pins support for the modern (Java 10-21) constructs the reference's
javaparser 3.6.17 predates, including the pre-14 compatibility readings
('yield' as a method/variable name outside switch expressions).
"""

import numpy as np
import pytest

from code2vec_tpu.extractor import extract_source


class JavaGen:
    """Random program generator over the extractor's supported grammar."""

    TYPES = ["int", "long", "double", "boolean", "String", "int[]"]
    BINOPS = ["+", "-", "*", "/", "%", "<", ">", "<=", ">=", "==", "!=", "&&", "||", "&", "|", "^", "<<", ">>"]

    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)
        self.uid = 0

    def pick(self, xs):
        return xs[int(self.rng.integers(0, len(xs)))]

    def name(self, prefix):
        self.uid += 1
        return f"{prefix}{self.uid}"

    def expr(self, depth=0):
        r = self.rng.random()
        if depth > 2 or r < 0.25:
            return self.pick([
                str(int(self.rng.integers(0, 100))),
                f"{float(self.rng.random()):.2f}",
                '"s"', "true", "false", "null", "x", "y", "this.x",
            ])
        if r < 0.45:
            return f"({self.expr(depth + 1)} {self.pick(self.BINOPS)} {self.expr(depth + 1)})"
        if r < 0.55:
            # parenthesized operand: "-" + "-x" must not fuse into "--x"
            return f"{self.pick(['-', '!', '~'])}({self.expr(depth + 1)})"
        if r < 0.65:
            return f"({self.expr(depth + 1)} {self.pick(['<', '>'])} 0 ? {self.expr(depth + 1)} : {self.expr(depth + 1)})"
        if r < 0.75:
            args = ", ".join(self.expr(depth + 1) for _ in range(int(self.rng.integers(0, 3))))
            return f"{self.pick(['helper', 'Math.max', 'Math.abs', 'String.valueOf'])}({args})"
        if r < 0.82:
            return f"new int[]{{{self.expr(depth + 1)}, {self.expr(depth + 1)}}}"
        if r < 0.88:
            return f"((int) {self.expr(depth + 1)})"
        if r < 0.94:
            return f'("a" + {self.expr(depth + 1)})'
        return f"new java.util.ArrayList<String>().size()"

    def stmt(self, depth=0):
        r = self.rng.random()
        ind = "        "
        if depth > 2 or r < 0.25:
            ty = self.pick(["int", "var", "long", "double"])
            init = self.expr() if ty != "var" else str(int(self.rng.integers(1, 50)))
            return f"{ind}{ty} {self.name('v')} = {init};\n"
        if r < 0.4:
            body = self.stmt(depth + 1)
            return f"{ind}if ({self.expr()} > 0) {{\n{body}{ind}}} else {{\n{self.stmt(depth + 1)}{ind}}}\n"
        if r < 0.5:
            i = self.name("i")
            return f"{ind}for (int {i} = 0; {i} < 10; {i}++) {{\n{self.stmt(depth + 1)}{ind}}}\n"
        if r < 0.58:
            w = self.name("w")
            return f"{ind}int {w} = 5;\n{ind}while ({w} > 0) {{\n{ind}    {w}--;\n{ind}}}\n"
        if r < 0.66:
            return (
                f"{ind}switch ((int) {self.expr()}) {{\n"
                f"{ind}case 0:\n{self.stmt(depth + 1)}{ind}    break;\n"
                f"{ind}default:\n{ind}    break;\n{ind}}}\n"
            )
        if r < 0.74:
            e = self.name("e")
            return (
                f"{ind}try {{\n{self.stmt(depth + 1)}{ind}}} "
                f"catch (RuntimeException | IllegalStateException {e}) {{\n"
                f"{ind}}} finally {{\n{ind}}}\n"
            )
        if r < 0.8:
            a = self.name("a")
            v = self.name("e")
            return (
                f"{ind}int[] {a} = new int[4];\n"
                f"{ind}for (int {v} : {a}) {{\n{self.stmt(depth + 1)}{ind}}}\n"
            )
        if r < 0.86:
            rn = self.name("r")
            return (
                f"{ind}Runnable {rn} = () -> {{\n{ind}    int q = 1;\n{ind}}};\n"
                f"{ind}{rn}.run();\n"
            )
        if r < 0.88:
            d = self.name("d")
            return f"{ind}int {d} = 3;\n{ind}do {{\n{ind}    {d}--;\n{ind}}} while ({d} > 0);\n"
        if r < 0.92:  # Java 14 switch expression with arrow entries + yield
            s = self.name("s")
            return (
                f"{ind}int {s} = switch ((int) {self.expr()}) {{\n"
                f"{ind}    case 0 -> {self.expr()};\n"
                f"{ind}    case 1, 2 -> ({self.expr()});\n"
                f"{ind}    default -> {{ yield (int) {self.expr()}; }}\n"
                f"{ind}}};\n"
            )
        if r < 0.96:  # Java 16 instanceof pattern
            o, b = self.name("o"), self.name("b")
            return (
                f"{ind}Object {o} = \"z\";\n"
                f"{ind}if ({o} instanceof String {b} && {b}.length() > 0) {{\n"
                f"{ind}    {b}.isEmpty();\n{ind}}}\n"
            )
        if r < 0.98:  # Java 15 text block
            t = self.name("t")
            return f'{ind}String {t} = """\n{ind}    line "a"\n{ind}    b""";\n'
        return f"{ind}{self.expr()};\n"

    def method(self):
        ret = self.pick(self.TYPES + ["void"])
        name = self.name("method")
        params = ", ".join(
            f"{self.pick(self.TYPES)} {self.name('p')}"
            for _ in range(int(self.rng.integers(0, 4)))
        )
        body = "".join(self.stmt() for _ in range(int(self.rng.integers(1, 5))))
        if ret == "void":
            ret_stmt = "        return;\n"
        elif ret == "boolean":
            ret_stmt = "        return false;\n"
        elif ret == "String":
            ret_stmt = '        return "r";\n'
        elif ret == "int[]":
            ret_stmt = "        return new int[0];\n"
        else:
            ret_stmt = f"        return ({ret}) 0;\n"
        mods = self.pick(["public ", "private ", "protected ", "", "public static ", "static final "])
        generics = self.pick(["", "", "", "<T> "]) if "static" not in mods else ""
        if generics:
            ret = "T" if ret not in ("void",) and self.rng.random() < 0.3 else ret
            if ret == "T":
                ret_stmt = "        return null;\n"
        return f"    {mods}{generics}{ret} {name}({params}) {{\n{body}{ret_stmt}    }}\n"

    def clazz(self):
        name = self.name("Widget")
        fields = "".join(
            f"    private {self.pick(self.TYPES)} {f} = {self.expr() if self.rng.random() < 0.5 else '0'};\n"
            if self.pick(self.TYPES) in ("int", "long", "double")
            else f"    int {f};\n"
            for f in ("x", "y")
        )
        methods = "".join(self.method() for _ in range(int(self.rng.integers(1, 4))))
        helper = "    int helper(int a, int b) { return a + b; }\n"
        ctor = f"    {name}() {{ this.x = 1; }}\n"
        inner = ""
        if self.rng.random() < 0.3:
            inner = (
                "    static class Inner {\n"
                "        int twice(int v) { return v * 2; }\n"
                "    }\n"
            )
        anon = ""
        if self.rng.random() < 0.3:
            anon = (
                "    Object listener = new Object() {\n"
                "        public int hear(int s) { return s + 1; }\n"
                "    };\n"
            )
        extras = ""
        if self.rng.random() < 0.2:
            extras = "enum Color { RED, GREEN; int idx() { return ordinal(); } }\n"
        if self.rng.random() < 0.2:
            extras += (
                "interface Op {\n"
                "    int apply(int v);\n"
                "    default int applyTwice(int v) { return apply(apply(v)); }\n"
            "}\n"
            )
        if self.rng.random() < 0.2:  # Java 16 record + compact constructor
            extras += (
                "record Pair(int a, int b) {\n"
                "    Pair { if (a > b) throw new IllegalArgumentException(); }\n"
                "    int total() { return a + b; }\n"
                "}\n"
            )
        return (
            "package sweep;\n"
            "import java.util.List;\n"
            f"public class {name} {{\n{fields}{ctor}{helper}{methods}{inner}{anon}}}\n"
            f"{extras}"
        )


class TestGeneratedSweep:
    @pytest.mark.parametrize("seed", range(0, 200, 10))
    def test_crash_free_and_extracts(self, seed):
        gen = JavaGen(seed)
        for i in range(20):
            src = gen.clazz()
            try:
                result = extract_source(src)
            except Exception as e:  # noqa: BLE001 - the assertion IS the test
                pytest.fail(
                    f"extractor crashed on generated program (seed={seed}, "
                    f"i={i}): {e}\n----\n{src}"
                )
            labels = [m.label for m in result.methods]
            # helper + ctor-filtered methods: at least the helper and one
            # generated method must come through with contexts
            assert "helper" in labels, f"helper missing from {labels}\n{src}"
            for m in result.methods:
                assert m.path_contexts, f"no contexts for {m.label}\n{src}"


class TestModernConstructSupport:
    """Modern Java (10-21) constructs the reference's javaparser 3.6.17
    predates — parsed and extracted, not rejected (detailed path-set golden
    tests live in test_extractor.py::TestModernJava)."""

    CASES = {
        "record": "record Point(int x, int y) { int dist() { return x * x + y * y; } }",
        "sealed": "sealed class A permits B { int f(int x) { return x; } }",
        "non_sealed": "non-sealed class A extends B { int f(int x) { return x; } }",
        "switch_expr": "class A { int f(int d) { int n = switch (d) { case 1 -> 1; default -> 0; }; return n; } }",
        "text_block": 'class A { String f(String p) { return p + """\nx "quoted"\n"""; } }',
        "yield": "class A { int f(int d) { return switch (d) { case 1: yield 10; default: yield 0; }; } }",
        "instanceof_pattern": "class A { int f(Object o) { if (o instanceof Integer n && n > 0) return n; return 0; } }",
        "guarded_pattern": 'class A { int f(Object o) { return switch (o) { case String s when s.isEmpty() -> 1; default -> 0; }; } }',
        "local_record": "class A { int f(int x) { record P(int v) { } return new P(x).v(); } }",
        "compact_ctor": "record R(int x) { R { if (x < 0) throw new IllegalArgumentException(); } int f() { return x; } }",
        # review regressions: enum-constant arrow labels must not parse as
        # lambdas; 'case null, default' is the JLS 21 null idiom; a
        # parenthesized yield operand is a YieldStmt, not a call (JLS 14.8)
        "enum_arrow_label": "class A { enum E { FOO, BAR } int f(E c) { return switch (c) { case FOO -> 1; case BAR -> 2; default -> 0; }; } }",
        "case_null_default": "class A { int f(Object o) { return switch (o) { case String s -> 1; case null, default -> 0; }; } }",
        "yield_paren_cast": "class A { int f(int d) { return switch (d) { default: yield (Integer) d; }; } }",
        "yield_paren_expr": "class A { int f(int d) { return switch (d) { default: yield (d + 1) * 2; }; } }",
        "yield_prefix_incr": "class A { int f(int d) { return switch (d) { default: yield ++d; }; } }",
        # pre-Java-14 readings survive outside switch expressions
        "yield_method_call": "class T { void f() { yield(); } }",
        "yield_variable": "class A { int f(int yield) { yield = 3; yield++; return yield; } }",
        # pre-Java-17: a class actually named 'sealed' keeps its type reading
        "class_named_sealed": "class sealed { } class A { sealed s; int f(int x) { return x; } }",
    }

    @pytest.mark.parametrize("name", CASES)
    def test_parses_and_extracts(self, name):
        res = extract_source(self.CASES[name], "f" if "f(" in self.CASES[name] else "*")
        assert res.methods, f"no methods extracted for {name}"
        for m in res.methods:
            assert m.path_contexts, f"no contexts for {m.label} in {name}"

    def test_var_and_switch_statement_still_supported(self):
        res = extract_source(
            "class A { int f(int d) { var x = d; "
            "switch (x) { case 1: return 1; default: break; } return 0; } }"
        )
        assert [m.label for m in res.methods] == ["f"]

    def test_var_is_vartype_leaf_terminal(self):
        res = extract_source("class A { int f(int d) { var x = d; return x; } }")
        assert "var" in res.terminal_vocab.values()

    def test_text_block_stays_single_line_unnormalized(self):
        # terminals are emitted on line-oriented surfaces; raw newlines in
        # a text block lexeme would corrupt terminal_idxs.txt / the ctypes
        # blob when --no-normalize-string is set
        res = extract_source(
            'class A { String f() { return """\nab "c"\nd"""; } }',
            "f", normalize_string=False,
        )
        terms = set(res.terminal_vocab.values())
        assert not [t for t in terms if "\n" in t]
        assert any("ab" in t and "\\n" in t for t in terms)

    def test_pattern_bindings_are_anonymized(self):
        res = extract_source(self.CASES["guarded_pattern"], "f")
        m = res.methods[0]
        assert ("s", "@var_1") in m.aliases
        used = {res.terminal_vocab[s] for s, _, e in m.path_contexts} | {
            res.terminal_vocab[e] for _, _, e in m.path_contexts
        }
        assert "s" not in used  # never leaks the raw binding name

    def test_pattern_binding_is_arm_scoped(self):
        # 'case String s ->' must not capture the same-named field
        # reference in a sibling arm (Java scopes the binding to its arm)
        res = extract_source(
            "class A { int s; int f(Object o) { return switch (o) "
            "{ case String s -> s.length(); default -> s; }; } }", "f")
        terms = set(res.terminal_vocab.values())
        assert "s" in terms  # the default arm's field ref stays raw
        assert ("s", "@var_1") in res.methods[0].aliases  # own arm resolves
