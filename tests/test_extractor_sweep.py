"""Bulk extractor hardening: a seeded grammar-derived Java generator
(productions follow the constructs pinned by the golden corpus in
test_extractor.py) sweeps hundreds of random programs through
extract_source. Every generated program is valid supported Java, so any
exception is an extractor bug; methods with bodies must produce contexts.

Also pins the explicit reject-with-message behavior for modern constructs
the parser deliberately does not cover (parser.h "out of scope" list).
"""

import numpy as np
import pytest

from code2vec_tpu.extractor import extract_source


class JavaGen:
    """Random program generator over the extractor's supported grammar."""

    TYPES = ["int", "long", "double", "boolean", "String", "int[]"]
    BINOPS = ["+", "-", "*", "/", "%", "<", ">", "<=", ">=", "==", "!=", "&&", "||", "&", "|", "^", "<<", ">>"]

    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)
        self.uid = 0

    def pick(self, xs):
        return xs[int(self.rng.integers(0, len(xs)))]

    def name(self, prefix):
        self.uid += 1
        return f"{prefix}{self.uid}"

    def expr(self, depth=0):
        r = self.rng.random()
        if depth > 2 or r < 0.25:
            return self.pick([
                str(int(self.rng.integers(0, 100))),
                f"{float(self.rng.random()):.2f}",
                '"s"', "true", "false", "null", "x", "y", "this.x",
            ])
        if r < 0.45:
            return f"({self.expr(depth + 1)} {self.pick(self.BINOPS)} {self.expr(depth + 1)})"
        if r < 0.55:
            # parenthesized operand: "-" + "-x" must not fuse into "--x"
            return f"{self.pick(['-', '!', '~'])}({self.expr(depth + 1)})"
        if r < 0.65:
            return f"({self.expr(depth + 1)} {self.pick(['<', '>'])} 0 ? {self.expr(depth + 1)} : {self.expr(depth + 1)})"
        if r < 0.75:
            args = ", ".join(self.expr(depth + 1) for _ in range(int(self.rng.integers(0, 3))))
            return f"{self.pick(['helper', 'Math.max', 'Math.abs', 'String.valueOf'])}({args})"
        if r < 0.82:
            return f"new int[]{{{self.expr(depth + 1)}, {self.expr(depth + 1)}}}"
        if r < 0.88:
            return f"((int) {self.expr(depth + 1)})"
        if r < 0.94:
            return f'("a" + {self.expr(depth + 1)})'
        return f"new java.util.ArrayList<String>().size()"

    def stmt(self, depth=0):
        r = self.rng.random()
        ind = "        "
        if depth > 2 or r < 0.25:
            ty = self.pick(["int", "var", "long", "double"])
            init = self.expr() if ty != "var" else str(int(self.rng.integers(1, 50)))
            return f"{ind}{ty} {self.name('v')} = {init};\n"
        if r < 0.4:
            body = self.stmt(depth + 1)
            return f"{ind}if ({self.expr()} > 0) {{\n{body}{ind}}} else {{\n{self.stmt(depth + 1)}{ind}}}\n"
        if r < 0.5:
            i = self.name("i")
            return f"{ind}for (int {i} = 0; {i} < 10; {i}++) {{\n{self.stmt(depth + 1)}{ind}}}\n"
        if r < 0.58:
            w = self.name("w")
            return f"{ind}int {w} = 5;\n{ind}while ({w} > 0) {{\n{ind}    {w}--;\n{ind}}}\n"
        if r < 0.66:
            return (
                f"{ind}switch ((int) {self.expr()}) {{\n"
                f"{ind}case 0:\n{self.stmt(depth + 1)}{ind}    break;\n"
                f"{ind}default:\n{ind}    break;\n{ind}}}\n"
            )
        if r < 0.74:
            e = self.name("e")
            return (
                f"{ind}try {{\n{self.stmt(depth + 1)}{ind}}} "
                f"catch (RuntimeException | IllegalStateException {e}) {{\n"
                f"{ind}}} finally {{\n{ind}}}\n"
            )
        if r < 0.8:
            a = self.name("a")
            v = self.name("e")
            return (
                f"{ind}int[] {a} = new int[4];\n"
                f"{ind}for (int {v} : {a}) {{\n{self.stmt(depth + 1)}{ind}}}\n"
            )
        if r < 0.86:
            rn = self.name("r")
            return (
                f"{ind}Runnable {rn} = () -> {{\n{ind}    int q = 1;\n{ind}}};\n"
                f"{ind}{rn}.run();\n"
            )
        if r < 0.92:
            d = self.name("d")
            return f"{ind}int {d} = 3;\n{ind}do {{\n{ind}    {d}--;\n{ind}}} while ({d} > 0);\n"
        return f"{ind}{self.expr()};\n"

    def method(self):
        ret = self.pick(self.TYPES + ["void"])
        name = self.name("method")
        params = ", ".join(
            f"{self.pick(self.TYPES)} {self.name('p')}"
            for _ in range(int(self.rng.integers(0, 4)))
        )
        body = "".join(self.stmt() for _ in range(int(self.rng.integers(1, 5))))
        if ret == "void":
            ret_stmt = "        return;\n"
        elif ret == "boolean":
            ret_stmt = "        return false;\n"
        elif ret == "String":
            ret_stmt = '        return "r";\n'
        elif ret == "int[]":
            ret_stmt = "        return new int[0];\n"
        else:
            ret_stmt = f"        return ({ret}) 0;\n"
        mods = self.pick(["public ", "private ", "protected ", "", "public static ", "static final "])
        generics = self.pick(["", "", "", "<T> "]) if "static" not in mods else ""
        if generics:
            ret = "T" if ret not in ("void",) and self.rng.random() < 0.3 else ret
            if ret == "T":
                ret_stmt = "        return null;\n"
        return f"    {mods}{generics}{ret} {name}({params}) {{\n{body}{ret_stmt}    }}\n"

    def clazz(self):
        name = self.name("Widget")
        fields = "".join(
            f"    private {self.pick(self.TYPES)} {f} = {self.expr() if self.rng.random() < 0.5 else '0'};\n"
            if self.pick(self.TYPES) in ("int", "long", "double")
            else f"    int {f};\n"
            for f in ("x", "y")
        )
        methods = "".join(self.method() for _ in range(int(self.rng.integers(1, 4))))
        helper = "    int helper(int a, int b) { return a + b; }\n"
        ctor = f"    {name}() {{ this.x = 1; }}\n"
        inner = ""
        if self.rng.random() < 0.3:
            inner = (
                "    static class Inner {\n"
                "        int twice(int v) { return v * 2; }\n"
                "    }\n"
            )
        anon = ""
        if self.rng.random() < 0.3:
            anon = (
                "    Object listener = new Object() {\n"
                "        public int hear(int s) { return s + 1; }\n"
                "    };\n"
            )
        extras = ""
        if self.rng.random() < 0.2:
            extras = "enum Color { RED, GREEN; int idx() { return ordinal(); } }\n"
        if self.rng.random() < 0.2:
            extras += (
                "interface Op {\n"
                "    int apply(int v);\n"
                "    default int applyTwice(int v) { return apply(apply(v)); }\n"
            "}\n"
            )
        return (
            "package sweep;\n"
            "import java.util.List;\n"
            f"public class {name} {{\n{fields}{ctor}{helper}{methods}{inner}{anon}}}\n"
            f"{extras}"
        )


class TestGeneratedSweep:
    @pytest.mark.parametrize("seed", range(0, 200, 10))
    def test_crash_free_and_extracts(self, seed):
        gen = JavaGen(seed)
        for i in range(20):
            src = gen.clazz()
            try:
                result = extract_source(src)
            except Exception as e:  # noqa: BLE001 - the assertion IS the test
                pytest.fail(
                    f"extractor crashed on generated program (seed={seed}, "
                    f"i={i}): {e}\n----\n{src}"
                )
            labels = [m.label for m in result.methods]
            # helper + ctor-filtered methods: at least the helper and one
            # generated method must come through with contexts
            assert "helper" in labels, f"helper missing from {labels}\n{src}"
            for m in result.methods:
                assert m.path_contexts, f"no contexts for {m.label}\n{src}"


class TestModernConstructRejects:
    CASES = {
        "record Point(int x, int y) { }": "record",
        "sealed class A permits B { }": "sealed",
        "non-sealed class A extends B { }": "sealed",
        "class A { int f(int d) { int n = switch (d) { case 1 -> 1; default -> 0; }; return n; } }": "switch *expressions*",
        'class A { String f() { return """\nx\n"""; } }': "text blocks",
    }

    @pytest.mark.parametrize("src,needle", CASES.items())
    def test_rejected_with_construct_name(self, src, needle):
        with pytest.raises(ValueError, match="not supported") as err:
            extract_source(src)
        assert needle in str(err.value)

    def test_var_and_switch_statement_still_supported(self):
        res = extract_source(
            "class A { int f(int d) { var x = d; "
            "switch (x) { case 1: return 1; default: break; } return 0; } }"
        )
        assert [m.label for m in res.methods] == ["f"]
