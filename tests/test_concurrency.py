"""Tests for the concurrency sanitizer: the CX static rule family
(paired positive/negative AST fixtures per rule, suppression handling,
the repo-wide CX002 graph), the traced-lock runtime (order-cycle
detection, RLock reentrancy, contention metrics, the plain-primitives
default pinned to the exact ``threading`` types), the fork-safety guard
(message pinned; ``parallel_feed`` proven guarded by the static rule),
and schedule-stressing runs of the REAL batcher / router / swap
controller / result cache with the sanitizer on — zero violations.

The fleet-level end-to-end (2 subprocess replicas, rolling swap under
load, sanitizer on in router AND workers) lives in
``tests/test_fleet.py::test_fleet_rolling_swap_with_lock_sanitizer``
next to the fleet it exercises.
"""

from __future__ import annotations

import textwrap
import threading
import time
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

from code2vec_tpu.analysis.concurrency import lint_concurrency
from code2vec_tpu.obs.runtime import RuntimeHealth, global_health
from code2vec_tpu.obs.sync import (
    SYNC_DEBUG_ENV,
    TracedCondition,
    TracedLock,
    TracedRLock,
    guard_fork_safety,
    make_condition,
    make_lock,
    make_rlock,
    register_event_log,
    reset_sync_state,
    sync_debug_enabled,
    sync_snapshot,
    violations,
)

pytestmark = pytest.mark.sync

REPO = Path(__file__).resolve().parents[1]


def lint(src: str):
    return lint_concurrency(textwrap.dedent(src), "mod.py")


def rule_ids(findings, *, include_suppressed=False):
    return {
        f.rule
        for f in findings
        if include_suppressed or not f.suppressed
    }


# ---------------------------------------------------------------------------
# CX001 unguarded shared state
# ---------------------------------------------------------------------------


class TestCX001UnguardedSharedState:
    def test_thread_written_attr_read_unguarded_flags(self):
        findings = lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        with self._lock:
                            self._count += 1

                def progress(self):
                    return self._count
            """
        )
        assert "CX001" in rule_ids(findings)
        (finding,) = [f for f in findings if f.rule == "CX001"]
        assert "_count" in finding.message

    def test_guarded_public_access_is_clean(self):
        findings = lint(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        with self._lock:
                            self._count += 1

                def progress(self):
                    with self._lock:
                        return self._count
            """
        )
        assert "CX001" not in rule_ids(findings)

    def test_no_thread_entry_no_finding(self):
        # same attr pattern but single-threaded by construction
        findings = lint(
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0

                def bump(self):
                    self._count += 1

                def progress(self):
                    return self._count
            """
        )
        assert "CX001" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# CX002 lock-order cycles
# ---------------------------------------------------------------------------


class TestCX002LockOrderCycle:
    def test_inverted_nesting_in_one_class_flags(self):
        findings = lint(
            """
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """
        )
        assert "CX002" in rule_ids(findings)

    def test_consistent_order_is_clean(self):
        findings = lint(
            """
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """
        )
        assert "CX002" not in rule_ids(findings)

    def test_cross_class_cycle_through_attr_calls_flags(self):
        # A holds its lock and calls into B; B holds its lock and calls
        # back into A — the cycle only exists in the JOINED graph
        findings = lint(
            """
            import threading

            class A:
                def __init__(self, b: "B"):
                    self._lock = threading.Lock()
                    self._b = b

                def touch(self):
                    with self._lock:
                        pass

                def kick(self):
                    with self._lock:
                        self._b.poke()

            class B:
                def __init__(self, a: "A"):
                    self._lock = threading.Lock()
                    self._a = a

                def poke(self):
                    with self._lock:
                        self._a.touch()
            """
        )
        assert "CX002" in rule_ids(findings)

    def test_rlock_reentry_through_self_call_is_clean(self):
        # engine.observe_width holds the RLock and calls prepare(), which
        # re-acquires the SAME RLock — reentrancy, not an inversion
        findings = lint(
            """
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.RLock()

                def prepare(self):
                    with self._lock:
                        pass

                def observe(self):
                    with self._lock:
                        self.prepare()
            """
        )
        assert "CX002" not in rule_ids(findings)

    def test_plain_lock_self_deadlock_flags(self):
        # the same shape with a NON-reentrant lock IS a self-deadlock
        findings = lint(
            """
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()

                def prepare(self):
                    with self._lock:
                        pass

                def observe(self):
                    with self._lock:
                        self.prepare()
            """
        )
        assert "CX002" in rule_ids(findings)


# ---------------------------------------------------------------------------
# CX003 blocking call under lock
# ---------------------------------------------------------------------------


class TestCX003BlockingUnderLock:
    def test_sleep_under_lock_flags(self):
        findings = lint(
            """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def slow(self):
                    with self._lock:
                        time.sleep(1.0)
            """
        )
        assert "CX003" in rule_ids(findings)

    def test_future_result_under_lock_flags(self):
        findings = lint(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def wait_on(self, future):
                    with self._lock:
                        return future.result()
            """
        )
        assert "CX003" in rule_ids(findings)

    def test_sleep_outside_lock_is_clean(self):
        findings = lint(
            """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def slow(self):
                    with self._lock:
                        pass
                    time.sleep(1.0)
            """
        )
        assert "CX003" not in rule_ids(findings)

    def test_inline_suppression_is_honored_and_counted(self):
        findings = lint(
            """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def slow(self):
                    with self._lock:
                        time.sleep(1.0)  # jaxlint: disable=CX003
            """
        )
        assert "CX003" not in rule_ids(findings)
        assert "CX003" in rule_ids(findings, include_suppressed=True)


# ---------------------------------------------------------------------------
# CX004 condition wait without predicate loop
# ---------------------------------------------------------------------------


class TestCX004ConditionWait:
    def test_bare_wait_flags(self):
        findings = lint(
            """
            import threading

            class D:
                def __init__(self):
                    self._cond = threading.Condition()

                def take(self):
                    with self._cond:
                        self._cond.wait()
            """
        )
        assert "CX004" in rule_ids(findings)

    def test_predicate_loop_is_clean(self):
        findings = lint(
            """
            import threading

            class D:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._items = []

                def take(self):
                    with self._cond:
                        while not self._items:
                            self._cond.wait()
                        return self._items.pop()
            """
        )
        assert "CX004" not in rule_ids(findings)

    def test_timeout_wait_is_clean(self):
        findings = lint(
            """
            import threading

            class D:
                def __init__(self):
                    self._cond = threading.Condition()

                def take(self):
                    with self._cond:
                        self._cond.wait(1.0)
            """
        )
        assert "CX004" not in rule_ids(findings)


# ---------------------------------------------------------------------------
# CX005 fork after threads
# ---------------------------------------------------------------------------


class TestCX005ForkAfterThreads:
    def test_unguarded_fork_context_flags(self):
        findings = lint(
            """
            import multiprocessing

            def boot():
                return multiprocessing.get_context("fork")
            """
        )
        assert "CX005" in rule_ids(findings)

    def test_guarded_fork_context_is_clean(self):
        findings = lint(
            """
            import multiprocessing

            from code2vec_tpu.obs.sync import guard_fork_safety

            def boot():
                guard_fork_safety("boot")
                return multiprocessing.get_context("fork")
            """
        )
        assert "CX005" not in rule_ids(findings)

    def test_spawn_context_is_clean(self):
        findings = lint(
            """
            import multiprocessing

            def boot():
                return multiprocessing.get_context("spawn")
            """
        )
        assert "CX005" not in rule_ids(findings)

    def test_parallel_feed_is_guarded(self):
        # the real FeedPool must carry its runtime guard — the static rule
        # and the runtime guard pin each other
        path = REPO / "code2vec_tpu" / "data" / "parallel_feed.py"
        findings = lint_concurrency(
            path.read_text(), "code2vec_tpu/data/parallel_feed.py"
        )
        assert "CX005" not in rule_ids(findings)
        assert "guard_fork_safety" in path.read_text()


# ---------------------------------------------------------------------------
# traced-lock runtime
# ---------------------------------------------------------------------------


@pytest.fixture
def sync_debug(monkeypatch):
    monkeypatch.setenv(SYNC_DEBUG_ENV, "1")
    reset_sync_state()
    yield
    reset_sync_state()


class TestFactoryDefaults:
    def test_disabled_returns_plain_primitives(self, monkeypatch):
        monkeypatch.delenv(SYNC_DEBUG_ENV, raising=False)
        assert not sync_debug_enabled()
        # EXACT plain types, zero attributes added: production serving
        # never pays for the sanitizer
        assert type(make_lock("x")) is type(threading.Lock())
        assert type(make_rlock("x")) is type(threading.RLock())
        assert type(make_condition("x")) is threading.Condition
        assert dir(make_lock("x")) == dir(threading.Lock())

    def test_falsy_env_values_stay_disabled(self, monkeypatch):
        for value in ("0", "false", "no", "off", ""):
            monkeypatch.setenv(SYNC_DEBUG_ENV, value)
            assert not sync_debug_enabled()
        monkeypatch.setenv(SYNC_DEBUG_ENV, "1")
        assert sync_debug_enabled()

    def test_enabled_returns_traced(self, sync_debug):
        assert isinstance(make_lock("a"), TracedLock)
        assert isinstance(make_rlock("a"), TracedRLock)
        assert isinstance(make_condition("a"), TracedCondition)


class TestOrderCycleDetection:
    def test_two_lock_inversion_fires_once(self, sync_debug):
        a, b = make_lock("a"), make_lock("b")
        with a:
            with b:
                pass
        with b:
            with a:  # inversion: b -> a after a -> b is on record
                pass
        recorded = violations()
        assert len(recorded) == 1
        v = recorded[0]
        assert v["lock"] == "a" and v["held"] == ["b"]
        assert v["other_thread"]  # provenance of the recorded a -> b edge
        # dedup: repeating the same inversion adds nothing
        with b:
            with a:
                pass
        assert len(violations()) == 1

    def test_three_thread_cycle_fires(self, sync_debug):
        a, b, c = make_lock("a"), make_lock("b"), make_lock("c")

        def nested(outer, inner):
            with outer:
                with inner:
                    pass

        # each leg on its own thread, joined sequentially: the graph is
        # a -> b -> c, and the third leg closes the cycle c -> a
        for outer, inner in ((a, b), (b, c), (c, a)):
            t = threading.Thread(target=nested, args=(outer, inner))
            t.start()
            t.join()
        recorded = violations()
        assert len(recorded) == 1
        assert recorded[0]["lock"] == "a" and recorded[0]["held"] == ["c"]
        snap = sync_snapshot()
        assert snap["enabled"] and snap["order_violations"] == 1
        assert snap["locks_tracked"] == 3

    def test_rlock_reentrancy_is_not_an_inversion(self, sync_debug):
        r, other = make_rlock("r"), make_lock("other")
        with r:
            with other:
                with r:  # reentrant re-acquire: no other -> r edge
                    pass
        with r:
            pass
        assert violations() == []

    def test_violation_emits_event_and_counter(self, sync_debug):
        emitted = []

        class _Log:
            def emit(self, kind, **fields):
                emitted.append((kind, fields))

        register_event_log(_Log())
        counter = global_health().counter("lock.order_violations")
        before = counter.value
        a, b = make_lock("ev.a"), make_lock("ev.b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert counter.value == before + 1
        (kind, fields), = emitted
        assert kind == "lock_order_violation"
        assert fields["lock"] == "ev.a" and fields["held"] == ["ev.b"]
        assert fields["stack"] and fields["other_stack"]


class TestContentionAndCondition:
    def test_contention_metrics_recorded(self, sync_debug):
        lock = make_lock("contended")
        counter = global_health().counter("lock.contended")
        before = counter.value
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                entered.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        assert entered.wait(5)
        waiter_done = threading.Event()

        def waiter():
            with lock:
                pass
            waiter_done.set()

        w = threading.Thread(target=waiter)
        w.start()
        time.sleep(0.05)  # let the waiter actually block
        release.set()
        assert waiter_done.wait(5)
        t.join(5)
        w.join(5)
        assert counter.value >= before + 1
        summary = global_health().latency("lock.wait_ms").summary()
        assert summary is not None and summary["count"] >= 1
        assert global_health().latency("lock.hold_ms").summary() is not None
        assert violations() == []

    def test_traced_condition_handoff(self, sync_debug):
        cond = make_condition("handoff")
        items: list[int] = []
        got: list[int] = []

        def consumer():
            with cond:
                while not items:
                    cond.wait(5)
                got.append(items.pop())

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        with cond:
            items.append(7)
            cond.notify_all()
        t.join(5)
        assert got == [7]
        assert violations() == []


# ---------------------------------------------------------------------------
# fork-safety guard
# ---------------------------------------------------------------------------


class TestForkGuard:
    def test_quiet_with_only_daemon_threads(self):
        assert guard_fork_safety("test") == []

    def test_offender_named_and_event_pinned(self):
        emitted = []

        class _Log:
            # first positional is the event name; "kind" arrives as a field
            def emit(self, event, **fields):
                emitted.append((event, fields))

        stop = threading.Event()
        t = threading.Thread(
            target=stop.wait, args=(10,), name="lingering-feeder",
            daemon=False,
        )
        t.start()
        try:
            offenders = guard_fork_safety("FeedPool", events=_Log())
        finally:
            stop.set()
            t.join(5)
        assert "lingering-feeder" in offenders
        (kind, fields), = emitted
        assert kind == "error"
        assert fields["where"] == "FeedPool"
        assert fields["kind"] == "fork_after_threads"
        assert "lingering-feeder" in fields["threads"]
        # the message is operator-facing: pin its load-bearing clauses
        assert "fork start-method requested while non-daemon threads" in (
            fields["message"]
        )
        assert "permanently frozen" in fields["message"]
        assert "start worker pools before serving/training threads" in (
            fields["message"]
        )


# ---------------------------------------------------------------------------
# schedule stress: real components, sanitizer on, zero violations
# ---------------------------------------------------------------------------


class _StubEngine:
    """Duck-typed engine for the batcher: instant numpy 'device' calls."""

    batch_sizes = (1, 4)
    max_width = 16

    def observe_width(self, width):
        pass

    def pad_requests(self, requests):
        batch = len(requests)
        width = max(len(r) for r in requests)
        starts = np.zeros((batch, width), np.int32)
        paths = np.zeros((batch, width), np.int32)
        ends = np.zeros((batch, width), np.int32)
        for i, contexts in enumerate(requests):
            n = len(contexts)
            starts[i, :n] = contexts[:, 0]
            paths[i, :n] = contexts[:, 1]
            ends[i, :n] = contexts[:, 2]
        return starts, paths, ends, batch, width

    def run(self, starts, paths, ends):
        batch, width = starts.shape
        logits = np.zeros((batch, 4), np.float32)
        vectors = np.ones((batch, 8), np.float32)
        attention = np.full((batch, width), 1.0 / max(width, 1), np.float32)
        return logits, vectors, attention


def _requests(rng, n):
    return [
        np.stack(
            [
                rng.integers(1, 50, w),
                rng.integers(1, 40, w),
                rng.integers(1, 50, w),
            ],
            axis=1,
        ).astype(np.int32)
        for w in rng.integers(1, 16, n)
    ]


class TestSanitizerStress:
    def test_batcher_under_concurrent_submitters(self, sync_debug):
        from code2vec_tpu.serve.batcher import MicroBatcher

        rng = np.random.default_rng(0)
        reqs = [_requests(rng, 40) for _ in range(4)]
        results: list[list] = [[] for _ in range(4)]
        with MicroBatcher(
            _StubEngine(), deadline_ms=1.0, health=RuntimeHealth()
        ) as batcher:

            def submitter(i):
                for contexts in reqs[i]:
                    results[i].append(
                        batcher.submit(contexts).result(timeout=30)
                    )

            threads = [
                threading.Thread(target=submitter, args=(i,))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        assert all(len(r) == 40 for r in results)
        assert violations() == []

    def test_result_cache_under_concurrent_leaders(self, sync_debug):
        from code2vec_tpu.serve.fleet.cache import ResultCache

        cache = ResultCache(1 << 16, health=RuntimeHealth())
        cache.set_version("v0")
        errors: list[BaseException] = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            try:
                for i in range(200):
                    key = ("k", int(rng.integers(0, 8)), "v0")
                    state, payload = cache.begin(key)
                    if state == "lead":
                        cache.fill(key, {"ok": True, "i": i})
                    elif state == "join":
                        payload.result(timeout=10)
                    else:
                        assert payload["ok"]
                    if rng.integers(0, 20) == 0:
                        cache.begin_swap()
                        cache.end_swap("v0")
            except BaseException as exc:  # noqa: BLE001 - surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(seed,)) for seed in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors[:2]
        assert violations() == []

    def test_swap_controller_reload_rollback_under_readers(self, sync_debug):
        from code2vec_tpu.serve.swap import Generation, SwapController

        class _StubBatcher:
            def __init__(self):
                self.closed = threading.Event()

            def close(self, timeout=None):
                self.closed.set()

        def gen(version):
            return Generation(
                version=version, engine=_StubEngine(),
                batcher=_StubBatcher(),
            )

        controller = SwapController(
            gen("v0"), build=lambda target: gen(str(target)),
            golden=None, health=RuntimeHealth(),
        )
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                controller.status()
                _ = controller.state

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for cycle in range(5):
                status = controller.reload(f"v{cycle + 1}", wait=True)
                assert status["last_swap"]["outcome"] == "committed"
                controller.rollback()
                controller.rollback()  # swap back and forth
        finally:
            stop.set()
            for t in threads:
                t.join(10)
            controller.close()
        assert violations() == []

    def test_router_fleet_under_concurrent_clients(self, sync_debug):
        from code2vec_tpu.obs.runtime import FlightRecorder
        from code2vec_tpu.serve.fleet.cache import ResultCache
        from code2vec_tpu.serve.fleet.router import FleetRouter

        class _Fake:
            def __init__(self, slot, incarnation=0):
                self.slot = slot
                self.incarnation = incarnation
                self._alive = True
                self._inflight = 0
                self._lock = threading.Lock()
                self.probe_failures = 0
                self.last_health = None
                self.last_health_unix = None
                self.death_reason = None
                self.pid = 41000 + slot

            @property
            def alive(self):
                return self._alive

            @property
            def in_flight(self):
                return self._inflight

            def send(self, request):
                future: Future = Future()
                with self._lock:
                    self._inflight += 1

                def run():
                    time.sleep(0.002)
                    with self._lock:
                        self._inflight -= 1
                    future.set_result(
                        {"ok": True, "op": request.get("op"),
                         "slot": self.slot}
                    )

                threading.Thread(target=run, daemon=True).start()
                return future

            def wait_ready(self, timeout):
                return {"ok": True}

            def stop(self, timeout=10.0):
                self._alive = False

            def kill(self, timeout=10.0):
                self._alive = False

        health = RuntimeHealth()
        cache = ResultCache(1 << 16, health=health)
        router = FleetRouter(
            lambda slot, incarnation: _Fake(slot, incarnation),
            2,
            health=health,
            probe_interval_s=0.05,  # prober thread in the mix
            flight=FlightRecorder(health=health),
            result_cache=cache,
        )
        failures: list = []

        def client(seed):
            rng = np.random.default_rng(seed)
            for i in range(60):
                op = ("embed", "neighbors", "health")[int(rng.integers(0, 3))]
                payload = router.handle(
                    {"op": op, "source": f"s{int(rng.integers(0, 6))}",
                     "language": "python", "method_name": "m"}
                )
                if payload.get("error"):
                    failures.append(payload)

        try:
            threads = [
                threading.Thread(target=client, args=(seed,))
                for seed in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        finally:
            router.close()
        assert not failures, failures[:3]
        assert violations() == []
