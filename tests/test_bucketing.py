"""Length-aware bucketed batching (data/pipeline.py bucketizer +
train/loop.py routing + train/device_epoch.py staged variant).

The load-bearing guarantee: PAD contexts carry zero attention weight, so an
example's forward pass is IDENTICAL at any bag width >= its real context
count — bucketing changes what gets padded, never what gets computed. The
parity tests here enforce that end to end (identical per-example loss
multiset, bitwise-equal eval metrics vs the fixed-L path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu import PAD_INDEX
from code2vec_tpu.data.pipeline import (
    assign_buckets,
    build_epoch,
    derive_bucket_ladder,
    epoch_context_counts,
    iter_batches,
    iter_bucketed_batches,
    pad_stats,
    parse_bucket_ladder,
    split_items,
)
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, SynthSpec, generate_corpus_data, generate_corpus_files
from code2vec_tpu.metrics import evaluate
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import train

BAG = 32


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_bucket")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return paths, data


TINY_CFG = dict(
    max_epoch=2,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=BAG,
    print_sample_cycle=0,
    bucketed=True,
)


class TestLadder:
    def test_geometric_capped_and_sorted(self):
        counts = np.random.default_rng(0).integers(1, 400, 5000)
        ladder = derive_bucket_ladder(counts, 200)
        assert ladder[-1] == 200
        assert list(ladder) == sorted(set(ladder))
        assert len(ladder) <= 4
        # geometric: each width ~half the next
        for a, b in zip(ladder, ladder[1:]):
            assert b == 2 * a or b == 2 * a - 1 or b == 2 * a + 1

    def test_sparse_buckets_merged_upward(self):
        # every count lands in (100, 200]: the narrow widths carry <5% of
        # the corpus each and must be pruned — they'd only add compiles
        counts = np.full(1000, 150)
        assert derive_bucket_ladder(counts, 200) == (200,)

    def test_single_bucket_floor(self):
        assert derive_bucket_ladder(np.asarray([5, 6]), 200, max_buckets=1) == (200,)

    def test_parse_explicit(self):
        assert parse_bucket_ladder("200,50,100,25", 200) == (25, 50, 100, 200)
        assert parse_bucket_ladder("", 200) is None
        assert parse_bucket_ladder("  ", 200) is None

    def test_parse_rejects_truncating_top(self):
        # a ladder topping below max_contexts would silently truncate long
        # bags relative to the fixed path
        with pytest.raises(ValueError, match="must end at max_contexts"):
            parse_bucket_ladder("25,50", 200)

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError, match="malformed"):
            parse_bucket_ladder("25,banana", 200)
        with pytest.raises(ValueError, match=">= 1"):
            parse_bucket_ladder("0,200", 200)

    def test_assignment_smallest_sufficient_width(self):
        ladder = (25, 50, 100, 200)
        counts = np.asarray([1, 25, 26, 50, 51, 100, 150, 200, 500])
        widths = np.asarray(ladder)[assign_buckets(counts, ladder)]
        assert widths.tolist() == [25, 25, 50, 50, 100, 100, 200, 200, 200]
        assert (widths >= np.minimum(counts, 200)).all()


class TestBucketedBatches:
    def _epoch(self, data, seed=0):
        rng = np.random.default_rng(seed)
        return build_epoch(data, np.arange(data.n_items), BAG, rng)

    def test_every_example_once_no_truncation(self, tiny):
        _, data = tiny
        epoch = self._epoch(data)
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        counts = epoch_context_counts(epoch)
        seen_ids = []
        for b in iter_bucketed_batches(epoch, ladder, 32, rng=np.random.default_rng(1)):
            width = b["starts"].shape[1]
            assert width in ladder
            valid = b["example_mask"].astype(bool)
            seen_ids.extend(b["ids"][valid].tolist())
            # no example lost contexts to its bucket: each valid row's real
            # count fits its width
            row_counts = (b["paths"][valid] != PAD_INDEX).sum(axis=1)
            assert (row_counts <= width).all()
        assert sorted(seen_ids) == sorted(epoch.ids.tolist())
        # and the real-count bound is tight: every count is represented
        assert counts.max() <= BAG

    def test_last_partial_batch_masked_per_bucket(self, tiny):
        _, data = tiny
        epoch = self._epoch(data)
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        total_valid = 0
        for b in iter_bucketed_batches(epoch, ladder, 32, rng=np.random.default_rng(1)):
            assert b["example_mask"].shape == (32,)
            assert len(b["labels"]) == 32  # padded rows repeat a real row
            total_valid += int(b["example_mask"].sum())
        assert total_valid == len(epoch)

    def test_seeded_interleave_deterministic(self, tiny):
        _, data = tiny
        epoch = self._epoch(data)
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)

        def run(seed):
            out = []
            for b in iter_bucketed_batches(
                epoch, ladder, 32, rng=np.random.default_rng(seed)
            ):
                out.append((b["starts"].shape, b["ids"].tolist()))
            return out

        a, b = run(7), run(7)
        assert a == b  # same seed -> identical schedule and rows
        c = run(8)
        assert a != c  # the interleave is actually seed-driven

    def test_eval_order_sequential_without_rng(self, tiny):
        _, data = tiny
        epoch = self._epoch(data)
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        widths = [
            b["starts"].shape[1]
            for b in iter_bucketed_batches(epoch, ladder, 32, rng=None)
        ]
        assert widths == sorted(widths)  # ladder order, bucket by bucket

    def test_drop_remainder(self, tiny):
        _, data = tiny
        epoch = self._epoch(data)
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        n_full = sum(
            1
            for b in iter_bucketed_batches(
                epoch, ladder, 32, rng=np.random.default_rng(1), pad_final=False
            )
        )
        bucket_of = assign_buckets(epoch_context_counts(epoch), ladder)
        expected = sum(
            int((bucket_of == i).sum()) // 32 for i in range(len(ladder))
        )
        assert n_full == expected

    def test_pad_stats_accounting(self):
        counts = np.asarray([10, 10, 10, 10, 190, 190])
        real, fixed_slots = pad_stats(counts, (200,), 2)
        assert real == 420 and fixed_slots == 3 * 2 * 200
        real_b, bucket_slots = pad_stats(counts, (25, 200), 2)
        assert real_b == real
        # two batches of 25-wide + one of 200-wide
        assert bucket_slots == 2 * 2 * 25 + 1 * 2 * 200
        assert bucket_slots < fixed_slots


class TestParity:
    """The acceptance bar: bucketing must not change any example's math."""

    def _per_example_losses(self, batches, state):
        @jax.jit
        def nll_of(state, batch):
            logits, _, _ = state.apply_fn(
                {"params": state.params},
                batch["starts"], batch["paths"], batch["ends"],
                deterministic=True,
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(
                logp, batch["labels"][:, None], axis=-1
            )[:, 0], jnp.argmax(logits, axis=-1)

        losses, expected, preds = {}, [], []
        for b in batches:
            nll, pred = nll_of(state, jax.device_put(b))
            valid = b["example_mask"].astype(bool)
            nll = np.asarray(nll)
            for i in np.flatnonzero(valid):
                losses[int(b["ids"][i])] = float(nll[i])
            expected.append(b["labels"][valid])
            preds.append(np.asarray(pred)[valid])
        return losses, np.concatenate(expected), np.concatenate(preds)

    def test_loss_multiset_and_eval_metrics_identical(self, tiny):
        from code2vec_tpu.train.loop import model_config_from
        from code2vec_tpu.train.step import create_train_state

        _, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        model_config = model_config_from(cfg, data)
        rng = np.random.default_rng(0)
        epoch = build_epoch(data, np.arange(data.n_items), BAG, rng)
        batch0 = next(iter_batches(epoch, 32, rng=None, pad_final=False))
        state = create_train_state(
            cfg, model_config, jax.random.PRNGKey(0), batch0
        )
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        assert len(ladder) > 1  # the test must actually exercise >1 width

        fixed = self._per_example_losses(
            iter_batches(epoch, 32, rng=None, pad_final=True), state
        )
        bucketed = self._per_example_losses(
            iter_bucketed_batches(
                epoch, ladder, 32, rng=np.random.default_rng(3), pad_final=True
            ),
            state,
        )
        # identical per-example loss MULTISET (keyed by example id, exact:
        # extra PAD slots contribute exact-zero attention terms)
        assert fixed[0].keys() == bucketed[0].keys()
        for k in fixed[0]:
            assert fixed[0][k] == bucketed[0][k], k

        # eval metrics bitwise-equal (order-invariant over (label, pred))
        m_fixed = evaluate("subtoken", fixed[1], fixed[2], data.label_vocab)
        m_bucketed = evaluate(
            "subtoken", bucketed[1], bucketed[2], data.label_vocab
        )
        assert m_fixed == m_bucketed


class TestTrainBucketed:
    def test_end_to_end_with_zero_recompiles(self, tiny):
        """Acceptance: a bucketed run with expected_compiles = n_buckets
        reports 0 post-warmup recompiles, learns, and records the
        pad_efficiency gauge per epoch."""
        from code2vec_tpu.obs.events import EventLog

        _, data = tiny
        seen = []
        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        res = train(TrainConfig(**TINY_CFG), data, events=events)
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert res.best_f1 > 0.0
        assert all(0.0 < h["pad_efficiency"] <= 1.0 for h in res.history)
        assert not [e for e in seen if e["event"] == "recompile"]
        epochs = [e for e in seen if e["event"] == "epoch"]
        assert epochs and all(
            e["health"]["gauges"]["pad_efficiency"] > 0 for e in epochs
        )
        assert all(
            e["health"]["counters"].get("recompiles", 0) == 0 for e in epochs
        )

    def test_prefetch_compatible_with_mixed_shapes(self, tiny):
        """Satellite: the host prefetcher must carry a mixed-shape batch
        stream unchanged — bitwise-identical loss trajectory to the
        synchronous bucketed run."""
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        sync = train(cfg, data)
        pref = train(cfg.with_updates(prefetch_batches=2), data)
        assert [h["train_loss"] for h in sync.history] == [
            h["train_loss"] for h in pref.history
        ]
        assert sync.final_f1 == pref.final_f1

    def test_explicit_ladder_respected(self, tiny):
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=1, bucket_ladder=f"16,{BAG}"
        )
        res = train(cfg, data)
        assert res.epochs_run == 1

    def test_streaming_combo_composes(self, tiny):
        """PR 10: the bucketed-vs-streaming mutual exclusion is gone — a
        streaming epoch emits ladder-width batches (per-bucket carry across
        chunks) and still reports the pad_efficiency honesty metric."""
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            max_epoch=1, stream_chunk_items=64
        )
        res = train(cfg, data)
        assert res.epochs_run == 1
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert all(0.0 < h["pad_efficiency"] <= 1.0 for h in res.history)

    def test_bad_ladder_rejected(self, tiny):
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(bucket_ladder="8,16")
        with pytest.raises(ValueError, match="must end at max_contexts"):
            train(cfg, data)

    def test_restored_step_is_strong_int32(self, tiny, tmp_path):
        """Resume must not undo create_train_state's int32 step
        normalization: a weak Python-int step traces one extra jit-cache
        entry on the first post-resume step, overflowing the bucketed
        expected_compiles budget and firing a spurious recompile event."""
        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )
        from code2vec_tpu.train.loop import model_config_from
        from code2vec_tpu.train.step import create_train_state, make_train_step

        _, data = tiny
        cfg = TrainConfig(**TINY_CFG)
        model_config = model_config_from(cfg, data)
        epoch = build_epoch(
            data, np.arange(data.n_items), BAG, np.random.default_rng(0)
        )
        batch = next(iter_batches(epoch, 32, rng=None, pad_final=False))
        state = create_train_state(
            cfg, model_config, jax.random.PRNGKey(0), batch
        )
        step_fn = make_train_step(
            model_config, jnp.ones(model_config.label_count, jnp.float32)
        )
        state, _ = step_fn(state, batch)
        out = str(tmp_path / "ckpt")
        save_checkpoint(out, state, TrainMeta())

        template = create_train_state(
            cfg, model_config, jax.random.PRNGKey(9), batch
        )
        restored, _ = restore_checkpoint(out, template)
        # a Python-int step has neither attribute, so either assert fails
        # closed without the normalization (compile COUNTS are not asserted:
        # orbax shifts jax's trace-context tuple in-process, which adds its
        # own cache entries independent of the step dtype)
        assert restored.step.dtype == jnp.int32
        assert not restored.step.weak_type
        state2, _ = step_fn(restored, batch)  # and the step fn accepts it
        assert state2.step.dtype == jnp.int32

    def test_ladder_without_bucketed_rejected(self, tiny):
        # a pinned ladder with bucketing off would be silently ignored
        # (full-padding fixed-L run) — fail loud instead
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            bucketed=False, bucket_ladder=f"8,{BAG}"
        )
        with pytest.raises(ValueError, match="--bucketed is off"):
            train(cfg, data)


class TestDeviceBucketed:
    def test_device_epoch_bucketed_trains(self, tiny):
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(device_epoch=True)
        res = train(cfg, data)
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert res.best_f1 > 0.0
        assert all(0.0 < h["pad_efficiency"] <= 1.0 for h in res.history)

    def test_bucket_staged_partition(self, tiny):
        from code2vec_tpu.train.device_epoch import (
            bucket_staged,
            stage_method_corpus,
        )

        _, data = tiny
        rng = np.random.default_rng(0)
        item_idx = np.arange(data.n_items)
        staged = stage_method_corpus(data, item_idx, rng, device="host")
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        bucketed = bucket_staged(staged, ladder)
        # every row lands in exactly one bucket; context totals conserved
        assert bucketed.n_items == staged.n_items
        assert bucketed.n_contexts == staged.n_contexts
        assert sorted(bucketed.host_labels().tolist()) == sorted(
            np.asarray(staged.labels).tolist()
        )
        for width, sub in bucketed.buckets:
            counts = np.diff(np.asarray(jax.device_get(sub.row_splits)))
            capped = np.minimum(counts, ladder[-1])
            assert (capped <= width).all()
            if width != ladder[0]:
                narrower = max(w for w in ladder if w < width)
                assert (capped > narrower).all()

    def test_shard_staged_combo_rejected(self, tiny):
        _, data = tiny
        cfg = TrainConfig(**TINY_CFG).with_updates(
            device_epoch=True, shard_staged_corpus=True, data_axis=1
        )
        with pytest.raises(ValueError, match="shard_staged"):
            train(cfg, data)


class TestSynthLengthSigma:
    def test_sigma_zero_is_constant_length(self):
        spec = SynthSpec(n_methods=200, length_sigma=0.0, mean_contexts=40.0)
        raw = generate_corpus_data(spec)
        counts = np.diff(raw.row_splits)
        assert len(np.unique(counts)) == 1

    def test_default_matches_previous_hardcoded(self):
        # the knob's default must reproduce the pre-knob corpus exactly
        a = generate_corpus_data(SynthSpec(n_methods=100))
        b = generate_corpus_data(SynthSpec(n_methods=100, length_sigma=0.6))
        np.testing.assert_array_equal(a.row_splits, b.row_splits)
        np.testing.assert_array_equal(a.paths, b.paths)

    def test_larger_sigma_is_more_skewed(self):
        lo = generate_corpus_data(SynthSpec(n_methods=2000, length_sigma=0.2))
        hi = generate_corpus_data(SynthSpec(n_methods=2000, length_sigma=1.2))
        assert np.diff(hi.row_splits).std() > np.diff(lo.row_splits).std()
