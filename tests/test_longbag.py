"""Long-bag encoding (PR 13): flash-style chunked softmax in the fused
kernel, longbag ladder rungs, truncation accounting, the hierarchical
file/class head, and serve-side longbag routing.

Everything runs in Pallas interpreter mode on CPU (the same code path the
TPU compiles); kernel parity is always against the unfused XLA reference.
"""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from code2vec_tpu.data.pipeline import (
    derive_bucket_ladder,
    derive_longbag_ladder,
    truncated_fraction,
    truncated_fraction_of_counts,
)
from code2vec_tpu.ops.fused_encode_pool import SOFTMAX_MODES
from tests.test_fused import call, op_inputs, reference

pytestmark = pytest.mark.longbag


# ---------------------------------------------------------------------------
# chunked softmax: kernel parity
# ---------------------------------------------------------------------------


class TestChunkedSoftmaxParity:
    """The acceptance matrix: both chunked modes match the unfused XLA
    reference across the chunk_l x dma_depth grid, including multi-chunk
    bags (L spans several chunk tiles), the single-chunk degenerate case
    (L below one chunk), partial batch tiles, and all-masked rows."""

    @pytest.mark.parametrize("mode", ["online", "two_pass"])
    @pytest.mark.parametrize("chunk_l,dma_depth", [
        (128, 1), (128, 2), (64, 2), (64, 3), (256, 2),
    ])
    def test_multi_chunk_matches_xla(self, mode, chunk_l, dma_depth):
        # L=300 pads to 384 lanes: 3-6 chunks depending on chunk_l (256
        # does not divide 384 and falls back to 128 — still chunked)
        inp = op_inputs(5, 300, seed=chunk_l + dma_depth)
        cv_ref, w_ref = reference(inp)
        cv, w = call(
            inp, impl="fused", block_b=4, dma_depth=dma_depth,
            chunk_l=chunk_l, softmax_mode=mode,
        )
        np.testing.assert_allclose(
            np.asarray(cv), np.asarray(cv_ref), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(w), np.asarray(w_ref), rtol=2e-5, atol=1e-6
        )

    @pytest.mark.parametrize("mode", ["online", "two_pass"])
    def test_single_chunk_degenerate(self, mode):
        # L=21 pads to one 128-lane chunk: the streamed recurrence must
        # collapse to the one-shot softmax exactly
        inp = op_inputs(3, 21, seed=9)
        cv_ref, w_ref = reference(inp)
        cv, w = call(inp, impl="fused", block_b=4, softmax_mode=mode)
        np.testing.assert_allclose(
            np.asarray(cv), np.asarray(cv_ref), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(w), np.asarray(w_ref), rtol=1e-5, atol=1e-6
        )

    @pytest.mark.parametrize("mode", ["online", "two_pass"])
    def test_all_masked_row_uniform_over_real_length(self, mode):
        inp = op_inputs(5, 150, seed=7, all_masked_row=2)
        cv_ref, w_ref = reference(inp)
        cv, w = call(inp, impl="fused", block_b=4, softmax_mode=mode)
        np.testing.assert_allclose(
            np.asarray(w[2]), np.asarray(w_ref[2]), rtol=1e-5
        )
        np.testing.assert_allclose(float(np.asarray(w)[2].sum()), 1.0,
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(cv[2]), np.asarray(cv_ref[2]), rtol=1e-4, atol=1e-5
        )

    @pytest.mark.parametrize("mode", ["online", "two_pass"])
    def test_grads_exact_to_unfused(self, mode):
        # the custom_vjp backward (XLA recompute over saved primals) is
        # softmax-mode-independent by construction; pin it anyway — a
        # forward/backward split bug would show here first
        inp = op_inputs(4, 140, seed=11)
        names = ("t_table", "p_table", "dense_kernel", "ln_scale",
                 "ln_bias", "attn_param")

        def loss(fn):
            def inner(*diff):
                d = dict(inp, **dict(zip(names, diff)))
                cv, w = fn(d)
                return jnp.sum(cv**2) + jnp.sum(w * jnp.cos(w))

            return inner

        args = tuple(inp[n] for n in names)
        g_ref = jax.grad(loss(reference), argnums=tuple(range(6)))(*args)
        g_chunked = jax.grad(
            loss(lambda d: call(
                d, impl="fused", block_b=4, chunk_l=64, softmax_mode=mode
            )),
            argnums=tuple(range(6)),
        )(*args)
        for a, b in zip(g_chunked, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            )

    def test_int8_tables_through_chunked_modes(self):
        from code2vec_tpu.ops.quant import quantize_table

        inp = op_inputs(4, 150, seed=5)
        qinp = dict(
            inp,
            t_table=quantize_table(inp["t_table"], "int8"),
            p_table=quantize_table(inp["p_table"], "int8"),
        )
        cv_ref, _ = reference(qinp)
        for mode in ("online", "two_pass"):
            cv, _ = call(qinp, impl="fused", block_b=4, softmax_mode=mode)
            np.testing.assert_allclose(
                np.asarray(cv), np.asarray(cv_ref), rtol=1e-4, atol=1e-4
            )

    def test_chunked_requires_fused_impl(self):
        inp = op_inputs(3, 16, seed=1)
        with pytest.raises(ValueError, match="impl='fused'"):
            call(inp, impl="gather_split", softmax_mode="online")

    def test_unknown_mode_fails_loudly(self):
        inp = op_inputs(3, 16, seed=1)
        with pytest.raises(ValueError, match="softmax_mode"):
            call(inp, impl="fused", softmax_mode="typo")
        assert "materialize" in SOFTMAX_MODES


class TestChunkedOnMesh:
    """The chunked kernel composed with mesh axes: the op's
    custom_partitioning rule shards the batch dim (same contract as
    TestFusedOnMesh for the materialized kernel), on the 8-device CPU
    harness."""

    @pytest.mark.parametrize("mode", ["online", "two_pass"])
    def test_matches_xla_path_on_mesh(self, mode):
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_batch, shard_state
        from code2vec_tpu.parallel.step import make_parallel_train_step
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state

        mesh = make_mesh(data=4, model=2, ctx=1)
        rng = np.random.default_rng(0)
        B, L = 16, 150  # two 128-lane chunks
        base = dict(
            terminal_count=60, path_count=50, label_count=9,
            terminal_embed_size=8, path_embed_size=8, encode_size=16,
            dropout_prob=0.0,
        )
        batch = {
            "ids": np.arange(B, dtype=np.int64),
            "starts": rng.integers(1, 60, (B, L)).astype(np.int32),
            "paths": rng.integers(1, 50, (B, L)).astype(np.int32),
            "ends": rng.integers(1, 60, (B, L)).astype(np.int32),
            "labels": rng.integers(0, 9, B).astype(np.int32),
            "example_mask": np.ones(B, np.float32),
        }
        batch["starts"][:, L // 2 :] = 0

        losses = {}
        for use_chunked in (False, True):
            mc = Code2VecConfig(
                **base,
                use_pallas=use_chunked,
                pallas_impl="fused",
                pallas_block_b=4,
                pallas_softmax=mode,
            )
            tc = TrainConfig(batch_size=B, max_path_length=L)
            state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
            state = shard_state(mesh, state)
            cw = jnp.ones(mc.label_count, jnp.float32)
            step = make_parallel_train_step(mc, cw, mesh, state)
            device_batch = shard_batch(mesh, batch)
            state, loss = step(state, device_batch)
            state, loss2 = step(state, device_batch)
            losses[use_chunked] = (float(loss), float(loss2))
        np.testing.assert_allclose(losses[False], losses[True], rtol=2e-5)


# ---------------------------------------------------------------------------
# longbag ladder derivation + truncation accounting
# ---------------------------------------------------------------------------


class TestLongbagLadder:
    def test_empty_when_nothing_exceeds_base(self):
        lengths = np.array([5, 20, 64])
        weights = np.array([10, 10, 10])
        assert derive_longbag_ladder(lengths, weights, 64) == ()

    def test_rungs_are_chunk_multiples_and_cover_max(self):
        lengths = np.array([10, 100, 900])
        weights = np.array([50, 20, 3])
        rungs = derive_longbag_ladder(lengths, weights, 64, chunk_l=128)
        assert rungs
        assert all(w % 128 == 0 for w in rungs)
        assert rungs[-1] >= 900
        assert all(w > 64 for w in rungs)
        assert list(rungs) == sorted(rungs)

    def test_empty_rungs_pruned_but_top_kept(self):
        # tail jumps straight from 70 to 4000: intermediate doublings hold
        # nothing and are pruned; the top rung must still cover 4000
        lengths = np.array([10, 70, 4000])
        weights = np.array([100, 5, 1])
        rungs = derive_longbag_ladder(lengths, weights, 64, chunk_l=128)
        assert rungs[-1] >= 4000
        prev = 64
        for w in rungs[:-1]:
            held = ((lengths > prev) & (lengths <= w) & (weights > 0)).any()
            assert held, f"rung {w} holds nothing"
            prev = w

    def test_max_rungs_respected(self):
        lengths = np.arange(65, 100_000, 997)
        weights = np.ones_like(lengths)
        rungs = derive_longbag_ladder(
            lengths, weights, 64, chunk_l=128, max_rungs=3
        )
        assert len(rungs) <= 3
        assert rungs[-1] >= lengths.max()

    def test_truncated_fraction(self):
        lengths = np.array([10, 100])
        weights = np.array([1, 1])
        # cap 50: drops 50 of 110 contexts
        assert truncated_fraction(lengths, weights, 50) == pytest.approx(
            50 / 110
        )
        assert truncated_fraction(lengths, weights, 100) == 0.0
        assert truncated_fraction_of_counts(
            np.array([10, 100, 100]), 50
        ) == pytest.approx(100 / 210)
        assert truncated_fraction(np.zeros(0), np.zeros(0), 10) == 0.0


# ---------------------------------------------------------------------------
# --max_contexts 0 end to end
# ---------------------------------------------------------------------------


def heavy_tailed_corpus(seed=0, n_methods=48):
    from code2vec_tpu.data.synth import (
        SynthSpec,
        corpus_data_from_raw,
        generate_corpus_data,
    )

    spec = SynthSpec(
        n_methods=n_methods, n_terminals=60, n_paths=50, n_labels=8,
        mean_contexts=10.0, length_sigma=1.2, max_contexts=200, seed=seed,
    )
    return corpus_data_from_raw(generate_corpus_data(spec))


class TestLongbagTrain:
    def test_unbounded_trains_with_zero_truncation(self, tmp_path):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        counts = np.diff(data.row_splits)
        assert (counts > 16).any(), "synth corpus lost its tail"
        cfg = TrainConfig(
            max_epoch=1, batch_size=8, encode_size=8,
            terminal_embed_size=4, path_embed_size=4, max_path_length=16,
            print_sample_cycle=0, bucketed=True, max_contexts=0,
            use_pallas=True, pallas_impl="pool_only", pallas_block_b=4,
        )
        res = train(cfg, data)
        h = res.history[-1]
        assert np.isfinite(h["train_loss"])
        # the acceptance bar: NOTHING was truncated
        assert h["truncated_context_fraction"] == 0.0

    def test_bounded_control_reports_the_loss(self):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        cfg = TrainConfig(
            max_epoch=1, batch_size=8, encode_size=8,
            terminal_embed_size=4, path_embed_size=4, max_path_length=16,
            print_sample_cycle=0, bucketed=True,
        )
        h = train(cfg, data).history[-1]
        expected = truncated_fraction_of_counts(
            np.diff(data.row_splits)[
                # the loop computes the fraction over the TRAIN split
                # (seeded split, first 20% test) — recompute it here
                np.random.default_rng(cfg.random_seed).permutation(
                    data.n_items
                )[int(data.n_items * 0.2):]
            ],
            16,
        )
        assert h["truncated_context_fraction"] == pytest.approx(expected)
        assert h["truncated_context_fraction"] > 0

    def test_unbounded_requires_bucketed(self):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        with pytest.raises(ValueError, match="--bucketed"):
            train(TrainConfig(max_contexts=0, max_epoch=1), data)

    def test_positive_max_contexts_rejected(self):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        with pytest.raises(ValueError, match="max_path_length"):
            train(
                TrainConfig(max_contexts=99, bucketed=True, max_epoch=1),
                data,
            )

    def test_unbounded_rejects_device_epoch(self):
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        with pytest.raises(ValueError, match="device_epoch"):
            train(
                TrainConfig(
                    max_contexts=0, bucketed=True, device_epoch=True,
                    max_epoch=1,
                ),
                data,
            )

    def test_meta_records_longbag_ladder(self, tmp_path):
        from code2vec_tpu.predict import MODEL_META
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        data = heavy_tailed_corpus()
        out_dir = str(tmp_path / "model")
        cfg = TrainConfig(
            max_epoch=1, batch_size=8, encode_size=8,
            terminal_embed_size=4, path_embed_size=4, max_path_length=16,
            print_sample_cycle=0, bucketed=True, max_contexts=0,
        )
        train(cfg, data, out_dir=out_dir)
        meta = json.load(open(f"{out_dir}/{MODEL_META}"))
        ladder = meta["bucket_ladder"]
        # the recorded ladder carries rungs ABOVE the base bag width, so
        # the serving engine inherits longbag routing with no corpus
        assert ladder[-1] > meta["max_path_length"]
        assert meta["max_path_length"] == 16


# ---------------------------------------------------------------------------
# serve: longbag routing vs the loud reject
# ---------------------------------------------------------------------------


class TestServeLongbag:
    BAG = 16
    LONGBAG_LADDER = (8, 16, 128)  # one longbag rung above the bag

    @pytest.fixture(scope="class")
    def tiny_state(self):
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state

        cfg = TrainConfig(batch_size=4, max_path_length=self.BAG)
        mc = Code2VecConfig(
            terminal_count=50, path_count=40, label_count=6,
            terminal_embed_size=8, path_embed_size=8, encode_size=12,
            dropout_prob=0.0,
        )
        example = {
            "starts": np.zeros((1, self.BAG), np.int32),
            "paths": np.zeros((1, self.BAG), np.int32),
            "ends": np.zeros((1, self.BAG), np.int32),
            "labels": np.zeros(1, np.int32),
            "example_mask": np.ones(1, np.float32),
        }
        return create_train_state(cfg, mc, jax.random.PRNGKey(0), example)

    def request_of(self, n, seed=0):
        rng = np.random.default_rng(seed)
        return np.stack(
            [
                rng.integers(1, 50, n),
                rng.integers(1, 40, n),
                rng.integers(1, 50, n),
            ],
            axis=1,
        ).astype(np.int32)

    def test_longbag_rungs_serve_oversized_requests(self, tiny_state):
        from code2vec_tpu.obs.runtime import RuntimeHealth
        from code2vec_tpu.serve.batcher import MicroBatcher
        from code2vec_tpu.serve.engine import ServingEngine

        engine = ServingEngine(
            tiny_state, max_width=self.BAG, ladder=self.LONGBAG_LADDER,
            batch_sizes=(1, 4), health=RuntimeHealth(),
        )
        engine.prepare()
        # the rungs raised the serveable width to the ladder top
        assert engine.max_width == self.LONGBAG_LADDER[-1]
        assert engine.base_width == self.BAG
        with MicroBatcher(engine, deadline_ms=0.0,
                          health=RuntimeHealth()) as batcher:
            # a bag far beyond the training width serves — no reject, no
            # truncation — through a pre-compiled longbag executable
            result = batcher.submit(self.request_of(100)).result(timeout=60)
            assert result.width == 128
            assert result.n_contexts == 100
            assert len(result.attention) == 100
            assert np.isfinite(result.code_vector).all()
        # ...and it hit a warm executable: zero post-warmup compiles
        assert engine.post_warmup_compiles == 0

    def test_beyond_top_rung_still_rejects_loudly(self, tiny_state):
        from code2vec_tpu.obs.runtime import RuntimeHealth
        from code2vec_tpu.serve.batcher import MicroBatcher
        from code2vec_tpu.serve.engine import ServingEngine

        engine = ServingEngine(
            tiny_state, max_width=self.BAG, ladder=self.LONGBAG_LADDER,
            batch_sizes=(1,), health=RuntimeHealth(),
        )
        engine.prepare()
        with MicroBatcher(engine, deadline_ms=0.0,
                          health=RuntimeHealth()) as batcher:
            with pytest.raises(ValueError, match="subsample"):
                batcher.submit(self.request_of(129))

    def test_no_rungs_keeps_the_original_reject(self, tiny_state):
        # regression: a ladder WITHOUT longbag rungs must reject oversized
        # bags at submit exactly as before PR 13
        from code2vec_tpu.obs.runtime import RuntimeHealth
        from code2vec_tpu.serve.batcher import MicroBatcher
        from code2vec_tpu.serve.engine import ServingEngine

        engine = ServingEngine(
            tiny_state, max_width=self.BAG, ladder=(8, 16),
            batch_sizes=(1,), health=RuntimeHealth(),
        )
        engine.prepare()
        assert engine.max_width == self.BAG
        with MicroBatcher(engine, deadline_ms=0.0,
                          health=RuntimeHealth()) as batcher:
            with pytest.raises(ValueError, match="subsample"):
                batcher.submit(self.request_of(self.BAG + 1))

    def test_ladder_below_max_width_still_rejected(self, tiny_state):
        from code2vec_tpu.serve.engine import ServingEngine

        with pytest.raises(ValueError, match="reach max_width"):
            ServingEngine(
                tiny_state, max_width=self.BAG, ladder=(4, 8),
                batch_sizes=(1,),
            )


# ---------------------------------------------------------------------------
# hierarchical file/class pooling
# ---------------------------------------------------------------------------


class TestHierarchicalPool:
    def test_group_pooling_matches_manual_softmax(self):
        from code2vec_tpu.models.hierarchical import (
            pool_vectors,
            pool_vectors_by_group,
        )

        rng = np.random.default_rng(0)
        vectors = rng.normal(size=(6, 4)).astype(np.float32)
        attn = rng.normal(size=4).astype(np.float32)
        groups = ["a.java", "b.java", "a.java", "c.java", "b.java", "a.java"]
        keys, pooled = pool_vectors_by_group(vectors, groups, attn)
        assert keys == ["a.java", "b.java", "c.java"]  # first appearance
        rows_a = vectors[[0, 2, 5]]
        s = rows_a @ attn
        w = np.exp(s - s.max())
        w /= w.sum()
        np.testing.assert_allclose(
            pooled[0], (w @ rows_a).astype(np.float32), rtol=1e-6
        )
        # mean fallback
        _, pooled_mean = pool_vectors_by_group(vectors, groups, None)
        np.testing.assert_allclose(
            pooled_mean[2], vectors[[3]].mean(axis=0), rtol=1e-6
        )
        with pytest.raises(ValueError, match="non-empty"):
            pool_vectors(np.zeros((0, 4), np.float32), attn)

    def test_flax_module_matches_numpy_pooling(self):
        from code2vec_tpu.models.hierarchical import (
            HierarchicalAttentionPool,
            pool_vectors,
        )

        rng = np.random.default_rng(1)
        G, M, H = 3, 5, 8
        vectors = rng.normal(size=(G, M, H)).astype(np.float32)
        mask = np.ones((G, M), np.float32)
        mask[1, 3:] = 0.0  # padded group
        module = HierarchicalAttentionPool(encode_size=H)
        params = module.init(jax.random.PRNGKey(0), vectors, mask)
        (fv, attn_w), p = (
            module.apply(params, vectors, mask),
            params["params"]["file_attention"],
        )
        fv = np.asarray(fv)
        for g in range(G):
            real = vectors[g][mask[g].astype(bool)]
            np.testing.assert_allclose(
                fv[g], pool_vectors(real, np.asarray(p)), rtol=1e-5,
                atol=1e-6,
            )
        # masked slots carry ~zero attention weight
        assert np.asarray(attn_w)[1, 3:].max() < 1e-30

    def test_file_vectors_round_trip_through_retrieval(self, tmp_path):
        """The acceptance criterion: file-level vectors from the
        hierarchical head round-trip export -> retrieval — `neighbors`
        returns them through the EXISTING serving stack."""
        from code2vec_tpu.export import export_file_vectors
        from code2vec_tpu.formats.vectors_io import read_code_vectors
        from code2vec_tpu.serve.retrieval import RetrievalIndex

        rng = np.random.default_rng(2)
        method_vectors = rng.normal(size=(12, 8)).astype(np.float32)
        groups = [f"file_{i % 4}.java" for i in range(12)]
        attn = rng.normal(size=8).astype(np.float32)
        path = str(tmp_path / "file.vec")
        keys, pooled = export_file_vectors(
            method_vectors, groups, path, attn_param=attn
        )
        assert len(keys) == 4 and pooled.shape == (4, 8)

        labels, rows = read_code_vectors(path)
        assert labels == [str(k) for k in keys]
        np.testing.assert_allclose(rows, pooled, rtol=1e-5)

        index = RetrievalIndex.from_code_vec(path)
        # querying a file's own vector returns that file first, sim ~1
        for g, key in enumerate(keys):
            neighbors = index.top_k(pooled[g], 2)
            assert neighbors[0][0] == str(key)
            assert neighbors[0][1] == pytest.approx(1.0, abs=1e-4)

    PY = (
        "def add(a, b):\n    total = a + b\n    return total\n\n\n"
        "def mul(a, b):\n    product = a * b\n    return product\n"
    )

    def test_predictor_embed_file(self, tmp_path):
        """Online path: pyextract-train tiny -> Predictor.embed_file pools
        the source's per-method vectors with the checkpoint's attention."""
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.models.hierarchical import pool_vectors
        from code2vec_tpu.predict import Predictor
        from code2vec_tpu.pyextract import extract_python_dataset
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.loop import train

        src, ds, out = tmp_path / "src", tmp_path / "ds", tmp_path / "out"
        for d in (src, ds, out):
            d.mkdir()
        (src / "util.py").write_text(self.PY)
        extract_python_dataset(str(ds), str(src), [("util.py", "*")])
        data = load_corpus(
            ds / "corpus.txt", ds / "path_idxs.txt", ds / "terminal_idxs.txt"
        )
        cfg = TrainConfig(
            max_epoch=2, batch_size=2, encode_size=16,
            terminal_embed_size=8, path_embed_size=8, max_path_length=32,
            print_sample_cycle=0,
        )
        train(cfg, data, out_dir=str(out))
        predictor = Predictor(
            str(out), str(ds / "terminal_idxs.txt"), str(ds / "path_idxs.txt")
        )
        file_vector, names = predictor.embed_file(self.PY, language="python")
        assert file_vector.shape == (16,)
        assert np.isfinite(file_vector).all()
        assert len(names) == 2
        # cross-check against manual per-method embed + pool
        vectors = [
            m.code_vector
            for m in predictor.predict_source(self.PY, language="python")
        ]
        attn = np.asarray(predictor.state.params["attention"], np.float32)
        np.testing.assert_allclose(
            file_vector, pool_vectors(np.stack(vectors), attn),
            rtol=1e-5, atol=1e-6,
        )

        # the serving surface on the same checkpoint: embed_file op + the
        # file-granularity neighbors path against an exported file.vec
        from code2vec_tpu.export import export_file_vectors
        from code2vec_tpu.obs.runtime import RuntimeHealth
        from code2vec_tpu.serve.batcher import MicroBatcher
        from code2vec_tpu.serve.engine import ServingEngine
        from code2vec_tpu.serve.protocol import CodeServer
        from code2vec_tpu.serve.retrieval import RetrievalIndex

        file_vec_path = str(tmp_path / "file.vec")
        export_file_vectors(
            np.stack(vectors), ["util.py", "util.py"], file_vec_path,
            attn_param=attn,
        )
        engine = ServingEngine.from_predictor(
            predictor, health=RuntimeHealth()
        )
        engine.prepare()
        batcher = MicroBatcher(engine, deadline_ms=0.0, health=RuntimeHealth())
        server = CodeServer(
            predictor, engine, batcher,
            retrieval=RetrievalIndex.from_code_vec(file_vec_path),
        )
        try:
            resp = server.handle(
                {"op": "embed_file", "source": self.PY, "language": "python"}
            )
            assert resp["ok"] and resp["n_methods"] == 2
            np.testing.assert_allclose(
                np.asarray(resp["file_vector"], np.float32), file_vector,
                rtol=1e-4, atol=1e-5,
            )
            nn = server.handle({
                "op": "neighbors", "source": self.PY, "language": "python",
                "granularity": "file", "top_k": 1,
            })
            assert nn["ok"]
            # the whole-file query comes back as its own exported file row
            assert nn["neighbors"][0]["name"] == "util.py"
            assert nn["neighbors"][0]["similarity"] == pytest.approx(
                1.0, abs=1e-3
            )
            bad = server.handle({
                "op": "neighbors", "source": self.PY, "language": "python",
                "granularity": "typo",
            })
            assert bad["error_kind"] == "bad_request"
        finally:
            server.close()


# ---------------------------------------------------------------------------
# tools + autotune surface
# ---------------------------------------------------------------------------


class TestTruncationTooling:
    def test_corpus_stats_reports_truncation_and_longbag(self, tmp_path):
        import os

        corpus = tmp_path / "corpus.txt"
        records = []
        for n in (3, 5, 40):
            rows = "\n".join("1\t2\t3" for _ in range(n))
            records.append(f"id:0\nlabel:m\npaths:\n{rows}\n")
        corpus.write_text("\n".join(records) + "\n")
        tool = os.path.join(
            os.path.dirname(__file__), "..", "tools", "corpus_stats.py"
        )
        proc = subprocess.run(
            [sys.executable, tool, str(corpus), "--max_contexts", "8"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = proc.stdout
        payload = json.loads(out.strip().splitlines()[-1])
        # 48 contexts total, cap 8 keeps 8 of the 40-bag: (40-8)/48
        assert payload["truncated_context_fraction"] == pytest.approx(
            32 / 48
        )
        assert payload["longbag_ladder"]
        assert payload["longbag_ladder"][-1] >= 40
        assert "truncated at L=8" in out

    def test_autotune_softmax_axis_round_trips(self, tmp_path):
        from code2vec_tpu.ops import autotune as at

        variants = at.enumerate_variants(8, 300, "f32")
        modes = {
            v.softmax for v in variants if v.impl == "fused"
        }
        assert modes == {"materialize", "online", "two_pass"}
        # labels disambiguate the chunked variants
        labels = {at._variant_label(v) for v in variants}
        assert any(label.endswith("/online") for label in labels)

        # a chunked schedule persists and loads back intact
        cache = at.ScheduleCache(str(tmp_path / "sched.json"))
        key = at.ShapeKey(
            device_kind="cpu", batch=8, width=384, terminal_embed=4,
            path_embed=4, encode=8, table_dtype="f32",
        )
        cache.put(
            key,
            at.KernelSchedule(impl="fused", chunk_l=128, softmax="online"),
        )
        cache.save()
        loaded = at.ScheduleCache(cache.path).get(key)
        assert loaded.softmax == "online" and loaded.source == "cache"
        # pre-PR-13 entries (no softmax field) default to materialize
        old = at.KernelSchedule.from_dict({"impl": "fused", "block_b": 8})
        assert old.softmax == "materialize"


class TestBenchLongbagAB:
    def test_metric_id(self):
        import importlib.util
        import os

        bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        spec = importlib.util.spec_from_file_location("_bench_lab", bench_path)
        bench = importlib.util.module_from_spec(spec)
        old = sys.argv
        try:
            sys.argv = ["bench.py", "--longbag-ab"]
            spec.loader.exec_module(bench)
            assert bench._metric_id() == (
                "longbag_real_contexts_per_sec", "contexts/sec"
            )
        finally:
            sys.argv = old

    @pytest.mark.slow
    def test_end_to_end_cpu_interpret(self, tmp_path):
        import os

        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            BENCH_SUPERVISED="1",
            BENCH_AB_REPEATS="1",
        )
        bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        proc = subprocess.run(
            [sys.executable, bench_path, "--longbag-ab"],
            env=env, capture_output=True, text=True, timeout=540,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        metric = json.loads(proc.stdout.strip().splitlines()[-1])
        assert metric["metric"] == "longbag_real_contexts_per_sec"
        assert metric["value"] and metric["value"] > 0
        detail = None
        for line in proc.stderr.splitlines():
            line = line.strip()
            if line.startswith("{") and '"detail"' in line:
                detail = json.loads(line)["detail"]
        assert detail["verdict_ok"] is True
        assert detail["post_warmup_recompiles"] == 0
        # the acceptance numbers: the chunked arm truncates NOTHING while
        # the control drops a real fraction
        assert detail["truncated_context_fraction_chunked"] == 0.0
        assert detail["truncated_context_fraction_truncated"] > 0
        assert detail["real_contexts_chunked"] > detail[
            "real_contexts_truncated"
        ]
