"""The tools/ scripts are the TPU-window measurement queue — a bug that
only fires at import or arg-parse time (e.g. the profile_step sys.path
regression, fixed 2026-07-31) silently burns a scarce tunnel window via
the watcher. Pin the cheap layers: byte-compilation and argparse."""

import json
import math
import os
import py_compile
import subprocess
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
SCRIPTS = sorted(
    f for f in os.listdir(TOOLS) if f.endswith(".py")
)


@pytest.mark.parametrize("script", SCRIPTS)
def test_tool_compiles(script):
    py_compile.compile(os.path.join(TOOLS, script), doraise=True)


@pytest.mark.slow
def test_rehearse_java_large_tiny_end_to_end(tmp_path):
    """The java-large rehearsal (round-4 evidence for BASELINE config 3)
    must keep running end-to-end: all phases (gen, int32 guard, host
    shards, streaming steps, sharded staging + steps) at a ~3k-method
    scale on the virtual CPU mesh. ~2.5 min."""
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "rehearse_java_large.py"),
         "--n_methods", "3000", "--batch", "64", "--bag", "16",
         "--steps", "1", "--chunk_items", "1024", "--data_axis", "2",
         "--n_hosts", "2", "--work_dir", str(tmp_path / "rjl")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.splitlines()
             if l.startswith("{")]
    assert any(r.get("done") for r in lines)
    phases = {r.get("phase") for r in lines}
    assert {"gen", "guard", "hostshard", "stream", "shard"} <= phases
    finals = [r["final_loss"] for r in lines if "final_loss" in r]
    assert finals and all(math.isfinite(v) for v in finals)


def test_corpus_stats_end_to_end(tmp_path):
    """corpus_stats must parse an L1 corpus, print the histogram, and end
    with a machine-parsable JSON line whose ladder the --bucketed path can
    consume directly."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(TOOLS, ".."))
    from code2vec_tpu.data.synth import SPECS, generate_corpus_files

    paths = generate_corpus_files(tmp_path, SPECS["tiny"])
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "corpus_stats.py"),
         paths["corpus"], "--max_contexts", "32", "--batch_size", "32"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert out.returncode == 0, out.stderr[-1000:]
    stats = json.loads(out.stdout.strip().splitlines()[-1])
    assert stats["n_methods"] == SPECS["tiny"].n_methods
    assert stats["ladder"][-1] == 32
    assert 0.0 < stats["pad_efficiency_fixed"] <= 1.0
    assert stats["pad_efficiency_bucketed"] >= stats["pad_efficiency_fixed"] - 1e-9
    # the suggested flags appear verbatim for copy-paste
    assert "--bucket_ladder" in out.stdout

    # the CSR container path: convert, then stats MUST come from the
    # histogram footer (no context scan) and match the text-scan numbers
    csr = str(tmp_path / "corpus.csr")
    conv = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "corpus_convert.py"),
         paths["corpus"], csr],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert conv.returncode == 0, conv.stderr[-1000:]
    out_csr = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "corpus_stats.py"),
         csr, "--max_contexts", "32", "--batch_size", "32"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert out_csr.returncode == 0, out_csr.stderr[-1000:]
    assert "footer" in out_csr.stdout
    stats_csr = json.loads(out_csr.stdout.strip().splitlines()[-1])
    assert stats_csr == stats


@pytest.mark.parametrize(
    "script", ["run_tpu_ablation.py", "bench_ctx.py", "rehearse_java_large.py",
               "parity_vs_reference.py", "corpus_stats.py", "corpus_convert.py"]
)
def test_tool_argparse_help(script):
    """--help exercises import + argparse without touching a backend.
    (profile_step and the profile_ablate pair run at import; their compile
    check above plus the watcher's CPU smoke cover them.)"""
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, script), "--help"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert out.returncode == 0, out.stderr[-1000:]
