"""The tools/ scripts are the TPU-window measurement queue — a bug that
only fires at import or arg-parse time (e.g. the profile_step sys.path
regression, fixed 2026-07-31) silently burns a scarce tunnel window via
the watcher. Pin the cheap layers: byte-compilation and argparse."""

import os
import py_compile
import subprocess
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
SCRIPTS = sorted(
    f for f in os.listdir(TOOLS) if f.endswith(".py")
)


@pytest.mark.parametrize("script", SCRIPTS)
def test_tool_compiles(script):
    py_compile.compile(os.path.join(TOOLS, script), doraise=True)


@pytest.mark.parametrize(
    "script", ["run_tpu_ablation.py", "bench_ctx.py", "rehearse_java_large.py",
               "parity_vs_reference.py"]
)
def test_tool_argparse_help(script):
    """--help exercises import + argparse without touching a backend.
    (profile_step and the profile_ablate pair run at import; their compile
    check above plus the watcher's CPU smoke cover them.)"""
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, script), "--help"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.join(TOOLS, ".."),
    )
    assert out.returncode == 0, out.stderr[-1000:]
