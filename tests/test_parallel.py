"""Mesh-parallelism tests on the 8-device virtual CPU platform
(SURVEY.md §4: the TPU-pod analogue of a fake backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.parallel.context import context_parallel_attention_pool
from code2vec_tpu.parallel.distributed import global_batch
from code2vec_tpu.parallel.mesh import AXIS_MODEL, make_mesh, single_device_mesh
from code2vec_tpu.parallel.shardings import (
    batch_shardings,
    param_shardings,
    shard_batch,
    shard_state,
)
from code2vec_tpu.parallel.step import (
    make_parallel_eval_step,
    make_parallel_train_step,
)
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.step import create_train_state, make_train_step


def tiny_model_config(**kw):
    defaults = dict(
        terminal_count=63,  # deliberately NOT divisible by the model axis
        path_count=41,
        label_count=13,
        terminal_embed_size=8,
        path_embed_size=8,
        encode_size=16,
        dropout_prob=0.25,
    )
    defaults.update(kw)
    return Code2VecConfig(**defaults)


def make_batch(model_config, B=8, L=8, seed=0):
    rng = np.random.default_rng(seed)
    starts = rng.integers(1, model_config.terminal_count, (B, L)).astype(np.int32)
    starts[:, L // 2 :] = 0
    return {
        "ids": np.arange(B, dtype=np.int64),
        "starts": starts,
        "paths": rng.integers(1, model_config.path_count, (B, L)).astype(np.int32),
        "ends": rng.integers(1, model_config.terminal_count, (B, L)).astype(np.int32),
        "labels": rng.integers(0, model_config.label_count, B).astype(np.int32),
        "example_mask": np.ones(B, np.float32),
    }


class TestMesh:
    def test_three_axes(self):
        mesh = make_mesh(data=2, model=2, ctx=2)
        assert mesh.shape == {"data": 2, "model": 2, "ctx": 2}

    def test_data_absorbs_remaining(self):
        mesh = make_mesh(model=2)
        assert mesh.shape["data"] == jax.device_count() // 2

    def test_too_many_devices_rejected(self):
        with pytest.raises(ValueError):
            make_mesh(data=1000)

    def test_single_device(self):
        mesh = single_device_mesh()
        assert mesh.shape == {"data": 1, "model": 1, "ctx": 1}


class TestParamShardings:
    def test_embedding_row_sharded_head_col_sharded(self):
        mesh = make_mesh(data=2, model=2, ctx=2)
        # divisible sizes so every rule actually shards
        mc = tiny_model_config(terminal_count=64, path_count=48, label_count=16)
        model = Code2Vec(mc)
        batch = make_batch(mc)
        params = model.init(
            jax.random.PRNGKey(0), batch["starts"], batch["paths"], batch["ends"]
        )["params"]
        sh = param_shardings(mesh, params)
        assert sh["terminal_embedding"]["embedding"].spec == P(AXIS_MODEL, None)
        assert sh["path_embedding"]["embedding"].spec == P(AXIS_MODEL, None)
        assert sh["output_dense"]["kernel"].spec == P(None, AXIS_MODEL)
        assert sh["output_dense"]["bias"].spec == P(AXIS_MODEL)
        assert sh["input_dense"]["kernel"].spec == P()
        assert sh["attention"].spec == P()

    def test_model_axis_1_replicates(self):
        mesh = make_mesh(data=8, model=1, ctx=1)
        mc = tiny_model_config()
        model = Code2Vec(mc)
        batch = make_batch(mc)
        params = model.init(
            jax.random.PRNGKey(0), batch["starts"], batch["paths"], batch["ends"]
        )["params"]
        sh = param_shardings(mesh, params)
        assert sh["terminal_embedding"]["embedding"].spec == P(None, None)


class TestParallelStepEquivalence:
    """The sharded step must compute the same numbers as the single-device
    step — dp/tp/sp is an implementation detail, not a semantics change."""

    @pytest.mark.parametrize(
        "axes", [(8, 1, 1), (2, 2, 2), (1, 4, 2), (4, 2, 1), (2, 1, 4)]
    )
    def test_loss_matches_single_device(self, axes):
        data, model_ax, ctx = axes
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        cfg = TrainConfig(batch_size=8, max_path_length=8)
        class_weights = jnp.ones(mc.label_count)

        state_single = create_train_state(cfg, mc, jax.random.PRNGKey(7), batch)
        single_step = make_train_step(mc, class_weights)
        _, loss_single = single_step(state_single, batch)

        mesh = make_mesh(data=data, model=model_ax, ctx=ctx)
        state_sharded = shard_state(
            mesh, create_train_state(cfg, mc, jax.random.PRNGKey(7), batch)
        )
        parallel_step = make_parallel_train_step(mc, class_weights, mesh, state_sharded)
        state_sharded, loss_sharded = parallel_step(state_sharded, batch)

        assert float(loss_single) == pytest.approx(float(loss_sharded), rel=1e-4)

    def test_multi_step_training_matches(self):
        mc = tiny_model_config(dropout_prob=0.0)
        batch = make_batch(mc, B=8, L=8)
        cfg = TrainConfig(batch_size=8, max_path_length=8)
        class_weights = jnp.ones(mc.label_count)

        state_a = create_train_state(cfg, mc, jax.random.PRNGKey(1), batch)
        step_a = make_train_step(mc, class_weights)
        for _ in range(3):
            state_a, loss_a = step_a(state_a, batch)

        mesh = make_mesh(data=2, model=2, ctx=2)
        state_b = shard_state(
            mesh, create_train_state(cfg, mc, jax.random.PRNGKey(1), batch)
        )
        step_b = make_parallel_train_step(mc, class_weights, mesh, state_b)
        for _ in range(3):
            state_b, loss_b = step_b(state_b, batch)

        assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-4)

    def test_eval_step_outputs_match(self):
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        cfg = TrainConfig(batch_size=8, max_path_length=8)
        class_weights = jnp.ones(mc.label_count)
        from code2vec_tpu.train.step import make_eval_step

        state = create_train_state(cfg, mc, jax.random.PRNGKey(3), batch)
        out_single = make_eval_step(mc, class_weights)(state, batch)

        mesh = make_mesh(data=2, model=2, ctx=2)
        state_sh = shard_state(
            mesh, create_train_state(cfg, mc, jax.random.PRNGKey(3), batch)
        )
        out_par = make_parallel_eval_step(mc, class_weights, mesh, state_sh)(
            state_sh, batch
        )
        np.testing.assert_array_equal(
            np.asarray(out_single["preds"]), np.asarray(out_par["preds"])
        )
        np.testing.assert_allclose(
            np.asarray(out_single["code_vector"]),
            np.asarray(out_par["code_vector"]),
            rtol=1e-4,
            atol=1e-5,
        )


class TestContextParallelAttention:
    def test_matches_reference_pool(self):
        mesh = make_mesh(data=1, model=1, ctx=8)
        rng = np.random.default_rng(0)
        B, L, E = 4, 32, 16
        ctx = rng.normal(size=(B, L, E)).astype(np.float32)
        mask = (rng.random((B, L)) > 0.3).astype(np.float32)
        mask[:, 0] = 1.0
        a = rng.normal(size=E).astype(np.float32)

        cv_ref, attn_ref = attention_pool(
            jnp.asarray(ctx), jnp.asarray(mask), jnp.asarray(a)
        )
        with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
            cv_cp, attn_cp = context_parallel_attention_pool(
                mesh, jnp.asarray(ctx), jnp.asarray(mask), jnp.asarray(a)
            )
        np.testing.assert_allclose(
            np.asarray(cv_cp), np.asarray(cv_ref), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(attn_cp), np.asarray(attn_ref), rtol=1e-5, atol=1e-6
        )

    def test_gradient_matches_reference_pool(self):
        # the streaming decomposition's max-shift is gradient-free (the -dm
        # terms cancel in the softmax normalization), so stop_gradient on
        # the pmax keeps backward EXACT — grads must match the XLA pool's
        mesh = make_mesh(data=1, model=1, ctx=8)
        rng = np.random.default_rng(1)
        B, L, E = 4, 32, 16
        ctx = rng.normal(size=(B, L, E)).astype(np.float32)
        mask = (rng.random((B, L)) > 0.3).astype(np.float32)
        mask[:, 0] = 1.0
        a = rng.normal(size=E).astype(np.float32)
        cotangent = rng.normal(size=(B, E)).astype(np.float32)

        def ref_loss(ctx, a):
            cv, _ = attention_pool(ctx, jnp.asarray(mask), a)
            return jnp.sum(cv * jnp.asarray(cotangent))

        def stream_loss(ctx, a):
            cv, _ = context_parallel_attention_pool(
                mesh, ctx, jnp.asarray(mask), a
            )
            return jnp.sum(cv * jnp.asarray(cotangent))

        g_ref = jax.grad(ref_loss, argnums=(0, 1))(jnp.asarray(ctx), jnp.asarray(a))
        g_cp = jax.grad(stream_loss, argnums=(0, 1))(jnp.asarray(ctx), jnp.asarray(a))
        for r, c in zip(g_ref, g_cp):
            np.testing.assert_allclose(
                np.asarray(c), np.asarray(r), rtol=1e-5, atol=1e-6
            )


class TestShardBatchAndState:
    def test_batch_placement(self):
        mesh = make_mesh(data=4, model=2, ctx=1)
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        device_batch = shard_batch(mesh, batch)
        assert device_batch["starts"].sharding.spec == P("data", None)
        assert device_batch["labels"].sharding.spec == P("data")

    def test_uneven_vocab_sharding_works(self):
        # vocab 63 / labels 13 over model axis 2 — the indivisible dims fall
        # back to replication and training still works
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        cfg = TrainConfig(batch_size=8, max_path_length=8)
        mesh = make_mesh(data=2, model=2, ctx=1)
        state = shard_state(
            mesh, create_train_state(cfg, mc, jax.random.PRNGKey(0), batch)
        )
        step = make_parallel_train_step(mc, jnp.ones(mc.label_count), mesh, state)
        _, loss = step(state, batch)
        assert np.isfinite(float(loss))


class TestDistributedHelpers:
    def test_local_to_global_batch_single_process(self):
        from code2vec_tpu.parallel.distributed import local_to_global_batch

        mesh = make_mesh(data=8, model=1, ctx=1)
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        out = local_to_global_batch(mesh, batch)
        assert out["starts"].shape == (8, 8)
        # placed with the data-axis layout
        assert str(out["starts"].sharding.spec[0]) == "data"

    def test_global_batch_single_process(self):
        mesh = make_mesh(data=8, model=1, ctx=1)
        mc = tiny_model_config()
        batch = make_batch(mc, B=8, L=8)
        out = global_batch(mesh, batch)
        assert out["starts"].shape == (8, 8)


class TestTrainLoopWithMesh:
    def test_loop_trains_on_mesh(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(
            max_epoch=2,
            batch_size=32,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            data_axis=2,
            model_axis=2,
            context_axis=2,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0

    def test_indivisible_batch_rejected(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus
        from code2vec_tpu.data.synth import SPECS, generate_corpus_files
        from code2vec_tpu.train.loop import train

        paths = generate_corpus_files(tmp_path, SPECS["tiny"])
        data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
        cfg = TrainConfig(batch_size=31, data_axis=2, max_epoch=1)
        with pytest.raises(ValueError, match="not divisible"):
            train(cfg, data)
