"""Device-resident epoch pipeline (train/device_epoch.py).

Covers: staging correctness (contexts preserved per method, @question
substitution), rotation-window sampling semantics (all contexts when
n <= L, no duplicates, inclusion marginals), loss equivalence with the
per-batch host pipeline when subsampling is inactive, and the end-to-end
training loop with device_epoch=True.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX
from code2vec_tpu.data.reader import load_corpus
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.models.code2vec import Code2VecConfig
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.device_epoch import (
    EpochRunner,
    _sample_batch,
    stage_method_corpus,
)
from code2vec_tpu.train.loop import train
from code2vec_tpu.train.step import create_train_state, make_train_step


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    out = tmp_path_factory.mktemp("tiny_device_epoch")
    paths = generate_corpus_files(out, SPECS["tiny"])
    data = load_corpus(paths["corpus"], paths["path_idx"], paths["terminal_idx"])
    return paths, data


class TestStaging:
    def test_rows_preserved_and_shuffled_within(self, tiny):
        _, data = tiny
        rng = np.random.default_rng(0)
        idx = np.arange(data.n_items)
        staged = stage_method_corpus(data, idx, rng)
        assert staged.n_items == data.n_items
        assert staged.n_contexts == data.n_contexts
        splits = np.asarray(staged.row_splits)
        ctx = np.asarray(staged.contexts)
        mid = data.method_token_index
        for i in range(min(data.n_items, 20)):
            lo, hi = data.row_splits[i], data.row_splits[i + 1]
            want_s = data.starts[lo:hi].copy()
            want_e = data.ends[lo:hi].copy()
            if mid is not None:
                want_s[want_s == mid] = QUESTION_TOKEN_INDEX
                want_e[want_e == mid] = QUESTION_TOKEN_INDEX
            got = ctx[splits[i] : splits[i + 1]]
            # same multiset of (start, path, end) triples, any order
            want = sorted(zip(want_s, data.paths[lo:hi], want_e))
            assert sorted(map(tuple, got)) == [tuple(map(int, t)) for t in want]

    def test_subset_staging_respects_item_idx(self, tiny):
        _, data = tiny
        rng = np.random.default_rng(1)
        idx = np.asarray([3, 0, 5])
        staged = stage_method_corpus(data, idx, rng)
        counts = np.diff(np.asarray(staged.row_splits))
        want = np.diff(data.row_splits)[idx]
        assert np.array_equal(counts, want)
        assert np.array_equal(np.asarray(staged.labels), data.labels[idx])

    def test_no_method_token_leak(self, tiny):
        _, data = tiny
        mid = data.method_token_index
        if mid is None:
            pytest.skip("corpus has no @method_0 token")
        staged = stage_method_corpus(
            data, np.arange(data.n_items), np.random.default_rng(0)
        )
        ctx = np.asarray(staged.contexts)
        assert not (ctx[:, 0] == mid).any()
        assert not (ctx[:, 2] == mid).any()


class TestSampling:
    def _csr(self, lens, seed=0):
        rng = np.random.default_rng(seed)
        splits = np.zeros(len(lens) + 1, np.int32)
        np.cumsum(lens, out=splits[1:])
        total = int(splits[-1])
        ctx = rng.integers(1, 1000, (total, 3)).astype(np.int32)
        labels = rng.integers(0, 7, len(lens)).astype(np.int32)
        return jnp.asarray(ctx), jnp.asarray(splits), jnp.asarray(labels), ctx

    def test_small_rows_take_everything_once(self):
        bag = 8
        ctx, splits, labels, ctx_np = self._csr([5, 8, 1, 0])
        rows = jnp.arange(4, dtype=jnp.int32)
        batch = _sample_batch(
            ctx, splits, labels, rows, jnp.ones(4), bag, jax.random.PRNGKey(0)
        )
        starts = np.asarray(batch["starts"])
        sp = np.asarray(splits)
        for i, n in enumerate([5, 8, 1, 0]):
            row = starts[i]
            assert (row[n:] == PAD_INDEX).all()
            # every context appears exactly once (rotation of the full row)
            want = sorted(ctx_np[sp[i] : sp[i] + n, 0])
            assert sorted(row[:n]) == [int(x) for x in want]

    def test_large_rows_no_duplicates_and_fresh_windows(self):
        bag = 8
        ctx, splits, labels, ctx_np = self._csr([40])
        rows = jnp.zeros(1, jnp.int32)
        seen = set()
        for seed in range(6):
            batch = _sample_batch(
                ctx, splits, labels, rows, jnp.ones(1), bag,
                jax.random.PRNGKey(seed),
            )
            window = tuple(int(x) for x in np.asarray(batch["paths"])[0])
            assert len(set(window)) == bag  # no duplicates within a bag
            seen.add(window)
        assert len(seen) > 1  # different epochs draw different windows

    def test_inclusion_marginals_uniform(self):
        # over many draws every context of an oversized row should be
        # included ~ bag/n of the time
        bag, n = 16, 64
        ctx, splits, labels, _ = self._csr([n])
        # unique start values so hits map back to one context each
        ctx = ctx.at[:, 0].set(jnp.arange(1, n + 1, dtype=jnp.int32))
        counts = np.zeros(n)
        draws = 300
        for seed in range(draws):
            batch = _sample_batch(
                ctx, splits, labels, jnp.zeros(1, jnp.int32), jnp.ones(1),
                bag, jax.random.PRNGKey(seed),
            )
            got = np.asarray(batch["starts"])[0]
            flat = np.asarray(ctx)[:, 0]
            for v in got:
                counts[np.where(flat == v)[0][0]] += 1
        expect = draws * bag / n
        assert counts.min() > 0.5 * expect
        assert counts.max() < 1.7 * expect


class TestRunnerEquivalence:
    def test_matches_host_loop_without_subsampling(self, tiny):
        """With bag >= every row length, dropout off and identical batch
        order, the scanned device epoch must equal the per-batch host loop
        (bags are permutation-invariant under attention pooling)."""
        _, data = tiny
        bag = int(np.diff(data.row_splits).max())
        config = TrainConfig(
            batch_size=16,
            max_path_length=bag,
            dropout_prob=0.0,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
        )
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16,
            path_embed_size=16,
            encode_size=32,
            dropout_prob=0.0,
        )
        cw = jnp.ones(model_config.label_count, jnp.float32)
        idx = np.arange(data.n_items)

        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        state_a = create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        )
        state_b = create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        )

        # host path: one epoch over idx in a fixed order
        from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches

        epoch = build_method_epoch(
            data, idx, bag, np.random.default_rng(0)
        )
        step = make_train_step(model_config, cw)
        host_losses = []
        for batch in iter_batches(epoch, 16, rng=None, pad_final=True):
            state_a, loss = step(state_a, batch)
            host_losses.append(float(loss))

        # device path: same order (corpus staged in idx order, identity perm)
        runner = EpochRunner(model_config, cw, 16, bag, chunk_batches=4)
        staged = stage_method_corpus(data, idx, np.random.default_rng(0))

        class _IdentityRng:
            def permutation(self, n):
                return np.arange(n)

        state_b, dev_loss, n_batches = runner.run_train_epoch(
            state_b, staged, _IdentityRng(), jax.random.PRNGKey(7)
        )
        assert n_batches == len(host_losses)
        assert dev_loss == pytest.approx(sum(host_losses), rel=2e-4)

        # final params identical too (same batches, same math)
        diff = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            state_a.params,
            state_b.params,
        )
        # not bit-identical: bag order differs (rotation vs shuffle), and
        # Adam's grad^2 / sqrt amplify float-association differences
        assert max(jax.tree.leaves(diff)) < 5e-4

    def test_eval_epoch_prediction_parity(self, tiny):
        _, data = tiny
        bag = int(np.diff(data.row_splits).max())
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16,
            path_embed_size=16,
            encode_size=32,
            dropout_prob=0.0,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag, dropout_prob=0.0)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        idx = np.arange(data.n_items)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        state = create_train_state(
            config, model_config, jax.random.PRNGKey(3), example
        )

        from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches
        from code2vec_tpu.train.step import make_eval_step

        epoch = build_method_epoch(data, idx, bag, np.random.default_rng(0))
        eval_step = make_eval_step(model_config, cw)
        host_preds = []
        for batch in iter_batches(epoch, 16, rng=None, pad_final=True):
            out = eval_step(state, batch)
            valid = batch["example_mask"].astype(bool)
            host_preds.append(np.asarray(out["preds"])[valid])
        host_preds = np.concatenate(host_preds)

        runner = EpochRunner(model_config, cw, 16, bag, chunk_batches=4)
        staged = stage_method_corpus(data, idx, np.random.default_rng(0))
        _, dev_preds, _ = runner.run_eval_epoch(state, staged, jax.random.PRNGKey(9))
        assert np.array_equal(host_preds, dev_preds)


class TestSamplePrefetch:
    """sample_prefetch=True double-buffers sampling inside the scanned
    chunk. The sample-key split sequence is unchanged, so the runner
    consumes the SAME batches in the same order; losses/params match up
    to float reassociation (the two settings compile different XLA
    programs, which may reorder f32 reductions — observed ~1e-7)."""

    def test_bit_identical_losses_and_params(self, tiny):
        _, data = tiny
        bag = 8
        config = TrainConfig(
            batch_size=16, max_path_length=bag, encode_size=32,
            terminal_embed_size=16, path_embed_size=16,
        )
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16, path_embed_size=16, encode_size=32,
            dropout_prob=0.25,  # dropout ON: the state rng stream must
                                # align too, not just the sample keys
        )
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        idx = np.arange(data.n_items)
        staged = stage_method_corpus(data, idx, np.random.default_rng(0))
        chunk = 4
        n_valid = chunk * 16
        rows = np.random.default_rng(1).integers(
            0, data.n_items, n_valid
        ).astype(np.int32)

        finals = []
        for prefetch in (False, True):
            state = create_train_state(
                config, model_config, jax.random.PRNGKey(0), example
            )
            runner = EpochRunner(model_config, cw, 16, bag, chunk,
                                 sample_prefetch=prefetch)
            run = runner._train_chunk(chunk)
            state, loss = run(state, staged.contexts, staged.row_splits,
                              staged.labels, rows, n_valid,
                              jax.random.PRNGKey(7))
            finals.append((state, float(loss)))

        (state_a, loss_a), (state_b, loss_b) = finals
        np.testing.assert_allclose(loss_b, loss_a, rtol=1e-6)
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-7
            ),
            state_a.params, state_b.params,
        )

    def test_prefetch_consumes_identical_batches_in_order(self, tiny):
        """The stronger claim, pinned against the REAL chunk programs: stub
        the train step with an exact integer checksum of the batch, weighted
        by the step counter (order-sensitive), and require the two variants'
        chunk outputs to be equal — integer sums are associative, so this
        is cross-program exact, unlike the float loss."""
        _, data = tiny
        bag = 8
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16, path_embed_size=16, encode_size=32,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag,
                             encode_size=32, terminal_embed_size=16,
                             path_embed_size=16)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        idx = np.arange(data.n_items)
        staged = stage_method_corpus(data, idx, np.random.default_rng(0))
        chunk = 4
        n_valid = chunk * 16
        rows = np.random.default_rng(1).integers(
            0, data.n_items, n_valid
        ).astype(np.int32)

        def checksum_step(state, batch):
            # int32 wraparound arithmetic: exact and order-independent
            # within a batch; the step-counter weight pins batch ORDER
            chk = (
                jnp.sum(batch["starts"].astype(jnp.int32)) * 7
                + jnp.sum(batch["paths"].astype(jnp.int32)) * 11
                + jnp.sum(batch["ends"].astype(jnp.int32)) * 13
                + jnp.sum(batch["labels"].astype(jnp.int32)) * 17
            )
            state = state.replace(step=state.step + 1)
            # stays int32 through scan/sum: exact mod 2^32 (a float32 cast
            # would lose exactness above 2^24)
            return state, chk * state.step.astype(jnp.int32)

        sums = []
        for prefetch in (False, True):
            state = create_train_state(
                config, model_config, jax.random.PRNGKey(0), example
            )
            runner = EpochRunner(model_config, cw, 16, bag, chunk,
                                 sample_prefetch=prefetch)
            runner._raw_train = checksum_step  # before _train_chunk caches
            run = runner._train_chunk(chunk)
            _, total = run(state, staged.contexts, staged.row_splits,
                           staged.labels, rows, n_valid,
                           jax.random.PRNGKey(7))
            sums.append(float(total))
        assert sums[0] == sums[1]  # exact: same batches, same order

    def test_prefetch_composes_with_variable_task(self, tmp_path_factory):
        """The remap-enabled sampler (variable task, shuffled @var ids)
        rides in the prefetch carry too."""
        out = tmp_path_factory.mktemp("prefetch_vars")
        paths = generate_corpus_files(out, SPECS["tiny"])
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            infer_method=False, infer_variable=True, cache=False,
        )
        config = TrainConfig(
            max_epoch=2, batch_size=16, encode_size=32,
            terminal_embed_size=16, path_embed_size=16, max_path_length=32,
            print_sample_cycle=0, device_epoch=True,
            device_chunk_batches=4, sample_prefetch=True,
            infer_method_name=False, infer_variable_name=True,
            shuffle_variable_indexes=True,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])

    def test_prefetch_composes_with_mesh(self, tiny):
        """The carried batch lives in the scan carry with its sharding
        constraints — must compile and train on a data×ctx mesh via the
        full loop."""
        _, data = tiny
        config = TrainConfig(
            max_epoch=2, batch_size=16, encode_size=32,
            terminal_embed_size=16, path_embed_size=16, max_path_length=32,
            print_sample_cycle=0, device_epoch=True,
            device_chunk_batches=4, sample_prefetch=True,
            data_axis=2, context_axis=2,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])

    def test_prefetch_rejected_without_device_epoch(self, tiny):
        _, data = tiny
        base = dict(
            max_epoch=1, batch_size=16, encode_size=32,
            terminal_embed_size=16, path_embed_size=16, max_path_length=32,
            print_sample_cycle=0, sample_prefetch=True,
        )
        with pytest.raises(ValueError, match="requires --device_epoch"):
            train(TrainConfig(**base), data)

    def test_eval_epoch_matches_unprefetched(self, tiny):
        """Eval chunks double-buffer too: same key walk → same sampled
        batches → identical predictions (integer argmax; float loss up to
        reassociation)."""
        _, data = tiny
        bag = 8
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16, path_embed_size=16, encode_size=32,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag,
                             encode_size=32, terminal_embed_size=16,
                             path_embed_size=16)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        state = create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        )
        staged = stage_method_corpus(
            data, np.arange(data.n_items), np.random.default_rng(0)
        )
        outs = []
        for prefetch in (False, True):
            runner = EpochRunner(model_config, cw, 16, bag, 4,
                                 sample_prefetch=prefetch)
            outs.append(runner.run_eval_epoch(
                state, staged, jax.random.PRNGKey(5)
            ))
        (loss_a, preds_a, ml_a), (loss_b, preds_b, ml_b) = outs
        np.testing.assert_array_equal(np.asarray(preds_b), np.asarray(preds_a))
        np.testing.assert_allclose(np.asarray(ml_b), np.asarray(ml_a),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(loss_b, loss_a, rtol=1e-6)

    def test_sharded_prefetch_consumes_identical_batches_in_order(self, tiny):
        """Same exact-checksum pin as the replicated runner, against the
        sharded runner's shard_map sampler on a data=2 mesh."""
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            stage_method_corpus_sharded,
        )

        _, data = tiny
        bag = 8
        mesh = make_mesh(data=2)
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16, path_embed_size=16, encode_size=32,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag,
                             encode_size=32, terminal_embed_size=16,
                             path_embed_size=16)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        staged = stage_method_corpus_sharded(
            data, np.arange(data.n_items), np.random.default_rng(0), mesh
        )
        chunk = 4

        def checksum_step(state, batch):
            chk = (
                jnp.sum(batch["starts"].astype(jnp.int32)) * 7
                + jnp.sum(batch["paths"].astype(jnp.int32)) * 11
                + jnp.sum(batch["ends"].astype(jnp.int32)) * 13
                + jnp.sum(batch["labels"].astype(jnp.int32)) * 17
            )
            state = state.replace(step=state.step + 1)
            return state, chk * state.step.astype(jnp.int32)

        sums = []
        for prefetch in (False, True):
            state = create_train_state(
                config, model_config, jax.random.PRNGKey(0), example
            )
            runner = ShardedEpochRunner(model_config, cw, 16, bag, chunk,
                                        mesh=mesh, sample_prefetch=prefetch)
            runner._raw_train = checksum_step
            run = runner._train_chunk(chunk)
            span = chunk * runner.per_shard
            rows = np.random.default_rng(1).integers(
                0, np.maximum(staged.shard_counts[:, None], 1),
                (runner.n_shards, span),
            ).astype(np.int32)
            valid = np.ones((runner.n_shards, span), np.float32)
            _, total = run(state, staged.contexts, staged.row_splits,
                           staged.labels, rows, valid,
                           jax.random.PRNGKey(7))
            sums.append(int(total))
        assert sums[0] == sums[1]

    def test_sharded_eval_epoch_matches_unprefetched(self, tiny):
        """The sharded eval chunk's prefetch carry (a shard_map-assembled
        batch dict with data-axis shardings) must compile and produce
        identical predictions."""
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            stage_method_corpus_sharded,
        )

        _, data = tiny
        bag = 8
        mesh = make_mesh(data=2)
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16, path_embed_size=16, encode_size=32,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag,
                             encode_size=32, terminal_embed_size=16,
                             path_embed_size=16)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        state = shard_state(mesh, create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        ))
        staged = stage_method_corpus_sharded(
            data, np.arange(data.n_items), np.random.default_rng(0), mesh
        )
        outs = []
        for prefetch in (False, True):
            runner = ShardedEpochRunner(model_config, cw, 16, bag, 4,
                                        mesh=mesh, sample_prefetch=prefetch)
            outs.append(runner.run_eval_epoch(
                state, staged, jax.random.PRNGKey(5)
            ))
        (loss_a, preds_a, _), (loss_b, preds_b, _) = outs
        np.testing.assert_array_equal(np.asarray(preds_b), np.asarray(preds_a))
        np.testing.assert_allclose(loss_b, loss_a, rtol=1e-6)

    def test_prefetch_composes_with_sharded_staging(self, tiny):
        """The sharded runner's shard_map sampler double-buffers the same
        way; end-to-end via the full loop on a data=2 mesh."""
        _, data = tiny
        config = TrainConfig(
            max_epoch=2, batch_size=16, encode_size=32,
            terminal_embed_size=16, path_embed_size=16, max_path_length=32,
            print_sample_cycle=0, device_epoch=True,
            device_chunk_batches=4, sample_prefetch=True,
            data_axis=2, shard_staged_corpus=True,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])


class TestVariableTask:
    """Device epochs for the variable task: corpus-static expansion staged
    as rows, per-epoch @var remap on device."""

    @pytest.fixture(scope="class")
    def vdata(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("var_device_epoch")
        paths = generate_corpus_files(out, SPECS["tiny"])
        return load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            infer_method=False, infer_variable=True, cache=False,
        )

    def test_staging_matches_host_expansion(self, vdata):
        from code2vec_tpu.data.pipeline import build_variable_epoch
        from code2vec_tpu.train.device_epoch import stage_variable_corpus

        idx = np.arange(vdata.n_items)
        bag = 64  # >= any per-variable context count in tiny
        epoch = build_variable_epoch(vdata, idx, bag, np.random.default_rng(0))
        staged = stage_variable_corpus(vdata, idx, np.random.default_rng(1))
        assert staged.n_items == len(epoch)
        np.testing.assert_array_equal(np.asarray(staged.labels), epoch.labels)
        splits = np.asarray(staged.row_splits)
        ctx = np.asarray(staged.contexts)
        for r in range(staged.n_items):
            got = sorted(map(tuple, ctx[splits[r] : splits[r + 1]]))
            valid = epoch.starts[r] != PAD_INDEX
            want = sorted(
                zip(
                    epoch.starts[r][valid].tolist(),
                    epoch.paths[r][valid].tolist(),
                    epoch.ends[r][valid].tolist(),
                )
            )
            assert got == want, f"row {r} context multiset mismatch"

    def test_eval_prediction_parity_no_shuffle(self, vdata):
        from code2vec_tpu.data.pipeline import build_variable_epoch, iter_batches
        from code2vec_tpu.train.device_epoch import stage_variable_corpus
        from code2vec_tpu.train.step import make_eval_step

        idx = np.arange(vdata.n_items)
        bag = 64
        model_config = Code2VecConfig(
            terminal_count=len(vdata.terminal_vocab),
            path_count=len(vdata.path_vocab),
            label_count=len(vdata.label_vocab),
            terminal_embed_size=16,
            path_embed_size=16,
            encode_size=32,
            dropout_prob=0.0,
        )
        config = TrainConfig(batch_size=16, max_path_length=bag, dropout_prob=0.0)
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((16, bag), np.int32),
            "paths": np.zeros((16, bag), np.int32),
            "ends": np.zeros((16, bag), np.int32),
            "labels": np.zeros(16, np.int32),
            "example_mask": np.ones(16, np.float32),
        }
        state = create_train_state(
            config, model_config, jax.random.PRNGKey(3), example
        )
        epoch = build_variable_epoch(vdata, idx, bag, np.random.default_rng(0))
        eval_step = make_eval_step(model_config, cw)
        host_preds = []
        for batch in iter_batches(epoch, 16, rng=None, pad_final=True):
            out = eval_step(state, batch)
            valid = batch["example_mask"].astype(bool)
            host_preds.append(np.asarray(out["preds"])[valid])
        host_preds = np.concatenate(host_preds)

        runner = EpochRunner(model_config, cw, 16, bag, chunk_batches=4)
        staged = stage_variable_corpus(vdata, idx, np.random.default_rng(0))
        _, dev_preds, _ = runner.run_eval_epoch(
            state, staged, jax.random.PRNGKey(9)
        )
        assert np.array_equal(host_preds, dev_preds)

    def test_remap_permutes_var_ids_only(self, vdata):
        from code2vec_tpu.train.device_epoch import (
            _sample_batch,
            stage_variable_corpus,
        )

        idx = np.arange(vdata.n_items)
        staged = stage_variable_corpus(vdata, idx, np.random.default_rng(0))
        var_ids = set(np.asarray(staged.remap_ids).tolist())
        rows = jnp.arange(min(8, staged.n_items), dtype=jnp.int32)
        plain = _sample_batch(
            staged.contexts, staged.row_splits, staged.labels, rows,
            jnp.ones(len(rows)), 32, jax.random.PRNGKey(0),
        )
        remapped = _sample_batch(
            staged.contexts, staged.row_splits, staged.labels, rows,
            jnp.ones(len(rows)), 32, jax.random.PRNGKey(0),
            staged.remap_ids, staged.remap_flags,
        )
        p_starts = np.asarray(plain["starts"])
        r_starts = np.asarray(remapped["starts"])
        # identical sampling -> non-var positions unchanged; var positions
        # stay inside the var-id set (a permutation, not arbitrary ids)
        non_var = ~np.isin(p_starts, list(var_ids))
        np.testing.assert_array_equal(p_starts[non_var], r_starts[non_var])
        is_var = np.isin(p_starts, list(var_ids))
        if is_var.any():
            assert set(r_starts[is_var].tolist()) <= var_ids
        # per-row bijectivity: within one row, equal originals map equal,
        # distinct originals map distinct
        for r in range(len(rows)):
            mapping = {}
            for o, m in zip(p_starts[r][is_var[r]], r_starts[r][is_var[r]]):
                assert mapping.setdefault(int(o), int(m)) == int(m)
            assert len(set(mapping.values())) == len(mapping)

    @pytest.mark.parametrize("shuffle", [False, True])
    def test_end_to_end_variable_training(self, vdata, shuffle):
        config = TrainConfig(
            max_epoch=2,
            batch_size=16,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=32,
            print_sample_cycle=0,
            device_epoch=True,
            device_chunk_batches=4,
            infer_method_name=False,
            infer_variable_name=True,
            shuffle_variable_indexes=shuffle,
        )
        result = train(config, vdata)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])

    def test_end_to_end_combined_tasks(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("combined_device_epoch")
        paths = generate_corpus_files(out, SPECS["tiny"])
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            infer_method=True, infer_variable=True, cache=False,
        )
        config = TrainConfig(
            max_epoch=2,
            batch_size=16,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=32,
            print_sample_cycle=0,
            device_epoch=True,
            device_chunk_batches=4,
            infer_method_name=True,
            infer_variable_name=True,
            shuffle_variable_indexes=True,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])


class TestMeshComposition:
    """Device epochs × mesh (VERDICT r2 #1): the staged fast path must run
    SPMD over the data/ctx axes with loss parity vs the unmeshed runner."""

    def _setup(self, data, bag=32, batch=16):
        model_config = Code2VecConfig(
            terminal_count=len(data.terminal_vocab),
            path_count=len(data.path_vocab),
            label_count=len(data.label_vocab),
            terminal_embed_size=16,
            path_embed_size=16,
            encode_size=32,
            dropout_prob=0.0,
        )
        config = TrainConfig(
            batch_size=batch, max_path_length=bag, dropout_prob=0.0
        )
        cw = jnp.ones(model_config.label_count, jnp.float32)
        example = {
            "starts": np.zeros((batch, bag), np.int32),
            "paths": np.zeros((batch, bag), np.int32),
            "ends": np.zeros((batch, bag), np.int32),
            "labels": np.zeros(batch, np.int32),
            "example_mask": np.ones(batch, np.float32),
        }
        state = create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        )
        return model_config, cw, state

    @pytest.mark.parametrize("axes", [dict(data=4), dict(data=2, ctx=2)])
    def test_meshed_runner_matches_unmeshed(self, tiny, axes):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state

        _, data = tiny
        model_config, cw, state = self._setup(data)
        mesh = make_mesh(**axes)
        idx = np.arange(data.n_items)

        plain = EpochRunner(model_config, cw, 16, 32, chunk_batches=4)
        staged = stage_method_corpus(data, idx, np.random.default_rng(0))
        s_plain, loss_plain, nb = plain.run_train_epoch(
            state, staged, np.random.default_rng(1), jax.random.PRNGKey(7)
        )

        meshed = EpochRunner(model_config, cw, 16, 32, chunk_batches=4, mesh=mesh)
        staged_m = stage_method_corpus(
            data, idx, np.random.default_rng(0),
            device=NamedSharding(mesh, P()),
        )
        state_m = self._setup(data)[2]  # fresh identical init
        state_m = shard_state(mesh, state_m)
        s_mesh, loss_mesh, nb_m = meshed.run_train_epoch(
            state_m, staged_m, np.random.default_rng(1), jax.random.PRNGKey(7)
        )

        assert nb == nb_m
        # same seeds -> same sampled batches; SPMD changes only the
        # reduction association, so losses agree to float tolerance
        assert loss_mesh == pytest.approx(loss_plain, rel=1e-4)
        diff = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            s_plain.params,
            jax.device_get(s_mesh.params),
        )
        assert max(jax.tree.leaves(diff)) < 1e-4

        # eval parity on the meshed runner too
        _, preds_plain, _ = plain.run_eval_epoch(
            s_plain, staged, jax.random.PRNGKey(9)
        )
        _, preds_mesh, _ = meshed.run_eval_epoch(
            s_mesh, staged_m, jax.random.PRNGKey(9)
        )
        assert np.mean(preds_plain == preds_mesh) > 0.95  # ties may flip

    def test_train_loop_device_epoch_with_mesh(self, tiny):
        """--device_epoch --data_axis now composes instead of silently
        falling back (the loop.py:232-238 restriction is gone)."""
        _, data = tiny
        config = TrainConfig(
            max_epoch=2,
            batch_size=32,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=32,
            print_sample_cycle=0,
            device_epoch=True,
            device_chunk_batches=4,
            data_axis=4,
            model_axis=2,
        )
        result = train(config, data)
        assert result.epochs_run == 2
        assert np.isfinite(result.history[-1]["train_loss"])
        # the staged corpus must actually live on all 8 mesh devices
        assert result.state is not None


class TestLoopIntegration:
    def test_end_to_end_device_epoch_training(self, tiny, tmp_path):
        _, data = tiny
        config = TrainConfig(
            max_epoch=3,
            batch_size=32,
            encode_size=64,
            terminal_embed_size=32,
            path_embed_size=32,
            max_path_length=32,
            print_sample_cycle=0,
            device_epoch=True,
            device_chunk_batches=4,
        )
        vectors = tmp_path / "code.vec"
        result = train(
            config, data, out_dir=str(tmp_path), vectors_path=str(vectors)
        )
        assert result.epochs_run == 3
        assert np.isfinite(result.history[-1]["train_loss"])
        assert result.best_f1 >= 0.0
        assert vectors.exists()  # best-F1 export built host epochs on demand

    def test_device_and_host_loops_converge_similarly(self, tiny):
        _, data = tiny
        base = dict(
            max_epoch=3,
            batch_size=32,
            encode_size=64,
            terminal_embed_size=32,
            path_embed_size=32,
            max_path_length=32,
            print_sample_cycle=0,
        )
        host = train(TrainConfig(**base), data)
        dev = train(TrainConfig(**base, device_epoch=True, device_chunk_batches=4), data)
        # same data, same recipe -> same ballpark (not bit-identical: the
        # device path samples windows, the host path samples subsets)
        h = host.history[-1]["train_loss"]
        d = dev.history[-1]["train_loss"]
        assert d == pytest.approx(h, rel=0.35)


class TestShardedStaging:
    """Data-axis-sharded corpus staging (the HBM-scaling follow-on in
    ARCHITECTURE.md): per-device corpus memory ~1/D, shard_map sampling,
    stratified-by-shard batches."""

    def test_partition_covers_all_and_balances(self):
        from code2vec_tpu.train.device_epoch import partition_items_balanced

        rng = np.random.default_rng(0)
        counts = rng.integers(1, 120, 101)
        groups = partition_items_balanced(counts, 4)
        seen = np.sort(np.concatenate(groups))
        np.testing.assert_array_equal(seen, np.arange(101))
        # ITEM counts equal +-1: the largest shard sets the epoch length
        sizes = np.array([len(g) for g in groups])
        assert sizes.max() - sizes.min() <= 1
        # context loads close (snake dealing over descending counts)
        loads = np.array([counts[g].sum() for g in groups])
        assert loads.max() - loads.min() <= counts.max()

    def test_partition_heavy_tail_keeps_items_even(self):
        # a few huge methods + many tiny ones must NOT produce an
        # item-imbalanced partition (which would inflate the epoch with
        # masked batches)
        from code2vec_tpu.train.device_epoch import partition_items_balanced

        counts = np.asarray([10_000, 9_000, 8_000] + [3] * 997)
        groups = partition_items_balanced(counts, 4)
        sizes = np.array([len(g) for g in groups])
        assert sizes.max() - sizes.min() <= 1

    def test_sharded_layout_is_one_block_per_data_shard(self, tiny):
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.train.device_epoch import stage_method_corpus_sharded

        _, data = tiny
        mesh = make_mesh(data=4, model=2)
        idx = np.arange(data.n_items)
        staged = stage_method_corpus_sharded(
            data, idx, np.random.default_rng(0), mesh
        )
        assert int(staged.shard_counts.sum()) == data.n_items
        # contexts are partitioned over data (each data shard holds 1 block,
        # replicated over the model axis)
        shard_shapes = {
            s.data.shape for s in staged.contexts.addressable_shards
        }
        assert shard_shapes == {(1, staged.contexts.shape[1], 3)}
        # every staged context of every shard appears in the source corpus
        total_real = sum(
            int(np.asarray(staged.row_splits)[s, staged.shard_counts[s]])
            for s in range(4)
        )
        assert total_real == int(np.diff(data.row_splits)[idx].sum())

    def test_sharded_runner_trains_and_roughly_matches_replicated(self, tiny):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            stage_method_corpus_sharded,
        )

        _, data = tiny
        helper = TestMeshComposition()
        model_config, cw, state = helper._setup(data)
        mesh = make_mesh(data=4, model=2)
        idx = np.arange(data.n_items)

        sharded = ShardedEpochRunner(
            model_config, cw, 16, 32, chunk_batches=4, mesh=mesh
        )
        staged_s = stage_method_corpus_sharded(
            data, idx, np.random.default_rng(0), mesh
        )
        state_s = shard_state(mesh, state)
        losses = []
        key = jax.random.PRNGKey(7)
        rng = np.random.default_rng(1)
        for _ in range(3):
            key, k = jax.random.split(key)
            state_s, loss, nb = sharded.run_train_epoch(state_s, staged_s, rng, k)
            losses.append(loss / nb)
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # it learns

        # replicated-staging comparison on the same recipe: stratified
        # sampling is a different draw order, so compare per-batch loss
        # magnitude after the same number of epochs, loosely
        replicated = EpochRunner(
            model_config, cw, 16, 32, chunk_batches=4, mesh=mesh
        )
        staged_r = stage_method_corpus(
            data, idx, np.random.default_rng(0),
            device=NamedSharding(mesh, P()),
        )
        state_r = shard_state(mesh, helper._setup(data)[2])
        r_losses = []
        key = jax.random.PRNGKey(7)
        rng = np.random.default_rng(1)
        for _ in range(3):
            key, k = jax.random.split(key)
            state_r, loss, nb_r = replicated.run_train_epoch(
                state_r, staged_r, rng, k
            )
            r_losses.append(loss / nb_r)
        assert losses[-1] == pytest.approx(r_losses[-1], rel=0.5)

    def test_ctx_axis_rejected(self, tiny):
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.train.device_epoch import ShardedEpochRunner

        _, data = tiny
        helper = TestMeshComposition()
        model_config, cw, _ = helper._setup(data)
        mesh = make_mesh(data=2, ctx=2)
        with pytest.raises(ValueError, match="ctx-sharded"):
            ShardedEpochRunner(model_config, cw, 16, 32, mesh=mesh)

    def test_indivisible_batch_rejected(self, tiny):
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.train.device_epoch import ShardedEpochRunner

        _, data = tiny
        helper = TestMeshComposition()
        model_config, cw, _ = helper._setup(data)
        mesh = make_mesh(data=4)
        with pytest.raises(ValueError, match="not divisible"):
            ShardedEpochRunner(model_config, cw, 15, 32, mesh=mesh)

    def test_train_loop_shard_staged_corpus(self, tiny):
        _, data = tiny
        cfg = TrainConfig(
            max_epoch=2,
            batch_size=16,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            device_epoch=True,
            shard_staged_corpus=True,
            data_axis=4,
            model_axis=2,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0

    def test_shard_staged_requires_mesh(self, tiny):
        _, data = tiny
        cfg = TrainConfig(
            max_epoch=1, batch_size=16, device_epoch=True,
            shard_staged_corpus=True,
        )
        with pytest.raises(ValueError, match="shard_staged_corpus needs"):
            train(cfg, data)

    def test_train_loop_shard_staged_variable_task(self, tiny):
        # the variable task shards too: remap ids replicated, flags
        # partitioned with the rows, per-epoch @var remap on device
        paths, _ = tiny
        data = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            infer_method=False, infer_variable=True, cache=False,
        )
        cfg = TrainConfig(
            max_epoch=2,
            batch_size=16,
            encode_size=32,
            terminal_embed_size=16,
            path_embed_size=16,
            max_path_length=16,
            print_sample_cycle=0,
            device_epoch=True,
            shard_staged_corpus=True,
            data_axis=4,
            infer_method_name=False,
            infer_variable_name=True,
            shuffle_variable_indexes=True,
        )
        res = train(cfg, data)
        assert np.isfinite(res.history[-1]["train_loss"])
        assert res.final_f1 > 0.0

    def test_shard_staged_requires_device_epoch(self, tiny):
        # without --device_epoch the flag would otherwise be silently
        # ignored (the HBM reduction the user asked for never happens)
        _, data = tiny
        cfg = TrainConfig(
            max_epoch=1, batch_size=16, data_axis=4,
            shard_staged_corpus=True,
        )
        with pytest.raises(ValueError, match="requires --device_epoch"):
            train(cfg, data)

    def test_sharded_eval_matches_replicated_multiset(self, tiny):
        # bag >= every method's context count makes eval deterministic
        # (sampling takes everything; pooling is permutation-invariant), so
        # the (label, pred) pair multiset must match the replicated
        # runner's exactly, just in shard-concatenation order
        from collections import Counter

        from jax.sharding import NamedSharding, PartitionSpec as P

        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.train.device_epoch import (
            ShardedEpochRunner,
            stage_method_corpus_sharded,
        )

        _, data = tiny
        bag = int(np.diff(data.row_splits).max())
        helper = TestMeshComposition()
        model_config, cw, state = helper._setup(data, bag=bag)
        mesh = make_mesh(data=4, model=2)
        idx = np.arange(data.n_items)

        replicated = EpochRunner(model_config, cw, 16, bag, chunk_batches=4,
                                 mesh=mesh)
        staged_r = stage_method_corpus(
            data, idx, np.random.default_rng(0),
            device=NamedSharding(mesh, P()),
        )
        state_m = shard_state(mesh, state)
        _, preds_r, _ = replicated.run_eval_epoch(
            state_m, staged_r, jax.random.PRNGKey(9)
        )
        pairs_r = Counter(zip(np.asarray(staged_r.labels).tolist(),
                              preds_r.tolist()))

        sharded = ShardedEpochRunner(model_config, cw, 16, bag,
                                     chunk_batches=4, mesh=mesh)
        staged_s = stage_method_corpus_sharded(
            data, idx, np.random.default_rng(0), mesh
        )
        loss_s, preds_s, logits_s = sharded.run_eval_epoch(
            state_m, staged_s, jax.random.PRNGKey(9)
        )
        expected = staged_s.flat_labels()
        assert len(preds_s) == len(expected) == data.n_items
        pairs_s = Counter(zip(expected.tolist(), preds_s.tolist()))
        assert pairs_s == pairs_r
        assert np.isfinite(loss_s) and len(logits_s) == data.n_items
