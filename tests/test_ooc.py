"""Out-of-core corpora (ISSUE 10): the binary CSR container, the unified
BatchSource protocol, and the composition matrix.

The load-bearing guarantees:

- text -> CSR -> text conversion is byte-faithful, and the container's
  histogram footer equals a full scan;
- the CSR mmap loader produces the SAME CorpusData semantics as the text
  parser (arrays, label-vocab insertion order, aliases, shards);
- every feed variant — {fixed-L, bucketed, streaming, mmap-gather} x
  {sync, prefetched} — yields the SAME per-example loss multiset and
  bitwise-equal eval metrics as the in-RAM fixed-L reference (under
  canonical context order, bag >= every real count);
- the previously-forbidden compositions (bucketed x streaming, bucketed x
  shard_staged, mmap x everything) train end to end with zero post-warmup
  recompiles, report pad_efficiency, resume bitwise from mid-epoch
  cursors, and keep host RSS bounded below the corpus size.
"""

import json
import os
import resource
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu import PAD_INDEX, faultinject
from code2vec_tpu.data.pipeline import (
    EpochSource,
    MmapCorpusSource,
    StreamingSource,
    assign_buckets,
    bucket_batch_counts,
    derive_bucket_ladder,
    derive_bucket_ladder_hist,
    iter_scheduled_bucketed_batches,
    make_batch_source,
    variable_items,
)
from code2vec_tpu.data.reader import load_corpus, load_corpus_csr
from code2vec_tpu.data.synth import SPECS, generate_corpus_files
from code2vec_tpu.formats.corpus_io import (
    CorpusRecord,
    is_csr_corpus,
    iter_corpus_records,
    open_corpus_csr,
    read_csr_histogram,
    write_corpus_csr,
)
from code2vec_tpu.metrics import evaluate
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.loop import model_config_from, train
from code2vec_tpu.train.prefetch import device_batches
from code2vec_tpu.train.step import create_train_state
from tools.corpus_convert import csr_to_text, text_to_csr

pytestmark = pytest.mark.ooc

BAG = 32

TINY_CFG = dict(
    max_epoch=2,
    batch_size=32,
    encode_size=64,
    terminal_embed_size=32,
    path_embed_size=32,
    max_path_length=BAG,
    print_sample_cycle=0,
)

METRIC_KEYS = ("train_loss", "test_loss", "accuracy", "precision", "recall", "f1")


@pytest.fixture(scope="module")
def corpora(tmp_path_factory):
    """(text paths, csr path, text-loaded data, mmap-loaded data)."""
    out = tmp_path_factory.mktemp("ooc")
    paths = generate_corpus_files(out, SPECS["tiny"])
    csr = str(out / "corpus.csr")
    text_to_csr(paths["corpus"], csr)
    data_text = load_corpus(
        paths["corpus"], paths["path_idx"], paths["terminal_idx"],
        cache=False, native=False,
    )
    data_mmap = load_corpus(csr, paths["path_idx"], paths["terminal_idx"])
    return paths, csr, data_text, data_mmap


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    faultinject.install_plan(None)
    yield
    faultinject.install_plan(None)


def assert_bitwise_history(r1, r2):
    assert len(r1.history) == len(r2.history)
    for h1, h2 in zip(r1.history, r2.history):
        for key in METRIC_KEYS:
            assert h1[key] == h2[key], (h1["epoch"], key, h1[key], h2[key])


# ---------------------------------------------------------------------------
# the container format
# ---------------------------------------------------------------------------


class TestContainer:
    def test_round_trip_byte_identical(self, corpora, tmp_path):
        paths, csr, _, _ = corpora
        back = str(tmp_path / "roundtrip.txt")
        csr_to_text(csr, back)
        with open(paths["corpus"], "rb") as a, open(back, "rb") as b:
            assert a.read() == b.read()

    def test_record_round_trip_edge_cases(self, tmp_path):
        """Records exercising every optional field: missing source/doc/id/
        label, empty context and var sections, unicode, tab-bearing
        aliases."""
        records = [
            CorpusRecord(id=7, label="getFoo", source="A.java",
                         path_contexts=[(1, 2, 3), (4, 5, 6)],
                         aliases=[("counter", "@var_0")]),
            CorpusRecord(id=None, label=None, source=None, doc="döc ünicode",
                         path_contexts=[], aliases=[]),
            CorpusRecord(id=2**40, label="naïve_name", source=None,
                         path_contexts=[(0, 0, 0)],
                         aliases=[("x", "@var_0"), ("y", "@var_1")]),
        ]
        path = str(tmp_path / "edge.csr")
        write_corpus_csr(path, records, terminal_shift=1)
        got = list(open_corpus_csr(path).iter_records())
        assert len(got) == len(records)
        for a, b in zip(records, got):
            assert (a.id, a.label, a.source, a.doc) == (
                b.id, b.label, b.source, b.doc
            )
            assert a.path_contexts == b.path_contexts
            assert a.aliases == b.aliases

    def test_histogram_footer_matches_scan(self, corpora):
        _, csr, data_text, _ = corpora
        lengths, weights = read_csr_histogram(csr)
        ul, uc = np.unique(np.diff(data_text.row_splits), return_counts=True)
        assert (lengths == ul).all() and (weights == uc).all()
        # the footer feeds the SAME ladder derivation a scan would
        assert derive_bucket_ladder_hist(lengths, weights, BAG) == (
            derive_bucket_ladder(np.diff(data_text.row_splits), BAG)
        )

    def test_magic_detection(self, corpora, tmp_path):
        paths, csr, _, _ = corpora
        assert is_csr_corpus(csr)
        assert not is_csr_corpus(paths["corpus"])
        assert not is_csr_corpus(str(tmp_path / "missing.csr"))
        with pytest.raises(ValueError, match="not a CSR"):
            open_corpus_csr(paths["corpus"])

    def test_mmap_views_are_lazy(self, corpora):
        _, csr, _, _ = corpora
        corpus = open_corpus_csr(csr)
        assert isinstance(corpus.starts, np.memmap)
        # gathers come back as plain in-RAM arrays
        got = corpus.starts[np.asarray([0, 5, 3])]
        assert not isinstance(got, np.memmap)


# ---------------------------------------------------------------------------
# the mmap loader
# ---------------------------------------------------------------------------


class TestCsrLoader:
    def test_matches_text_loader(self, corpora):
        _, _, t, m = corpora
        assert m.mmap_backed and m.row_base is None
        assert (np.asarray(m.starts) == t.starts).all()
        assert (np.asarray(m.paths) == t.paths).all()
        assert (np.asarray(m.ends) == t.ends).all()
        assert (m.row_splits == t.row_splits).all()
        assert (m.ids == t.ids).all()
        assert (m.labels == t.labels).all()
        assert m.label_vocab.stoi == t.label_vocab.stoi
        assert m.normalized_labels == t.normalized_labels
        assert m.sources == t.sources
        assert m.aliases == t.aliases
        assert (m.variable_indexes == t.variable_indexes).all()

    def test_sharded_loader_row_base(self, corpora):
        """A sharded mmap load keeps the FULL on-disk arrays and maps local
        items through row_base — epoch builds must equal the text shard
        loader's (which gathers local copies)."""
        paths, csr, _, _ = corpora
        for index in (0, 1):
            t = load_corpus(
                paths["corpus"], paths["path_idx"], paths["terminal_idx"],
                cache=False, native=False, shard=(index, 2),
            )
            m = load_corpus_csr(
                csr, paths["path_idx"], paths["terminal_idx"],
                shard=(index, 2),
            )
            assert m.row_base is not None
            assert (m.row_splits == t.row_splits).all()
            assert (m.ids == t.ids).all()
            from code2vec_tpu.data.pipeline import build_method_epoch

            idx = np.arange(m.n_items)
            et = build_method_epoch(t, idx, BAG, np.random.default_rng(9))
            em = build_method_epoch(m, idx, BAG, np.random.default_rng(9))
            assert (et.starts == em.starts).all()
            assert (et.paths == em.paths).all()
            assert (et.ends == em.ends).all()
            assert (et.labels == em.labels).all()

    def test_variable_items_through_row_base(self, corpora):
        paths, csr, _, _ = corpora
        t = load_corpus(
            paths["corpus"], paths["path_idx"], paths["terminal_idx"],
            cache=False, native=False, shard=(1, 2),
        )
        m = load_corpus_csr(
            csr, paths["path_idx"], paths["terminal_idx"], shard=(1, 2)
        )
        idx = np.arange(m.n_items)
        got_t = [
            (i, tuple(a), s.tolist(), p.tolist(), e.tolist())
            for i, a, _, s, p, e in variable_items(t, idx)
        ]
        got_m = [
            (i, tuple(a), s.tolist(), p.tolist(), e.tolist())
            for i, a, _, s, p, e in variable_items(m, idx)
        ]
        assert got_t == got_m


# ---------------------------------------------------------------------------
# the parity matrix: {fixed-L, bucketed, streaming, mmap} x {sync, prefetch}
# ---------------------------------------------------------------------------


class TestParityMatrix:
    """Every feed variant must compute the SAME per-example forward —
    identical loss multiset, bitwise-equal eval metrics — as the in-RAM
    fixed-L reference. Canonical context order makes rows comparable
    across variants that build them at different stream positions; the
    tiny corpus's counts all fit BAG, so the subsample is the full bag."""

    def _per_example_losses(self, source, state, prefetch):
        @jax.jit
        def nll_of(state, batch):
            logits, _, _ = state.apply_fn(
                {"params": state.params},
                batch["starts"], batch["paths"], batch["ends"],
                deterministic=True,
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(
                logp, batch["labels"][:, None], axis=-1
            )[:, 0], jnp.argmax(logits, axis=-1)

        losses, expected, preds = {}, [], []
        with device_batches(
            source.batches(np.random.default_rng(11)),
            jax.device_put,
            prefetch,
        ) as stream:
            for host_batch, device_batch in stream:
                nll, pred = nll_of(state, device_batch)
                valid = host_batch["example_mask"].astype(bool)
                nll = np.asarray(nll)
                for i in np.flatnonzero(valid):
                    losses[int(host_batch["ids"][i])] = float(nll[i])
                expected.append(host_batch["labels"][valid])
                preds.append(np.asarray(pred)[valid])
        return losses, np.concatenate(expected), np.concatenate(preds)

    def test_matrix_vs_in_ram_fixed_reference(self, corpora):
        _, _, data_text, data_mmap = corpora
        counts = np.diff(data_text.row_splits)
        # a bag holding every real count: the subsample keeps the FULL bag
        # for every method, so rows are comparable across variants that
        # draw at different stream positions
        bag = int(2 ** np.ceil(np.log2(counts.max())))
        assert counts.max() <= bag
        ladder = derive_bucket_ladder(counts, bag)
        assert len(ladder) > 1

        cfg = TrainConfig(**TINY_CFG).with_updates(max_path_length=bag)
        model_config = model_config_from(cfg, data_text)
        idx = np.arange(data_text.n_items)
        src_kw = dict(context_order="corpus")
        reference_source = EpochSource(
            data_text, idx, 32, bag, ladder=None, **src_kw
        )
        batch0 = next(reference_source.batches(np.random.default_rng(0)))
        state = create_train_state(
            cfg, model_config, jax.random.PRNGKey(0), batch0
        )
        reference = self._per_example_losses(reference_source, state, 0)

        arms = {
            "bucketed": EpochSource(
                data_text, idx, 32, bag, ladder=ladder, **src_kw
            ),
            "streaming": StreamingSource(
                data_text, idx, 32, bag, chunk_items=48, ladder=ladder,
                **src_kw,
            ),
            "streaming_fixed": StreamingSource(
                data_text, idx, 32, bag, chunk_items=48, **src_kw
            ),
            "mmap": MmapCorpusSource(
                data_mmap, idx, 32, bag, ladder=ladder, **src_kw
            ),
            "mmap_fixed": MmapCorpusSource(
                data_mmap, idx, 32, bag, **src_kw
            ),
        }
        m_ref = evaluate(
            "subtoken", reference[1], reference[2], data_text.label_vocab
        )
        for name, source in arms.items():
            for prefetch in (0, 2):
                got = self._per_example_losses(source, state, prefetch)
                label = f"{name}/prefetch={prefetch}"
                assert got[0].keys() == reference[0].keys(), label
                for k in reference[0]:
                    assert got[0][k] == reference[0][k], (label, k)
                m_got = evaluate(
                    "subtoken", got[1], got[2], data_text.label_vocab
                )
                assert m_got == m_ref, label


# ---------------------------------------------------------------------------
# end-to-end composition through train()
# ---------------------------------------------------------------------------


class TestComposition:
    def test_mmap_bucketed_streaming_prefetched_one_invocation(self, corpora):
        """The acceptance bar: bucketed + streaming + prefetched + mmap-CSR
        in ONE train() — trains, reports pad_efficiency, and compiles
        exactly the ladder (zero recompile events)."""
        _, _, _, data_mmap = corpora
        seen = []
        from code2vec_tpu.obs.events import EventLog

        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        res = train(
            TrainConfig(**TINY_CFG).with_updates(
                bucketed=True, stream_chunk_items=64, prefetch_batches=2
            ),
            data_mmap,
            events=events,
        )
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert res.best_f1 > 0.0
        assert all(0.0 < h["pad_efficiency"] <= 1.0 for h in res.history)
        assert not [e for e in seen if e["event"] == "recompile"]

    def test_mmap_gather_source_trains(self, corpora):
        """Without streaming, a mmap corpus feeds through the per-bucket
        gather source — no [N, L] epoch tensor exists at any point."""
        _, _, _, data_mmap = corpora
        seen = []
        from code2vec_tpu.obs.events import EventLog

        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        res = train(
            TrainConfig(**TINY_CFG).with_updates(
                bucketed=True, prefetch_batches=2
            ),
            data_mmap,
            events=events,
        )
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert all(0.0 < h["pad_efficiency"] <= 1.0 for h in res.history)
        assert not [e for e in seen if e["event"] == "recompile"]

    def test_text_vs_csr_bitwise(self, corpora):
        """Same flags, same seed, different backing: the streaming source
        is backing-agnostic, so a csr-fed run reproduces the text-fed
        run's history BITWISE (the ooc-smoke parity bar)."""
        _, _, data_text, data_mmap = corpora
        cfg = TrainConfig(**TINY_CFG).with_updates(
            bucketed=True, stream_chunk_items=64, prefetch_batches=2
        )
        assert_bitwise_history(train(cfg, data_text), train(cfg, data_mmap))

    def test_streaming_reports_pad_efficiency(self, corpora):
        """Satellite: --stream_chunk_items used to silently drop the
        honesty metric; now every epoch reports it — as the metric AND the
        health gauge — and it equals the exact corpus geometry."""
        _, _, data_text, _ = corpora
        from code2vec_tpu.data.pipeline import pad_stats
        from code2vec_tpu.obs.events import EventLog

        seen = []
        events = EventLog()
        events.subscribe(lambda e: seen.append(e))
        res = train(
            TrainConfig(**TINY_CFG).with_updates(
                max_epoch=1, stream_chunk_items=64
            ),
            data_text,
            events=events,
        )
        assert all("pad_efficiency" in h for h in res.history)
        train_idx_size = len(res.history)  # history exists
        epochs = [e for e in seen if e["event"] == "epoch"]
        assert epochs and all(
            e["health"]["gauges"]["pad_efficiency"] > 0 for e in epochs
        )
        # exact geometry: the train split is 80% of items; recompute from
        # the corpus like the in-RAM accounting would
        from code2vec_tpu.data.pipeline import split_items

        rng = np.random.default_rng(TINY_CFG.get("random_seed", 123))
        train_idx, _ = split_items(data_text.n_items, rng)
        counts = np.minimum(np.diff(data_text.row_splits)[train_idx], BAG)
        real, slots = pad_stats(counts, (BAG,), 32)
        assert res.history[0]["pad_efficiency"] == pytest.approx(
            real / slots
        )
        assert train_idx_size == 1

    def test_bucketed_shard_staged_device_epoch(self, corpora):
        """Guard 3 deleted: --bucketed composes with --shard_staged_corpus
        — each ladder bucket shards over the data axis and scans at its
        own width."""
        _, _, data_text, _ = corpora
        res = train(
            TrainConfig(**TINY_CFG).with_updates(
                bucketed=True,
                device_epoch=True,
                shard_staged_corpus=True,
                data_axis=2,
            ),
            data_text,
        )
        assert res.epochs_run == 2
        assert all(np.isfinite(h["train_loss"]) for h in res.history)
        assert res.best_f1 > 0.0


# ---------------------------------------------------------------------------
# mid-epoch resume on the previously-unreachable combinations
# ---------------------------------------------------------------------------


class TestResume:
    def _kill_and_resume(self, data, out_dir, kill_cfg, resume_cfg):
        with pytest.raises(faultinject.FaultInjected):
            train(kill_cfg, data, out_dir=out_dir, sinks=())
        return train(resume_cfg, data, out_dir=out_dir, sinks=())

    def test_kill_resume_bitwise_streaming_bucketed(self, corpora, tmp_path):
        """Satellite: mid-epoch kill -> resume, bitwise, on a STREAMING
        BUCKETED run — a combination the old mutual-exclusion guard made
        unreachable. The stream is a pure function of the epoch-start RNG
        state, so skip_batches replays it exactly, per-bucket carry and
        all."""
        _, _, data, _ = corpora
        base = dict(
            TINY_CFG, max_epoch=3, checkpoint_cycle=1,
            bucketed=True, bucket_ladder=f"8,16,{BAG}",
            stream_chunk_items=64,
        )
        r_full = train(
            TrainConfig(**base), data, out_dir=str(tmp_path / "full"),
            sinks=(),
        )
        r_resumed = self._kill_and_resume(
            data, str(tmp_path / "killed"),
            TrainConfig(**base, checkpoint_every_steps=2,
                        fault_plan="train_step@9:raise"),
            TrainConfig(**base, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)

    def test_kill_resume_bitwise_mmap_bucketed(self, corpora, tmp_path):
        """Same bar through the mmap gather source: its batch plan and
        per-batch subsample draws are a pure function of the epoch-start
        RNG too."""
        _, _, _, data_mmap = corpora
        base = dict(
            TINY_CFG, max_epoch=3, checkpoint_cycle=1,
            bucketed=True, bucket_ladder=f"8,16,{BAG}",
        )
        r_full = train(
            TrainConfig(**base), data_mmap, out_dir=str(tmp_path / "full"),
            sinks=(),
        )
        r_resumed = self._kill_and_resume(
            data_mmap, str(tmp_path / "killed"),
            TrainConfig(**base, checkpoint_every_steps=2,
                        fault_plan="train_step@9:raise"),
            TrainConfig(**base, resume=True),
        )
        assert_bitwise_history(r_full, r_resumed)


# ---------------------------------------------------------------------------
# the host-sharded lockstep schedule (single-process unit coverage)
# ---------------------------------------------------------------------------


class TestScheduledBatches:
    def test_schedule_followed_with_masked_empties(self, corpora):
        _, _, data, _ = corpora
        from code2vec_tpu.data.pipeline import build_epoch

        epoch = build_epoch(
            data, np.arange(data.n_items), BAG, np.random.default_rng(0)
        )
        ladder = derive_bucket_ladder(np.diff(data.row_splits), BAG)
        counts = bucket_batch_counts(
            np.minimum(np.diff(data.row_splits), BAG), ladder, 32
        )
        # a schedule with 2 EXTRA steps per width: the local queues run
        # dry and the tail must come out as fully-masked empties
        schedule = np.repeat(np.asarray(ladder), counts + 2)
        rng = np.random.default_rng(3)
        schedule = schedule[rng.permutation(len(schedule))]
        got_widths, n_valid = [], 0
        for batch in iter_scheduled_bucketed_batches(
            epoch, ladder, 32, schedule, rng=np.random.default_rng(4)
        ):
            got_widths.append(batch["paths"].shape[1])
            n_valid += int(batch["example_mask"].sum())
        assert got_widths == [int(w) for w in schedule]
        assert n_valid == len(epoch)  # every example exactly once

    def test_mmap_scheduled_matches(self, corpora):
        _, _, _, data_mmap = corpora
        ladder = derive_bucket_ladder(np.diff(data_mmap.row_splits), BAG)
        idx = np.arange(data_mmap.n_items)
        source = MmapCorpusSource(data_mmap, idx, 32, BAG, ladder=ladder)
        counts = bucket_batch_counts(
            np.minimum(np.diff(data_mmap.row_splits), BAG), ladder, 32
        )
        schedule = np.repeat(np.asarray(ladder), counts + 1)
        seen, got_widths = [], []
        for batch in source.scheduled_batches(
            np.random.default_rng(5), schedule
        ):
            got_widths.append(batch["paths"].shape[1])
            valid = batch["example_mask"].astype(bool)
            seen.extend(batch["ids"][valid].tolist())
        assert got_widths == [int(w) for w in schedule]
        assert sorted(seen) == sorted(data_mmap.ids.tolist())


# ---------------------------------------------------------------------------
# bounded host RSS: feed a corpus bigger than the address-space headroom
# ---------------------------------------------------------------------------


BOUNDED_RSS_SCRIPT = textwrap.dedent("""
    import os, resource, sys
    import numpy as np

    # ALL imports before the budget is measured: module loading grows the
    # address space and would eat the margin
    from code2vec_tpu.data.reader import load_corpus_csr
    from code2vec_tpu.data.pipeline import MmapCorpusSource, derive_bucket_ladder_hist
    from code2vec_tpu.formats.corpus_io import read_csr_histogram

    csr_path, path_idx, terminal_idx = sys.argv[1:4]

    def vm_size():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmSize:"):
                    return int(line.split()[1]) * 1024
        raise RuntimeError("no VmSize")

    corpus_bytes = os.path.getsize(csr_path)
    # budget: the current address space + ONE corpus-sized mapping (the
    # mmap itself) + a margin far smaller than a second copy. In-RAM
    # materialization needs corpus-size ADDITIONAL allocations and must
    # die; mmap feeding must fit.
    margin = 48 << 20
    budget = vm_size() + corpus_bytes + margin
    resource.setrlimit(resource.RLIMIT_AS, (budget, budget))

    data = load_corpus_csr(csr_path, path_idx, terminal_idx)
    assert data.mmap_backed
    # ladder from the loaded row_splits: read_csr_histogram would map the
    # container a SECOND time — free address space normally, but this
    # budget counts every mapping
    lengths, weights = np.unique(np.diff(data.row_splits), return_counts=True)
    ladder = derive_bucket_ladder_hist(lengths, weights, 200)
    source = MmapCorpusSource(
        data, np.arange(data.n_items), 64, 200, ladder=ladder
    )
    n = 0
    for batch in source.batches(np.random.default_rng(0)):
        n += 1
        if n >= 40:
            break
    assert n == 40, n

    # negative control: materializing the context arrays (what an in-RAM
    # load would do) must blow the same budget
    try:
        hoard = [np.array(data.starts), np.array(data.paths), np.array(data.ends)]
        print("CONTROL-SURVIVED", len(hoard))
        sys.exit(3)
    except MemoryError:
        pass
    print("BOUNDED-OK", n)
""")


@pytest.mark.skipif(sys.platform != "linux", reason="rlimit/VmSize probe")
def test_mmap_feed_bounded_by_rlimit(tmp_path, corpora):
    """THE out-of-core guarantee, enforced with an address-space budget:
    a corpus whose in-RAM copy cannot fit the rlimit feeds fine through
    the mmap gather source (jax-free subprocess: the data layer imports
    no backend, so the budget measures the feed, not XLA)."""
    paths, _, _, _ = corpora
    rng = np.random.default_rng(0)
    big = str(tmp_path / "big.csr")
    n_methods, ctx_per = 6000, 900  # ~65 MB of context sections
    records = (
        CorpusRecord(
            id=i,
            label=f"m{i}",
            path_contexts=rng.integers(
                1, 1000, size=(ctx_per, 3), dtype=np.int64
            ).tolist(),
            aliases=[],
        )
        for i in range(n_methods)
    )
    write_corpus_csr(big, records, terminal_shift=1)
    assert os.path.getsize(big) > 60 << 20

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", BOUNDED_RSS_SCRIPT, big,
         paths["path_idx"], paths["terminal_idx"]],
        capture_output=True, text=True, timeout=300,
        cwd=repo_root,
        # minimal env: inherited vars (threadpool sizing, preloads,
        # allocator tuning) change the interpreter's address-space
        # baseline between the vm_size() probe and the mmap
        env={
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": repo_root,
            "OMP_NUM_THREADS": "1",
            "OPENBLAS_NUM_THREADS": "1",
        },
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "BOUNDED-OK" in proc.stdout


def test_rss_stays_below_corpus_size_during_mmap_epoch(tmp_path, corpora):
    """The obs-memory-sampler form of the acceptance criterion: streaming
    an epoch of batches from a mmap corpus grows host RSS by (much) less
    than the corpus size, where an in-RAM load of the same container
    grows it by at least the context sections."""
    paths, _, _, _ = corpora
    from code2vec_tpu.obs.runtime import host_rss_bytes

    rng = np.random.default_rng(1)
    big = str(tmp_path / "sampler.csr")
    n_methods, ctx_per = 4000, 900
    write_corpus_csr(
        big,
        (
            CorpusRecord(
                id=i, label=f"m{i}",
                path_contexts=rng.integers(
                    1, 1000, size=(ctx_per, 3), dtype=np.int64
                ).tolist(),
                aliases=[],
            )
            for i in range(n_methods)
        ),
        terminal_shift=1,
    )
    corpus_bytes = os.path.getsize(big)
    data = load_corpus_csr(big, paths["path_idx"], paths["terminal_idx"])
    source = MmapCorpusSource(
        data, np.arange(data.n_items), 64, 200, ladder=(50, 200)
    )
    # warm one pass so allocator pools exist, then measure a full epoch
    for i, _ in enumerate(source.batches(np.random.default_rng(2))):
        if i > 4:
            break
    rss_before = host_rss_bytes()
    for _ in source.batches(np.random.default_rng(3)):
        pass
    grown = host_rss_bytes() - rss_before
    # mmap page cache can keep touched pages resident; the bound that
    # matters is "well below the corpus" (an in-RAM load adds >= the
    # ~41 MB context sections immediately)
    assert grown < corpus_bytes // 2, (grown, corpus_bytes)


# ---------------------------------------------------------------------------
# CLI: --corpus_format + the ooc-smoke path
# ---------------------------------------------------------------------------


class TestCli:
    def test_corpus_format_mismatch_fails_loudly(self, corpora, tmp_path):
        paths, csr, _, _ = corpora
        from code2vec_tpu.cli import main

        with pytest.raises(SystemExit, match="corpus_format"):
            main([
                "--corpus_path", paths["corpus"],
                "--path_idx_path", paths["path_idx"],
                "--terminal_idx_path", paths["terminal_idx"],
                "--corpus_format", "csr",
                "--model_path", str(tmp_path / "out"),
                "--max_epoch", "1",
            ])

    def test_cli_trains_from_csr(self, corpora, tmp_path):
        """The ooc-smoke: CLI end to end from a converted container,
        bucketed + prefetched, zero recompile events in the log."""
        paths, csr, _, _ = corpora
        from code2vec_tpu.cli import main

        events_dir = tmp_path / "events"
        main([
            "--corpus_path", csr,
            "--path_idx_path", paths["path_idx"],
            "--terminal_idx_path", paths["terminal_idx"],
            "--corpus_format", "csr",
            "--bucketed",
            "--prefetch_batches", "2",
            "--batch_size", "32",
            "--max_path_length", str(BAG),
            "--encode_size", "64",
            "--terminal_embed_size", "32",
            "--path_embed_size", "32",
            "--max_epoch", "1",
            "--print_sample_cycle", "0",
            "--model_path", str(tmp_path / "out"),
            "--vectors_path", str(tmp_path / "out" / "code.vec"),
            "--events_dir", str(events_dir),
        ])
        log_files = list(events_dir.glob("*.jsonl"))
        assert log_files
        events = [
            json.loads(line)
            for line in log_files[0].read_text().splitlines()
        ]
        assert any(e.get("event") == "epoch" for e in events)
        assert not [e for e in events if e.get("event") == "recompile"]
