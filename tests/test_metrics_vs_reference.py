"""Differential test: our eval metrics vs the REFERENCE's own evaluators.

`metrics.py` re-implements main.py:300-359 (exact / subtoken /
ave_subtoken); a drift in any of them would shift every reported quality
number. These tests import the reference's actual functions from its
main.py (argv patched to defaults — the module parses flags at import)
and compare all four returned numbers on randomized label vocabularies
and prediction vectors.

The reference's `subtoken_match` calls ``.item()`` on elements produced
by ``.tolist()`` — an upstream crash on plain arrays (python ints have no
``.item()``). The oracle is driven through a thin sequence wrapper whose
``tolist()`` yields numpy scalars, which exercises the reference code
unmodified.
"""

import sys

import numpy as np
import pytest

from conftest import import_reference

_argv = sys.argv
sys.argv = ["main.py"]
try:
    _ref_main = import_reference("main")
finally:
    sys.argv = _argv

from code2vec_tpu import metrics  # noqa: E402
from code2vec_tpu.data.vocab import Vocab  # noqa: E402
from code2vec_tpu.text import normalize_and_subtokenize  # noqa: E402

_NAMES = [
    "getValue", "toString", "HTMLParser", "parseHTTPResponse", "a",
    "setUserName", "indexOf", "X", "snake_case_name", "computeMax2",
]


class _NumpyScalarList(list):
    """tolist() -> numpy scalars, so the reference's ``x.item()`` works."""

    def tolist(self):
        return [np.int64(x) for x in self]


def _vocabs():
    ours = Vocab()
    theirs = _ref_main.Vocab()
    for name in _NAMES:
        ours.add_label(name)
        normalized, subtokens = normalize_and_subtokenize(name)
        theirs.append(normalized, subtokens=list(subtokens))
    assert ours.itos == theirs.itos
    return ours, theirs


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_subtoken_match_matches_reference(seed):
    ours_vocab, theirs_vocab = _vocabs()
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 60))
    expected = rng.integers(0, len(_NAMES), n)
    actual = rng.integers(0, len(_NAMES), n)

    ours = metrics.subtoken_match(expected, actual, ours_vocab)
    theirs = _ref_main.subtoken_match(
        _NumpyScalarList(expected), _NumpyScalarList(actual), theirs_vocab
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-12)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_averaged_subtoken_match_matches_reference(seed):
    ours_vocab, theirs_vocab = _vocabs()
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 60))
    expected = rng.integers(0, len(_NAMES), n)
    actual = rng.integers(0, len(_NAMES), n)

    ours = metrics.averaged_subtoken_match(expected, actual, ours_vocab)
    theirs = _ref_main.averaged_subtoken_match(
        _NumpyScalarList(expected), _NumpyScalarList(actual), theirs_vocab
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-12)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_exact_match_matches_reference(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 60))
    expected = rng.integers(0, len(_NAMES), n)
    actual = np.where(
        rng.random(n) < 0.5, expected, rng.integers(0, len(_NAMES), n)
    )

    ours = metrics.exact_match(expected, actual)
    theirs = _ref_main.exact_match(expected, actual)
    np.testing.assert_allclose(ours, theirs, rtol=1e-12)
