"""Touched-rows (lazy) table optimizer: torch.optim.SparseAdam parity,
per-slot-grad (zero-offset) equivalence with the dense table gradient, and
the lazy step through the scanned-chunk and mesh paths.

The dense twin's oracle is the reference's torch.optim.Adam over the
nn.Embedding tables (reference main.py:138, model/model.py:21-22); the
lazy mode's oracle is torch.optim.SparseAdam — torch's own answer to the
same full-table-RMW problem — which coalesces duplicate ids and updates
only the touched rows (train/table_opt.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.train.config import TrainConfig
from code2vec_tpu.train.step import (
    build_train_step_fn,
    create_train_state,
    make_train_step,
    weighted_nll,
)
from code2vec_tpu.train.table_opt import (
    SparseTableGrad,
    _dedupe_sorted,
    mixed_table_adam,
)


def _toy_batch(rng, B=8, L=12, V_t=50, V_p=40, C=7):
    return {
        "starts": jnp.asarray(rng.integers(1, V_t, (B, L)), jnp.int32),
        "paths": jnp.asarray(rng.integers(1, V_p, (B, L)), jnp.int32),
        "ends": jnp.asarray(rng.integers(1, V_t, (B, L)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, C, (B,)), jnp.int32),
        "example_mask": jnp.ones((B,), jnp.float32),
    }


def _toy_config(V_t=50, V_p=40, C=7, **kw):
    return Code2VecConfig(
        terminal_count=V_t, path_count=V_p, label_count=C,
        terminal_embed_size=6, path_embed_size=5, encode_size=10, **kw
    )


class TestDedupe:
    def test_coalesces_duplicates_and_pads_with_sentinel(self):
        ids = jnp.asarray([3, 1, 3, 7, 1, 3], jnp.int32)
        slots = jnp.asarray(
            [[1.0], [10.0], [2.0], [100.0], [20.0], [4.0]], jnp.float32
        )
        uids, gsum = _dedupe_sorted(ids, slots, vocab=9)
        uids, gsum = np.asarray(uids), np.asarray(gsum)
        assert sorted(uids[:3].tolist()) == [1, 3, 7]
        assert (uids[3:] == 9).all()  # capacity padding -> sentinel
        by_id = {int(u): float(g) for u, g in zip(uids[:3], gsum[:3, 0])}
        assert by_id == {1: 30.0, 3: 7.0, 7: 100.0}
        assert (gsum[3:] == 0.0).all()

    def test_all_distinct_and_all_same(self):
        ids = jnp.asarray([4, 2, 8], jnp.int32)
        slots = jnp.ones((3, 2), jnp.float32)
        uids, gsum = _dedupe_sorted(ids, slots, vocab=10)
        assert sorted(np.asarray(uids).tolist()) == [2, 4, 8]
        assert np.asarray(gsum).sum() == 6.0
        ids = jnp.asarray([5, 5, 5], jnp.int32)
        uids, gsum = _dedupe_sorted(ids, slots, vocab=10)
        assert np.asarray(uids)[0] == 5 and (np.asarray(uids)[1:] == 10).all()
        assert (np.asarray(gsum)[0] == 3.0).all()


class TestSparseAdamParity:
    """The lazy table update IS torch.optim.SparseAdam: same coalescing,
    same global-step bias correction, same eps placement."""

    @pytest.mark.parametrize("mu_dtype", ["float32", "bfloat16"])
    def test_matches_torch_sparse_adam(self, mu_dtype):
        torch = pytest.importorskip("torch")

        rng = np.random.default_rng(7)
        vocab, dim, n_slots, steps = 23, 4, 40, 5
        init = rng.standard_normal((vocab, dim)).astype(np.float32)
        lr, b1, b2 = 0.01, 0.9, 0.999

        # --- ours: the table subtree of the mixed transform
        params = {"terminal_embedding": {"embedding": jnp.asarray(init)},
                  "path_embedding": {"embedding": jnp.zeros((5, dim))},
                  "other": jnp.zeros((3,))}
        tx = mixed_table_adam(lr, b1, b2, 0.0, mu_dtype=mu_dtype)
        opt_state = tx.init(params)

        # --- torch: SparseAdam over the same tensor
        t_param = torch.tensor(init, requires_grad=True)
        t_opt = torch.optim.SparseAdam([t_param], lr=lr, betas=(b1, b2))

        from code2vec_tpu.train.table_opt import apply_updates_sparse

        for step in range(steps):
            ids = rng.integers(0, vocab, n_slots).astype(np.int32)
            slots = rng.standard_normal((n_slots, dim)).astype(np.float32)

            grads = {
                "terminal_embedding": {"embedding": SparseTableGrad(
                    ids=jnp.asarray(ids), slots=jnp.asarray(slots))},
                "path_embedding": {"embedding": SparseTableGrad(
                    ids=jnp.zeros(4, jnp.int32),
                    slots=jnp.zeros((4, dim), jnp.float32))},
                "other": jnp.zeros((3,)),
            }
            updates, opt_state = tx.update(grads, opt_state, params)
            params = apply_updates_sparse(params, updates)

            t_grad = torch.sparse_coo_tensor(
                torch.tensor(ids[None, :].astype(np.int64)),
                torch.tensor(slots), (vocab, dim)
            )
            t_opt.zero_grad()
            t_param.grad = t_grad
            t_opt.step()

            ours = np.asarray(params["terminal_embedding"]["embedding"])
            theirs = t_param.detach().numpy()
            tol = 2e-3 if mu_dtype == "bfloat16" else 1e-6
            np.testing.assert_allclose(ours, theirs, atol=tol, rtol=tol,
                                       err_msg=f"step {step}")

    def test_untouched_rows_frozen(self):
        rng = np.random.default_rng(3)
        vocab, dim = 11, 3
        init = rng.standard_normal((vocab, dim)).astype(np.float32)
        params = {"terminal_embedding": {"embedding": jnp.asarray(init)},
                  "path_embedding": {"embedding": jnp.asarray(init[:5])}}
        tx = mixed_table_adam(0.01, 0.9, 0.999, 0.0)
        opt_state = tx.init(params)
        touched = np.array([2, 5, 2], np.int32)
        grads = {
            "terminal_embedding": {"embedding": SparseTableGrad(
                ids=jnp.asarray(touched),
                slots=jnp.ones((3, dim), jnp.float32))},
            "path_embedding": {"embedding": SparseTableGrad(
                ids=jnp.asarray([0], jnp.int32),
                slots=jnp.zeros((1, dim), jnp.float32))},
        }
        from code2vec_tpu.train.table_opt import apply_updates_sparse

        updates, opt_state = tx.update(grads, opt_state, params)
        params = apply_updates_sparse(params, updates)
        new = np.asarray(params["terminal_embedding"]["embedding"])
        untouched = [i for i in range(vocab) if i not in (2, 5)]
        np.testing.assert_array_equal(new[untouched], init[untouched])
        assert not np.allclose(new[[2, 5]], init[[2, 5]])
        # mu/nu of untouched rows also frozen (SparseAdam semantics)
        mu = np.asarray(opt_state.lazy.mu["terminal_embedding"]["embedding"])
        assert (mu[untouched] == 0.0).all()
        assert not np.allclose(mu[[2, 5]], 0.0)


class TestOffsetGradEquivalence:
    """The zero-offset per-slot grads, scatter-added, equal the dense
    table gradients — the lazy step sees the same gradient signal, just
    never materialized as [vocab, dim]."""

    @pytest.mark.parametrize("encoder_impl", ["concat", "split"])
    def test_slot_grads_match_dense_table_grads(self, encoder_impl):
        rng = np.random.default_rng(11)
        mc = _toy_config(encoder_impl=encoder_impl)
        batch = _toy_batch(rng)
        model = Code2Vec(mc)
        params = model.init(
            {"params": jax.random.PRNGKey(0)},
            batch["starts"], batch["paths"], batch["ends"],
            labels=batch["labels"], deterministic=True,
        )["params"]
        cw = jnp.ones((mc.label_count,), jnp.float32)

        def dense_loss(params):
            logits, _, _ = model.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"], deterministic=True,
            )
            return weighted_nll(logits, batch["labels"], cw,
                                batch["example_mask"])

        dense_grads = jax.grad(dense_loss)(params)

        def offset_loss(offsets):
            logits, _, _ = model.apply(
                {"params": params}, batch["starts"], batch["paths"],
                batch["ends"], deterministic=True, embed_offsets=offsets,
            )
            return weighted_nll(logits, batch["labels"], cw,
                                batch["example_mask"])

        B, L = batch["starts"].shape
        off = (jnp.zeros((B, 2 * L, mc.terminal_embed_size)),
               jnp.zeros((B, L, mc.path_embed_size)))
        g_se, g_p = jax.grad(offset_loss)(off)

        term_ids = np.concatenate(
            [np.asarray(batch["starts"]), np.asarray(batch["ends"])], axis=1
        ).reshape(-1)
        scat_t = np.zeros((mc.terminal_count, mc.terminal_embed_size),
                          np.float32)
        np.add.at(scat_t, term_ids,
                  np.asarray(g_se).reshape(-1, mc.terminal_embed_size))
        np.testing.assert_allclose(
            scat_t,
            np.asarray(dense_grads["terminal_embedding"]["embedding"]),
            atol=1e-5, rtol=1e-5,
        )
        scat_p = np.zeros((mc.path_count, mc.path_embed_size), np.float32)
        np.add.at(scat_p, np.asarray(batch["paths"]).reshape(-1),
                  np.asarray(g_p).reshape(-1, mc.path_embed_size))
        np.testing.assert_allclose(
            scat_p, np.asarray(dense_grads["path_embedding"]["embedding"]),
            atol=1e-5, rtol=1e-5,
        )

    def test_offsets_leave_forward_bit_identical(self):
        rng = np.random.default_rng(5)
        mc = _toy_config()
        batch = _toy_batch(rng)
        model = Code2Vec(mc)
        params = model.init(
            {"params": jax.random.PRNGKey(0)},
            batch["starts"], batch["paths"], batch["ends"],
            deterministic=True,
        )["params"]
        B, L = batch["starts"].shape
        out_plain = model.apply(
            {"params": params}, batch["starts"], batch["paths"],
            batch["ends"], deterministic=True,
        )
        out_off = model.apply(
            {"params": params}, batch["starts"], batch["paths"],
            batch["ends"], deterministic=True,
            embed_offsets=(jnp.zeros((B, 2 * L, mc.terminal_embed_size)),
                           jnp.zeros((B, L, mc.path_embed_size))),
        )
        for a, b in zip(out_plain, out_off):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestLazyTrainStep:
    def test_tracks_dense_closely_and_nontable_params_match(self):
        """Same init, same batches: the non-table params see identical
        grads (so only eps-placement dust separates them), and the loss
        trajectories stay within lazy-vs-dense semantic drift."""
        rng = np.random.default_rng(0)
        mc = _toy_config()
        cw = jnp.ones((mc.label_count,), jnp.float32)
        batch = _toy_batch(rng)

        states, losses = {}, {}
        for mode in ("dense", "lazy"):
            tc = TrainConfig(batch_size=8, table_update=mode)
            state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
            step = make_train_step(mc, cw, table_update=mode)
            ls = []
            for _ in range(4):
                state, loss = step(state, batch)
                ls.append(float(loss))
            states[mode], losses[mode] = state, ls
        assert losses["dense"][0] == pytest.approx(losses["lazy"][0], abs=1e-6)
        np.testing.assert_allclose(losses["dense"], losses["lazy"], atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(states["dense"].params["input_dense"]["kernel"]),
            np.asarray(states["lazy"].params["input_dense"]["kernel"]),
            atol=1e-4,
        )

    def test_weight_decay_applies_dense_side_only(self):
        rng = np.random.default_rng(2)
        mc = _toy_config()
        cw = jnp.ones((mc.label_count,), jnp.float32)
        batch = _toy_batch(rng)
        tc = TrainConfig(batch_size=8, table_update="lazy", weight_decay=0.1)
        state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
        step = make_train_step(mc, cw, table_update="lazy")
        state, loss = step(state, batch)
        assert np.isfinite(float(loss))

    def test_unknown_mode_raises(self):
        mc = _toy_config()
        cw = jnp.ones((mc.label_count,), jnp.float32)
        with pytest.raises(ValueError, match="table_update"):
            build_train_step_fn(mc, cw, table_update="sparse")


class TestLazyChunkAndMesh:
    def test_epoch_runner_scanned_chunk(self):
        """The lazy step composes with the scanned-chunk device-epoch path
        (the flagship path bench.py measures)."""
        from code2vec_tpu.data.synth import (
            SynthSpec, corpus_data_from_raw, generate_corpus_data,
        )
        from code2vec_tpu.train.device_epoch import (
            EpochRunner, stage_method_corpus,
        )

        spec = SynthSpec(n_methods=64, n_terminals=60, n_paths=50,
                         n_labels=9, mean_contexts=6.0, max_contexts=16,
                         seed=0)
        data = corpus_data_from_raw(generate_corpus_data(spec))
        B, L, chunk = 16, 8, 2
        mc = Code2VecConfig(
            terminal_count=spec.n_terminals + 2,
            path_count=spec.n_paths + 1,
            label_count=len(data.label_vocab),
            terminal_embed_size=6, path_embed_size=5, encode_size=10,
        )
        cw = jnp.ones((mc.label_count,), jnp.float32)
        rng = np.random.default_rng(0)
        from code2vec_tpu.data.pipeline import build_method_epoch, iter_batches

        epoch = build_method_epoch(data, np.arange(B), L, rng)
        example = next(iter_batches(epoch, B, rng=rng, pad_final=False))
        tc = TrainConfig(batch_size=B, max_path_length=L,
                         table_update="lazy")
        state = create_train_state(tc, mc, jax.random.PRNGKey(0), example)
        runner = EpochRunner(mc, cw, B, L, chunk, table_update="lazy")
        staged = stage_method_corpus(data, np.arange(data.n_items), rng)
        run = runner._train_chunk(chunk)
        rows = rng.integers(0, data.n_items, chunk * B).astype(np.int32)
        state, loss = run(state, staged.contexts, staged.row_splits,
                          staged.labels, rows, chunk * B,
                          jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert int(state.step) == chunk

    def test_mesh_compiles_and_runs(self):
        """Lazy step over a data x model mesh: GSPMD partitions the sort/
        segment/gather/scatter chain (collectives unoptimized for sharded
        tables, but correct — the single-chip path is the perf target)."""
        from code2vec_tpu.parallel.mesh import make_mesh
        from code2vec_tpu.parallel.shardings import shard_state
        from code2vec_tpu.parallel.step import make_parallel_train_step

        rng = np.random.default_rng(1)
        mc = _toy_config(V_t=48, V_p=40, vocab_pad_multiple=2)
        cw = jnp.ones((mc.label_count,), jnp.float32)
        batch = _toy_batch(rng, V_t=48, V_p=40)
        batch["ids"] = jnp.arange(8, dtype=jnp.int64)  # batch_shardings key
        tc = TrainConfig(batch_size=8, table_update="lazy")
        state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
        mesh = make_mesh(data=2, model=2, ctx=1)
        state = shard_state(mesh, state)
        step = make_parallel_train_step(mc, cw, mesh, state,
                                        table_update="lazy")
        state, loss = step(state, batch)
        assert np.isfinite(float(loss))


class TestLazyCheckpoint:
    def test_roundtrip_and_mode_mismatch_guidance(self, tmp_path):
        from code2vec_tpu.checkpoint import (
            TrainMeta, restore_checkpoint, save_checkpoint,
        )

        rng = np.random.default_rng(4)
        mc = _toy_config()
        cw = jnp.ones((mc.label_count,), jnp.float32)
        batch = _toy_batch(rng)
        tc = TrainConfig(batch_size=8, table_update="lazy")
        state = create_train_state(tc, mc, jax.random.PRNGKey(0), batch)
        step = make_train_step(mc, cw, table_update="lazy")
        state, _ = step(state, batch)
        out = str(tmp_path / "ckpt")
        save_checkpoint(out, state, TrainMeta())

        template = create_train_state(tc, mc, jax.random.PRNGKey(9), batch)
        restored, meta = restore_checkpoint(out, template)
        assert meta.table_update == "lazy"
        np.testing.assert_array_equal(
            np.asarray(restored.params["terminal_embedding"]["embedding"]),
            np.asarray(state.params["terminal_embedding"]["embedding"]),
        )
        mu = restored.opt_state.lazy.mu["terminal_embedding"]["embedding"]
        np.testing.assert_array_equal(
            np.asarray(mu),
            np.asarray(state.opt_state.lazy.mu["terminal_embedding"]["embedding"]),
        )

        dense_template = create_train_state(
            TrainConfig(batch_size=8), mc, jax.random.PRNGKey(9), batch
        )
        with pytest.raises(ValueError, match="--table_update lazy"):
            restore_checkpoint(out, dense_template)
