"""Python-language extractor (code2vec_tpu/pyextract.py) — the
multi-language leg of BASELINE config 5. Conventions must match the C++
Java extractor so both legs intern into one vocab space."""

import subprocess
import sys

import numpy as np
import pytest

from code2vec_tpu.pyextract import (
    DOWN,
    UP,
    PyExtractConfig,
    extract_python_dataset,
    extract_python_source,
)


def contexts_of(src, name):
    methods = extract_python_source(src)
    for m in methods:
        if m.label == name:
            return m
    raise AssertionError(f"{name} not extracted; got {[m.label for m in methods]}")


class TestAnonymization:
    def test_params_become_var_aliases(self):
        m = contexts_of("def add(a, b):\n    return a + b\n", "add")
        assert ("a", "@var_0") in m.variables
        assert ("b", "@var_1") in m.variables
        terms = {t for s, _, e in m.contexts for t in (s, e)}
        assert "@var_0" in terms and "@var_1" in terms
        assert "a" not in terms and "b" not in terms

    def test_own_name_becomes_method_alias(self):
        m = contexts_of("def fib(n):\n    return fib(n - 1) + n\n", "fib")
        assert ("fib", "@method_0") in m.methods
        terms = {t for s, _, e in m.contexts for t in (s, e)}
        assert "@method_0" in terms
        assert "fib" not in terms  # the label must never leak as a terminal

    def test_locals_bind_at_first_store(self):
        src = (
            "def f(xs):\n"
            "    total = 0\n"
            "    for x in xs:\n"
            "        total += x\n"
            "    return total\n"
        )
        m = contexts_of(src, "f")
        originals = [o for o, _ in m.variables]
        assert originals == ["xs", "total", "x"]

    def test_unbound_names_keep_text(self):
        m = contexts_of("def f(x):\n    return len(x) + GLOBAL\n", "f")
        terms = {t for s, _, e in m.contexts for t in (s, e)}
        assert "len" in terms  # builtins/globals pass through, like Java
        assert "GLOBAL" in terms  # case-preserved here; interning lowercases


class TestLiterals:
    def test_string_and_float_normalized_int_kept(self):
        src = 'def f():\n    a = "hi"\n    b = 2.5\n    c = 7\n    return a\n'
        m = contexts_of(src, "f")
        terms = {t for s, _, e in m.contexts for t in (s, e)}
        assert "@string_literal" in terms
        assert "@double_literal" in terms
        assert "7" in terms  # normalize_int_literal=False default (parity)

    def test_raw_multiline_strings_cannot_corrupt_vocab(self, tmp_path):
        """--no-normalize-string + a triple-quoted literal: the newline/tab
        must be escaped in terminal_idxs.txt or load_corpus breaks."""
        from code2vec_tpu.data.reader import load_corpus

        (tmp_path / "src").mkdir()
        (tmp_path / "src" / "m.py").write_text(
            'def banner(n):\n    text = """a\nb\tc"""\n    return text * n\n'
        )
        (tmp_path / "dataset").mkdir()
        rows = [("src/m.py", "*")]
        extract_python_dataset(
            str(tmp_path / "dataset"), str(tmp_path), rows,
            config=PyExtractConfig(normalize_string_literal=False),
        )
        data = load_corpus(
            tmp_path / "dataset" / "corpus.txt",
            tmp_path / "dataset" / "path_idxs.txt",
            tmp_path / "dataset" / "terminal_idxs.txt",
            cache=False,
        )
        assert data.n_items == 1
        assert any("\\n" in name for name in data.terminal_vocab.stoi)

    def test_int_normalization_opt_in(self):
        src = "def f():\n    c = 7\n    return c\n"
        methods = extract_python_source(
            src, config=PyExtractConfig(normalize_int_literal=True)
        )
        terms = {t for s, _, e in methods[0].contexts for t in (s, e)}
        assert "@int_literal" in terms


class TestPaths:
    def test_path_format_uses_reference_arrows(self):
        m = contexts_of("def f(a):\n    return a\n", "f")
        assert all(UP in p or DOWN in p for _, p, _ in m.contexts)
        # hinge form: ups before the single hinge, then downs
        for _, p, _ in m.contexts:
            assert p.index(DOWN) > -1
            up_part = p.split(DOWN)[0]
            assert UP in up_part or up_part  # terminal-side names first

    def test_length_cap_prunes(self):
        src = "def f(a):\n    return ((((a + 1) + 2) + 3) + 4)\n"
        wide = extract_python_source(src, config=PyExtractConfig(max_length=20))
        tight = extract_python_source(src, config=PyExtractConfig(max_length=4))
        assert len(wide[0].contexts) > len(tight[0].contexts) > 0
        for _, p, _ in tight[0].contexts:
            assert p.count(UP) + p.count(DOWN) + 1 <= 4 + 1

    def test_operator_suffixed_nodes(self):
        m = contexts_of("def f(a, b):\n    return a * b\n", "f")
        assert any("BinOp:*" in p for _, p, _ in m.contexts)
        m = contexts_of("def f(a, b):\n    return a < b\n", "f")
        assert any("Compare:<" in p for _, p, _ in m.contexts)


class TestMethodFilter:
    def test_dunders_and_trivial_accessors_skipped(self):
        src = (
            "class A:\n"
            "    def __init__(self):\n"
            "        self.x = 1\n"
            "    def __repr__(self):\n"
            "        return str(self.x)\n"
            "    def get_x(self):\n"
            "        return self.x\n"
            "    def set_x(self, v):\n"
            "        self.x = v\n"
            "    def busy(self, v):\n"
            "        w = v * 2\n"
            "        return w + 1\n"
        )
        labels = [m.label for m in extract_python_source(src)]
        assert labels == ["busy"]

    def test_docstring_only_skipped(self):
        src = 'def doc_only():\n    """just a doc"""\n'
        assert extract_python_source(src) == []

    def test_nested_defs_extracted_separately(self):
        src = (
            "def outer(a):\n"
            "    def inner(b):\n"
            "        return b * 2\n"
            "    return inner(a) + a\n"
        )
        labels = sorted(m.label for m in extract_python_source(src))
        assert labels == ["inner", "outer"]


class TestMergedDataset:
    def _write_sources(self, root):
        (root / "src").mkdir()
        (root / "src" / "MathOps.java").write_text(
            "public class MathOps {\n"
            "    public static int add(int a, int b) { return a + b; }\n"
            "}\n"
        )
        (root / "src" / "math_ops.py").write_text(
            "def add(a, b):\n    return a + b\n\n"
            "def scale(v, k):\n    return v * k\n"
        )
        (root / "dataset").mkdir()

    def test_mixed_cli_merges_vocab_and_loads(self, tmp_path):
        from code2vec_tpu.data.reader import load_corpus

        self._write_sources(tmp_path)
        (tmp_path / "dataset" / "methods.txt").write_text(
            "src/MathOps.java\t*\nsrc/math_ops.py\t*\n"
        )
        result = subprocess.run(
            [sys.executable, "-m", "code2vec_tpu.extractor",
             str(tmp_path / "dataset"), str(tmp_path)],
            capture_output=True, text=True, check=True,
        )
        assert "1 java" in result.stderr and "python" in result.stderr

        data = load_corpus(
            tmp_path / "dataset" / "corpus.txt",
            tmp_path / "dataset" / "path_idxs.txt",
            tmp_path / "dataset" / "terminal_idxs.txt",
            cache=False,
        )
        assert data.n_items == 3  # java add + python add + python scale
        np.testing.assert_array_equal(data.ids, [0, 1, 2])
        # both languages' add anonymize to the same terminals -> both rows
        # reference the SAME @var vocab entries (the merged-vocab property)
        assert data.labels[0] == data.labels[1]  # same label "add"
        java_terms = set(data.starts[: data.row_splits[1]])
        py_terms = set(
            data.starts[data.row_splits[1] : data.row_splits[2]]
        )
        assert java_terms & py_terms  # shared vocab ids across languages

    def test_missing_file_warns_and_continues(self, tmp_path):
        """One bad row must not abort mid-write and orphan vocab ids (the
        C++ leg's warn-and-continue policy)."""
        self._write_sources(tmp_path)
        rows = [("src/gone.py", "*"), ("src/math_ops.py", "*")]
        n, vocabs = extract_python_dataset(
            str(tmp_path / "dataset"), str(tmp_path), rows
        )
        assert n == 2  # both math_ops methods extracted
        assert (tmp_path / "dataset" / "terminal_idxs.txt").exists()

    def test_normalization_flags_reach_python_leg(self, tmp_path):
        """--normalize-int must apply to BOTH legs or the merged vocab
        interns literals inconsistently."""
        self._write_sources(tmp_path)
        (tmp_path / "src" / "nums.py").write_text(
            "def pick(a):\n    return a + 42\n"
        )
        (tmp_path / "dataset" / "methods.txt").write_text(
            "src/nums.py\t*\n"
        )
        subprocess.run(
            [sys.executable, "-m", "code2vec_tpu.extractor",
             str(tmp_path / "dataset"), str(tmp_path), "--normalize-int"],
            capture_output=True, text=True, check=True,
        )
        terms = (tmp_path / "dataset" / "terminal_idxs.txt").read_text()
        assert "@int_literal" in terms and "\t42\n" not in terms
        params = (tmp_path / "dataset" / "params.txt").read_text()
        assert "nomalize_int_literal:true" in params

    def test_method_declarations_cover_python_leg(self, tmp_path):
        self._write_sources(tmp_path)
        (tmp_path / "dataset" / "methods.txt").write_text(
            "src/MathOps.java\t*\nsrc/math_ops.py\t*\n"
        )
        subprocess.run(
            [sys.executable, "-m", "code2vec_tpu.extractor",
             str(tmp_path / "dataset"), str(tmp_path),
             "--method-declarations", "decls.txt"],
            capture_output=True, text=True, check=True,
        )
        decls = (tmp_path / "dataset" / "decls.txt").read_text()
        assert "src/MathOps.java#add" in decls
        assert "src/math_ops.py#scale" in decls  # python methods included

    def test_python_only_dataset(self, tmp_path):
        self._write_sources(tmp_path)
        rows = [("src/math_ops.py", "*")]
        n, vocabs = extract_python_dataset(
            str(tmp_path / "dataset"), str(tmp_path), rows
        )
        assert n == 2
        assert (tmp_path / "dataset" / "params.txt").exists()
        corpus = (tmp_path / "dataset" / "corpus.txt").read_text()
        assert corpus.startswith("#0\nlabel:add\n")
