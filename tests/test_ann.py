"""ANN retrieval (code2vec_tpu.ann): IVF-PQ index, LUT kernel, container.

The load-bearing contracts pinned here:

- k-means is seeded-DETERMINISTIC (same seed => bitwise-identical
  centroids) and topology-independent (single-device vs 8-device mesh
  assignment step => bitwise-identical fit — every float accumulation
  folds on the host in fixed order, kmeans.py);
- PQ round-trips within bounds, and all-zero residual rows round-trip
  EXACTLY (the shared ops/quant per-row-absmax scale contract);
- the Pallas LUT-scoring kernel matches the XLA take-based reference
  bitwise-compatibly (allclose incl. the -inf pad positions), across
  chunk sizes and DMA depths;
- the on-disk container round-trips every array bitwise plus labels and
  serving defaults;
- recall@10 >= 0.95 at a pinned n_probe on a synthetic clustered corpus,
  with a bounded executable table on the query path (the PR-9 compile
  discipline, asserted through the `_cache_size` probe);
- the serving `neighbors` op answers identically-SHAPED responses from
  the ann backend, and `health` reports the backend provenance.
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from code2vec_tpu.ann import pq
from code2vec_tpu.ann.index import (
    AnnSearcher,
    build_index,
    load_index,
    normalize_rows,
    save_index,
)
from code2vec_tpu.ann.kmeans import assign_cells, kmeans_fit
from code2vec_tpu.ann.lut_kernel import lut_score_cells

pytestmark = pytest.mark.ann

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def clustered_rows(n=3000, dim=16, k0=48, noise=0.15, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k0, dim)).astype(np.float32)
    member = rng.integers(0, k0, n)
    return (
        centers[member] + noise * rng.normal(size=(n, dim))
    ).astype(np.float32)


# ---------------------------------------------------------------------------
# k-means: determinism + mesh parity
# ---------------------------------------------------------------------------


def test_kmeans_same_seed_bitwise_identical():
    x = clustered_rows(n=1500, dim=8, k0=16)
    a = kmeans_fit(x, 16, seed=7, iters=10, batch_size=512)
    b = kmeans_fit(x, 16, seed=7, iters=10, batch_size=512)
    assert np.array_equal(a, b)
    # a different seed must actually change the fit (the rng is live)
    c = kmeans_fit(x, 16, seed=8, iters=10, batch_size=512)
    assert not np.array_equal(a, c)


def test_kmeans_mesh_parity_bitwise():
    """Single-device vs 8-device data-sharded fit: the assignment step is
    row-local and the centroid fold is host-side fixed-order float64, so
    the mesh changes NOTHING — bitwise, not approximately."""
    from code2vec_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    x = clustered_rows(n=1600, dim=8, k0=12)
    # batch_size 300 is NOT divisible by the 8-way data axis: the mesh may
    # round the COMPILED batch shape up (padding inside the assigner), but
    # the rng must still draw exactly 300 rows per iteration either way
    single = kmeans_fit(x, 12, seed=3, iters=8, batch_size=300)
    mesh = make_mesh(data=8, model=1, ctx=1)
    meshed = kmeans_fit(x, 12, seed=3, iters=8, batch_size=300, mesh=mesh)
    assert np.array_equal(single, meshed)
    assert np.array_equal(
        assign_cells(x, single), assign_cells(x, single, mesh=mesh)
    )


# ---------------------------------------------------------------------------
# PQ round trip
# ---------------------------------------------------------------------------


def test_pq_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    residuals = rng.normal(size=(2000, 8)).astype(np.float32) * 0.2
    codebooks, scales = pq.train_codebooks(residuals, 4, seed=0, iters=8)
    codes = pq.encode(residuals, codebooks, scales)
    decoded = pq.decode(codes, codebooks, scales)
    assert codes.dtype == np.uint8 and codes.shape == (2000, 4)
    norms = np.linalg.norm(residuals, axis=1)
    errs = np.linalg.norm(decoded - residuals, axis=1)
    # 256-entry codebooks over 2-dim subspaces: reconstruction must beat
    # the trivial zero quantizer by a wide margin
    assert float((errs / np.maximum(norms, 1e-12)).mean()) < 0.3
    # absmax scale bound: no decoded coordinate exceeds the row's scale
    assert np.all(np.abs(decoded) <= scales[:, None] + 1e-6)


def test_pq_zero_rows_roundtrip_exact():
    rng = np.random.default_rng(1)
    residuals = rng.normal(size=(300, 8)).astype(np.float32)
    residuals[::7] = 0.0  # scale-0 rows interleaved with real ones
    codebooks, scales = pq.train_codebooks(residuals, 4, seed=0, iters=5)
    codes = pq.encode(residuals, codebooks, scales)
    decoded = pq.decode(codes, codebooks, scales)
    assert np.all(scales[::7] == 0.0)
    assert np.all(decoded[::7] == 0.0)


# ---------------------------------------------------------------------------
# LUT kernel: Pallas vs XLA parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk_c,dma_depth", [(128, 1), (128, 2), (256, 2)])
def test_lut_kernel_parity(chunk_c, dma_depth):
    rng = np.random.default_rng(0)
    q, m, n_list, cap, n_probe = 3, 4, 10, 256, 5
    lut = rng.normal(size=(q, m, 256)).astype(np.float32)
    probed = rng.integers(0, n_list, (q, n_probe)).astype(np.int32)
    codes = rng.integers(0, 256, (n_list, cap, m)).astype(np.uint8)
    scales = rng.random((n_list, cap)).astype(np.float32)
    bias = np.zeros((n_list, cap), np.float32)
    bias[:, 200:] = -np.inf  # pad slots
    ref = np.asarray(
        lut_score_cells(lut, probed, codes, scales, bias, impl="xla")
    )
    got = np.asarray(
        lut_score_cells(
            lut, probed, codes, scales, bias, impl="pallas",
            chunk_c=chunk_c, dma_depth=dma_depth, interpret=True,
        )
    )
    assert np.array_equal(np.isneginf(ref), np.isneginf(got))
    finite = np.isfinite(ref)
    assert np.allclose(ref[finite], got[finite], atol=1e-5)


def test_lut_kernel_rejects_unknown_impl():
    z = np.zeros((1, 2, 256), np.float32)
    with pytest.raises(ValueError, match="impl"):
        lut_score_cells(
            z, np.zeros((1, 1), np.int32), np.zeros((1, 128, 2), np.uint8),
            np.zeros((1, 128), np.float32), np.zeros((1, 128), np.float32),
            impl="cuda",
        )


# ---------------------------------------------------------------------------
# index: build / container round trip / recall
# ---------------------------------------------------------------------------


def test_container_save_load_roundtrip(tmp_path):
    rows = clustered_rows(n=600, dim=16, k0=12)
    index, unit = build_index(
        rows, n_list=8, m=4, seed=0, kmeans_iters=5, pq_iters=4
    )
    labels = [f"m{i}" for i in range(600)]
    path = tmp_path / "ann.index"
    save_index(
        str(path), index, unit, labels,
        defaults={"n_probe": 4, "shortlist": 64},
    )
    loaded, rows2, labels2 = load_index(str(path))
    for field in ("centroids", "codebooks", "codes", "scales", "ids",
                  "cell_counts"):
        assert np.array_equal(
            getattr(index, field), np.asarray(getattr(loaded, field))
        ), field
    assert np.array_equal(unit, np.asarray(rows2))
    assert labels2 == labels
    assert loaded.meta["defaults"] == {"n_probe": 4, "shortlist": 64}
    for key in ("n", "dim", "n_list", "m", "capacity"):
        assert loaded.meta[key] == index.meta[key]


def test_container_rejects_foreign_file(tmp_path):
    from code2vec_tpu.formats.ann_io import is_ann_index, read_ann_container

    path = tmp_path / "not_an_index"
    path.write_bytes(b"hello world, definitely not an index")
    assert not is_ann_index(str(path))
    with pytest.raises(ValueError, match="not an ANN index"):
        read_ann_container(str(path))


def test_every_row_lands_in_exactly_one_cell():
    rows = clustered_rows(n=500, dim=8, k0=10)
    index, _ = build_index(
        rows, n_list=6, m=4, seed=0, kmeans_iters=5, pq_iters=4
    )
    real = index.ids[index.ids >= 0]
    assert sorted(real.tolist()) == list(range(500))
    assert int(index.cell_counts.sum()) == 500


def test_recall_at_pinned_n_probe():
    """The acceptance contract in miniature: clustered corpus, pinned
    n_probe, recall@10 >= 0.95 vs the exact ranking."""
    rows = clustered_rows(n=4000, dim=16, k0=64)
    index, unit = build_index(
        rows, n_list=32, m=4, seed=0, kmeans_iters=10, pq_iters=8
    )
    searcher = AnnSearcher(index, n_probe=8, shortlist=100)
    rng = np.random.default_rng(5)
    queries = rows[rng.integers(0, 4000, 25)] + 0.05 * rng.normal(
        size=(25, 16)
    ).astype(np.float32)
    qn = normalize_rows(queries)
    truth = np.argsort(-(qn @ unit.T), axis=1)[:, :10]
    _, ids = searcher.search(queries)
    recall = 0.0
    for i in range(25):
        valid = ids[i][ids[i] >= 0]
        sims = unit[valid] @ qn[i]
        top10 = valid[np.argsort(-sims)][:10]
        recall += len(set(top10.tolist()) & set(truth[i].tolist())) / 10
    assert recall / 25 >= 0.95


def test_searcher_executable_table_bounded():
    """Query batches bucket to powers of two; repeated shapes never
    compile again (the RecompileDetector-visible contract)."""
    from code2vec_tpu.obs.runtime import RecompileDetector, RuntimeHealth

    rows = clustered_rows(n=800, dim=8, k0=10)
    index, _ = build_index(
        rows, n_list=8, m=4, seed=0, kmeans_iters=5, pq_iters=4
    )
    searcher = AnnSearcher(index, n_probe=4, shortlist=32)
    rng = np.random.default_rng(0)
    for q in (1, 3, 5, 2, 8, 1, 7):
        searcher.search(rng.normal(size=(q, 8)).astype(np.float32))
    assert searcher._cache_size() <= 4  # buckets {1, 2, 4, 8}
    det = RecompileDetector(health=RuntimeHealth())
    det.track("ann_search", searcher)
    det.check()
    for q in (1, 3, 5, 2, 8, 1, 7):
        searcher.search(rng.normal(size=(q, 8)).astype(np.float32))
    assert det.check() == 0


def test_probed_fraction_ignores_empty_cells():
    """The accounting must rank cells exactly like the compiled query
    path: an empty cell's centroid (its k-means++ seed — a real data
    point) can top the raw similarity, but the query path never probes it
    (cell_bias = -inf), so probed_fraction must skip it too."""
    from code2vec_tpu.ann.index import IvfPqIndex

    dim, cap = 8, 128
    centroids = np.zeros((2, dim), np.float32)
    centroids[0, 0] = 1.0  # empty cell, dead-on the query direction
    centroids[1, 1] = 1.0
    codes = np.zeros((2, cap, 2), np.uint8)
    scales = np.zeros((2, cap), np.float32)
    ids = np.full((2, cap), -1, np.int32)
    ids[1, :3] = np.arange(3)
    scales[1, :3] = 1.0
    index = IvfPqIndex(
        centroids=centroids,
        codebooks=np.zeros((2, 256, 4), np.float32),
        codes=codes, scales=scales, ids=ids,
        cell_counts=np.array([0, 3], np.int32),
        meta={"version": 1, "n": 3, "dim": dim, "n_list": 2, "m": 2,
              "dsub": 4, "capacity": cap, "seed": 0},
    )
    searcher = AnnSearcher(index, n_probe=1, shortlist=3)
    q = np.zeros((1, dim), np.float32)
    q[0, 0] = 1.0
    # probes cell 1 (all 3 real rows), never the empty cell 0
    assert searcher.probed_fraction(q) == 1.0
    _, got_ids = searcher.search(q)
    assert sorted(got_ids[0].tolist()) == [0, 1, 2]


def test_ann_topk_beyond_shortlist_rejected(tmp_path):
    """k beyond the shortlist cannot be served honestly (the exact
    backend would return k entries) — loud bad_request, not silent
    truncation."""
    _, ann = _build_retrieval(tmp_path)
    with pytest.raises(ValueError, match="shortlist"):
        ann.top_k(np.ones(16, np.float32), 100)  # shortlist is 64
    resp = _ann_server(ann).handle(
        {"op": "neighbors", "vector": [1.0] * 16, "top_k": 100}
    )
    assert resp["error_kind"] == "bad_request"
    assert "shortlist" in resp["error"]


def test_searcher_mesh_parity():
    """model=4-sharded cell arrays return the same shortlist as a single
    device (n_list chosen indivisible to exercise the cell padding)."""
    from code2vec_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 on CPU)")
    rows = clustered_rows(n=1200, dim=16, k0=24)
    index, _ = build_index(
        rows, n_list=10, m=4, seed=0, kmeans_iters=6, pq_iters=4
    )
    single = AnnSearcher(index, n_probe=4, shortlist=48)
    mesh = make_mesh(data=1, model=4, ctx=1, devices=jax.devices()[:4])
    meshed = AnnSearcher(index, n_probe=4, shortlist=48, mesh=mesh)
    q = np.random.default_rng(1).normal(size=(5, 16)).astype(np.float32)
    s1, i1 = single.search(q)
    s2, i2 = meshed.search(q)
    assert np.array_equal(i1, i2)
    assert np.allclose(s1, s2, atol=1e-5, equal_nan=True)


# ---------------------------------------------------------------------------
# autotune: the LUT variant axis
# ---------------------------------------------------------------------------


def test_autotune_lut_cache_roundtrip(tmp_path):
    from code2vec_tpu.ops.autotune import (
        LutShapeKey,
        ScheduleCache,
        autotune_lut,
        counters_snapshot,
        device_kind,
        lookup_lut_schedule,
    )

    cache_path = str(tmp_path / "schedules.json")
    cache = ScheduleCache(cache_path)
    key = LutShapeKey(
        device_kind=device_kind(), m=4, n_list=8, capacity=128, shortlist=32
    )
    before = counters_snapshot()
    autotune_lut([key], cache=cache, dry=True)
    # a second cache object (fresh load) must serve the stored schedule
    reloaded = ScheduleCache(cache_path)
    found = lookup_lut_schedule(4, 8, 128, 32, cache=reloaded)
    assert found.source == "cache"
    after = counters_snapshot()
    delta = {k: after[k] - before[k] for k in after}
    assert delta["autotune_cache_miss"] == 1  # the dry stamp
    assert delta["autotune_cache_hit"] == 1  # the lookup
    assert delta["autotune_timing_run"] == 0  # dry: zero search
    # forward-kernel entries and LUT entries share the file disjointly
    assert all(k.startswith("lut|") for k in reloaded.entries)


def test_autotune_lut_timed_search_picks_a_variant(tmp_path):
    from code2vec_tpu.ops.autotune import (
        LutShapeKey,
        ScheduleCache,
        autotune_lut,
        device_kind,
    )

    cache = ScheduleCache(str(tmp_path / "schedules.json"))
    key = LutShapeKey(
        device_kind=device_kind(), m=2, n_list=4, capacity=128, shortlist=16
    )
    out = autotune_lut([key], cache=cache, dry=False, iters=1, repeats=1,
                       n_probe=2, q_batch=2)
    sched = out[key.cache_key()]
    assert sched.source == "autotune"
    assert sched.impl in ("xla", "pallas")
    entry = cache.entries[key.cache_key()]
    assert entry["timings_ms"]  # per-variant provenance persisted


# ---------------------------------------------------------------------------
# serving: the ann backend behind the neighbors op
# ---------------------------------------------------------------------------


class _StubBatcher:
    def close(self):
        pass


def _ann_server(retrieval):
    from code2vec_tpu.serve.protocol import CodeServer

    return CodeServer(
        predictor=None, engine=None, batcher=_StubBatcher(),
        retrieval=retrieval,
    )


def _build_retrieval(tmp_path, n=600, dim=16):
    from code2vec_tpu.serve.retrieval import AnnRetrievalIndex

    rows = clustered_rows(n=n, dim=dim, k0=12)
    index, unit = build_index(
        rows, n_list=8, m=4, seed=0, kmeans_iters=5, pq_iters=4
    )
    labels = [f"m{i}" for i in range(n)]
    path = str(tmp_path / "ann.index")
    save_index(path, index, unit, labels,
               defaults={"n_probe": 6, "shortlist": 64})
    return rows, AnnRetrievalIndex.from_container(path)


def test_ann_neighbors_schema_matches_exact(tmp_path):
    """Same request, both backends: identical response SHAPE, and on an
    easy query (a corpus point) identical top-1 with exact similarity."""
    from code2vec_tpu.serve.retrieval import RetrievalIndex

    rows, ann = _build_retrieval(tmp_path)
    exact = RetrievalIndex(ann.labels, rows)
    server_exact = _ann_server(exact)
    server_ann = _ann_server(ann)
    req = {"op": "neighbors", "vector": rows[17].tolist(), "top_k": 5}
    a = server_exact.handle(req)
    b = server_ann.handle(req)
    assert a["ok"] and b["ok"]
    assert [sorted(n) for n in a["neighbors"]] == [
        sorted(n) for n in b["neighbors"]
    ]
    assert b["neighbors"][0]["name"] == "m17"
    assert b["neighbors"][0]["similarity"] == pytest.approx(1.0, abs=1e-5)
    # re-ranked similarities are EXACT cosines, not ADC approximations
    assert a["neighbors"][0]["similarity"] == pytest.approx(
        b["neighbors"][0]["similarity"], abs=1e-5
    )


def test_ann_backend_describe_and_health_fields(tmp_path):
    _, ann = _build_retrieval(tmp_path)
    desc = ann.describe()
    assert desc["backend"] == "ann"
    assert desc["size"] == 600
    assert desc["n_probe"] == 6  # the container's baked-in default
    assert desc["shortlist"] == 64
    assert desc["n_list"] == 8
    assert desc["schedule"]["impl"] in ("xla", "pallas")
    assert "index_path" in desc


def test_load_retrieval_index_dispatch(tmp_path):
    from code2vec_tpu.serve.retrieval import load_retrieval_index

    with pytest.raises(ValueError, match="ann_index_path"):
        load_retrieval_index("ann")
    with pytest.raises(ValueError, match="code_vec_path"):
        load_retrieval_index("exact")
    with pytest.raises(ValueError, match="retrieval_backend"):
        load_retrieval_index("fuzzy")
    _, ann = _build_retrieval(tmp_path)
    loaded = load_retrieval_index(
        "ann", ann_index_path=str(tmp_path / "ann.index"), n_probe=3
    )
    assert loaded.searcher.n_probe == 3  # CLI override beats the default


def test_ann_build_cli_and_stdio_neighbors(tmp_path):
    """The CI smoke satellite end to end: export a tiny code.vec, build an
    index with the REAL tools/ann_build.py subprocess, then serve one
    neighbors query through the stdio transport."""
    from code2vec_tpu.formats.vectors_io import (
        append_code_vectors,
        write_code_vectors_header,
    )
    from code2vec_tpu.serve.protocol import serve_stdio
    from code2vec_tpu.serve.retrieval import AnnRetrievalIndex

    rng = np.random.default_rng(0)
    n, dim = 400, 16
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    names = [f"meth{i}" for i in range(n)]
    code_vec = tmp_path / "code.vec"
    write_code_vectors_header(str(code_vec), n, dim)
    append_code_vectors(str(code_vec), names, vecs)

    out_path = tmp_path / "ann.index"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "ann_build.py"),
            "--code_vec", str(code_vec), "--out", str(out_path),
            "--n_list", "8", "--m", "4", "--kmeans_iters", "4",
            "--pq_iters", "3",
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["n"] == n and summary["n_list"] == 8

    ann = AnnRetrievalIndex.from_container(str(out_path))
    server = _ann_server(ann)
    requests = [
        json.dumps(
            {"id": 1, "op": "neighbors", "vector": vecs[3].tolist(),
             "top_k": 3}
        ),
        json.dumps({"id": 2, "op": "shutdown"}),
    ]
    out_stream = io.StringIO()
    serve_stdio(server, iter(requests), out_stream)
    lines = [json.loads(l) for l in out_stream.getvalue().splitlines()]
    assert lines[0]["id"] == 1
    assert lines[0]["neighbors"][0]["name"] == "meth3"
    assert lines[1]["shutting_down"] is True
